"""Always-on structured wide-event log — the black-box substrate.

A metrics counter says *how often* something happened; a trace span says
*how long* it took; neither says **what happened, in order, with
context** when a replica dies at 3am.  This module is that third leg:
a bounded, thread-safe ring of structured events, ON from import (the
write path is one lock + a tuple append, nanoseconds against the
warnings and guard trips it records), so the flight recorder
(:mod:`~lightgbmv1_tpu.obs.dump`) always has a tail to dump and the
aggregator (:mod:`~lightgbmv1_tpu.obs.agg`) can interleave N processes'
last moments on one wall-clock timeline.

Every event is a flat dict:

``seq``            process-wide monotone sequence number
``severity``       ``debug | info | warning | error | fatal``
``kind``           dotted event name (``guard.finite``, ``serve.shed``,
                   ``fault.injected``, ``log.warning``, ...)
``t_mono_ns``      ``time.perf_counter_ns()`` — ordering within the run
``t_wall``         ``time.time()`` — cross-process alignment
``host, pid, role, run_id``   process identity (:func:`set_identity`)
``trace_id``       the current thread's bound trace id, when any
``message``        human line
``fields``         kind-specific extras (JSON-able)

Publishers wired through the codebase (grep ``events.publish``):
``utils/log.py`` warnings/fatals, every ``faults.fire`` injection,
``finite_guard`` boundary trips, the serving failure domains (shed,
watchdog stall, dispatcher restart, breaker trip, publish reject),
``BlockCacheError``, and checkpoint resume decisions.  Each publish
also counts into the default registry
(``obs_events_total{severity=...}``), so a fleet scrape sees error
rates without shipping the ring.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional

DEFAULT_RING_EVENTS = 4096

SEVERITIES = ("debug", "info", "warning", "error", "fatal")

_lock = threading.Lock()
_ring: List[dict] = []
_ring_cap = DEFAULT_RING_EVENTS
_ring_pos = 0
_dropped = 0
_seq = 0

_HOST = socket.gethostname()
_identity = {
    "host": _HOST,
    "pid": os.getpid(),
    "role": os.environ.get("LGBMV1_OBS_ROLE", "proc"),
    "run_id": os.environ.get("LGBMV1_RUN_ID", "") or os.urandom(4).hex(),
}

_counter = None          # lazily bound obs_events_total{severity}


def set_identity(role: Optional[str] = None,
                 run_id: Optional[str] = None) -> None:
    """Bind this process's ``role`` (trainer / server / loadgen / worker0
    ...) and ``run_id`` (shared across the processes of one logical run
    so the aggregator can group them).  Events published BEFORE the call
    keep the identity they were stamped with."""
    with _lock:
        if role is not None:
            _identity["role"] = str(role)
        if run_id is not None:
            _identity["run_id"] = str(run_id)
        _identity["pid"] = os.getpid()   # re-stamp after fork


def identity() -> Dict[str, object]:
    with _lock:
        return dict(_identity)


def configure(capacity: int = DEFAULT_RING_EVENTS) -> None:
    """Resize the ring (drops buffered events; tests and long-lived
    servers that want a deeper black box)."""
    global _ring, _ring_cap, _ring_pos, _dropped
    with _lock:
        _ring = []
        _ring_cap = max(int(capacity), 16)
        _ring_pos = 0
        _dropped = 0


def reset() -> None:
    """Drop all buffered events (test isolation; identity/seq survive)."""
    global _ring, _ring_pos, _dropped
    with _lock:
        _ring = []
        _ring_pos = 0
        _dropped = 0


def _count(severity: str) -> None:
    global _counter
    try:
        if _counter is None:
            from .metrics import default_registry

            _counter = default_registry().counter(
                "obs_events_total", "Structured events published",
                label_names=("severity",))
        _counter.labels(severity=severity).inc()
    except Exception:   # noqa: BLE001 — the log must never throw
        pass


def publish(kind: str, message: str = "", severity: str = "info",
            **fields) -> dict:
    """Record one structured event; returns the event dict (the ring
    keeps a reference — do not mutate it).  Never raises: the event log
    is the thing that must still work when everything else is broken."""
    global _ring_pos, _dropped, _seq
    if severity not in SEVERITIES:
        severity = "info"
    trace_id = None
    try:
        from . import trace

        trace_id = trace.current_trace_id()
    except Exception:   # noqa: BLE001
        pass
    ev = {
        "seq": 0,
        "severity": severity,
        "kind": str(kind),
        "t_mono_ns": time.perf_counter_ns(),
        "t_wall": time.time(),
        "message": str(message),
    }
    with _lock:
        _seq += 1
        ev["seq"] = _seq
        ev.update(_identity)
        if trace_id:
            ev["trace_id"] = trace_id
        if fields:
            ev["fields"] = fields
        if len(_ring) < _ring_cap:
            _ring.append(ev)
        else:
            _ring[_ring_pos] = ev
            _ring_pos = (_ring_pos + 1) % _ring_cap
            _dropped += 1
    _count(severity)
    return ev


def seq() -> int:
    """Current sequence number (test/driver bookmarks: events published
    after a bookmark are exactly those with ``seq`` greater than it)."""
    with _lock:
        return _seq


def dropped() -> int:
    with _lock:
        return _dropped


def tail(n: Optional[int] = None, since_seq: int = 0,
         kind_prefix: str = "") -> List[dict]:
    """Buffered events oldest -> newest, optionally only those after
    ``since_seq`` and/or whose kind starts with ``kind_prefix``; ``n``
    keeps the newest n after filtering."""
    with _lock:
        if len(_ring) < _ring_cap or _ring_pos == 0:
            evs = list(_ring)
        else:
            evs = _ring[_ring_pos:] + _ring[:_ring_pos]
    if since_seq:
        evs = [e for e in evs if e["seq"] > since_seq]
    if kind_prefix:
        evs = [e for e in evs if e["kind"].startswith(kind_prefix)]
    if n is not None:
        evs = evs[-int(n):]
    return evs


def to_jsonl(events: List[dict]) -> str:
    """One event per line — the bundle/artifact wire format (merge-able
    by sort on ``t_wall`` across processes)."""
    return "\n".join(json.dumps(e, sort_keys=True, default=str)
                     for e in events) + ("\n" if events else "")


def from_jsonl(text: str) -> List[dict]:
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue   # a torn tail line from a crashed writer is expected
    return out
