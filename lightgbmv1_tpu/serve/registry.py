"""Versioned model registry with atomic hot-swap.

The expensive parts of bringing a new ensemble online — materializing
host trees, building the serving binner, stacking the SoA node tables,
and compiling the bucketed walk executables — all happen in
``publish()`` OFF the serving path.  Only after the new
:class:`~lightgbmv1_tpu.models.predict.BatchPredictor` is fully warmed
does the registry swap a single reference under a lock; the dispatcher
reads that reference once per batch, so in-flight batches finish on the
version they started with and every later batch sees the new one.
``rollback()`` is the same single-reference swap back to the previous
entry (its predictor and compiled cache are retained, so rollback is
instant, not a re-publish).

Every response carries the version tag of the predictor that computed
it, which is what makes "bit-identical to ``Booster.predict`` of the
version the response names" a testable contract across a mid-traffic
swap (tests/test_serve.py).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..models.predict import BatchPredictor
from ..utils import faults
from ..utils.log import log_info, log_warning


class PublishValidationError(RuntimeError):
    """The candidate version failed pre-swap validation (structurally
    invalid trees, non-finite outputs, or a golden-probe mismatch
    between the device predictor and the host-tree oracle).  The active
    version is untouched: a corrupt model can never reach traffic."""


@dataclass
class ModelVersion:
    """One published ensemble: the serving predictor plus the optional
    truncated-tree degrade predictor (overload answers; fewer trees =
    strictly less walk work per row)."""

    tag: str
    predictor: BatchPredictor
    degraded: Optional[BatchPredictor] = None
    num_features: int = 0
    num_class: int = 1
    n_trees: int = 0
    meta: Dict[str, Any] = field(default_factory=dict)


def _booster_parts(model):
    """Accept a Booster or an explicit (trees, K, num_features) triple."""
    if isinstance(model, tuple):
        trees, k, f = model
        return list(trees), int(k), int(f)
    return (model._all_trees(), model.num_model_per_iteration(),
            model.num_feature())


class ModelRegistry:
    """Publish / current / rollback over :class:`ModelVersion` entries."""

    def __init__(self, *, warm_buckets: Optional[List[int]] = None,
                 history: int = 4, metrics=None,
                 predictor_kwargs: Optional[Dict[str, Any]] = None,
                 name: str = ""):
        self._lock = threading.Lock()
        self._active: Optional[ModelVersion] = None
        self._history: List[ModelVersion] = []
        self._seq = 0
        self._warm_buckets = warm_buckets
        self._keep = max(int(history), 1)
        self._metrics = metrics
        self._predictor_kwargs = dict(predictor_kwargs or {})
        # replica identity (fleet.py): prefixes the publish_warm fault
        # site so a chaos plan can fail ONE replica's warm phase
        self.name = str(name)

    # -- build + warm (off the serving path) -----------------------------
    def _build(self, trees, K, F, degrade_trees: int) -> ModelVersion:
        self._seq += 1
        tag = f"v{self._seq}"
        bp = BatchPredictor(trees, K, F, **self._predictor_kwargs)
        degraded = None
        if degrade_trees and 0 < degrade_trees < len(trees):
            # truncate on an iteration boundary so multiclass ensembles
            # keep whole per-class tree groups
            n = max(degrade_trees - degrade_trees % max(K, 1), K)
            degraded = BatchPredictor(trees[:n], K, F,
                                      **self._predictor_kwargs)
        return ModelVersion(tag=tag, predictor=bp, degraded=degraded,
                            num_features=F, num_class=K, n_trees=len(trees))

    def _warm(self, mv: ModelVersion, max_batch_rows: int) -> int:
        """Compile the bucketed walk for every bucket a live batch can
        land in, BEFORE the version becomes visible — the first real
        request must never pay a trace.  Every warm output is
        finite-checked: a version whose executables produce NaN/Inf is
        rejected here, pre-swap.

        Device truth (ISSUE 12): the warm phase IS this publish's
        compile bill — the obs/xla.py per-label counters price it, and
        the version carries ``warm_compile_ms``/``warm_compiles`` in its
        meta (plus a ``serve.publish_warm`` event), so a publish that
        suddenly compiles more than its predecessor is a visible number,
        not a mystery pause before the swap."""
        from ..obs import xla as obs_xla

        ms0 = obs_xla.compile_ms_total()
        counts0 = obs_xla.compile_counts()
        n_compiled = 0
        for bp in filter(None, (mv.predictor, mv.degraded)):
            buckets = self._warm_buckets
            if buckets is None:
                buckets, b = [], bp.bucket_for(1)
                top = bp.bucket_for(max(int(max_batch_rows), 1))
                while b <= top:
                    buckets.append(b)
                    b *= 2
            for bucket in buckets:
                # chaos seam: a publish() that dies mid-warm must leave
                # the active version serving (utils/faults.py); the
                # replica name prefixes the site so a fleet chaos plan
                # can target one replica's warm phase
                faults.fire("publish_warm",
                            site=(f"{self.name}:{mv.tag}" if self.name
                                  else mv.tag))
                x = np.zeros((min(bucket, max_batch_rows), mv.num_features),
                             np.float64)
                out = np.asarray(bp.predict_raw(x))
                if not np.isfinite(out).all():
                    raise PublishValidationError(
                        f"{mv.tag}: non-finite scores from the "
                        f"{bucket}-row warm batch")
                n_compiled += 1
        counts1 = obs_xla.compile_counts()
        warm_ms = round(obs_xla.compile_ms_total() - ms0, 1)
        warm_compiles = sum(
            counts1.get(k, 0) - counts0.get(k, 0)
            for k in counts1 if k.startswith("predict."))
        mv.meta["warm_compile_ms"] = warm_ms
        mv.meta["warm_compiles"] = warm_compiles
        try:
            from ..obs import events

            events.publish(
                "serve.publish_warm",
                f"{mv.tag}: warmed {n_compiled} batches, "
                f"{warm_compiles} compiles in {warm_ms} ms",
                tag=mv.tag, replica=self.name or "",
                warm_compile_ms=warm_ms, warm_compiles=warm_compiles)
        except Exception:   # noqa: BLE001 — telemetry must never block
            pass            # a publish
        return n_compiled

    # -- pre-swap validation ---------------------------------------------
    @staticmethod
    def _validate_trees(trees) -> None:
        """Structural + finite validation of every candidate tree (rides
        PR 4's validate_host_tree: acyclicity, child-index bounds)."""
        from ..models.tree import validate_host_tree

        for i, t in enumerate(trees):
            validate_host_tree(t, i)
            nl = t.num_leaves
            if not np.isfinite(np.asarray(t.leaf_value[:nl],
                                          np.float64)).all():
                raise PublishValidationError(
                    f"tree {i}: non-finite leaf values")
            if nl > 1 and not np.isfinite(
                    np.asarray(t.threshold[: nl - 1], np.float64)).all():
                raise PublishValidationError(
                    f"tree {i}: non-finite split thresholds")

    @staticmethod
    def _probe_check(mv: ModelVersion, trees, K: int, F: int,
                     probe_rows: int) -> None:
        """Golden probe: the candidate's device predictor must reproduce
        the host-tree oracle BIT-EXACTLY (f64 reconstruction path, the
        PR 4 parity contract) on a seeded batch of random rows.  Catches
        what structural checks cannot: a mis-stacked serving table, a
        broken binner, a miscompiled walk.  Probes BOTH lanes when they
        differ: the f64 reconstruction path must be bit-exact, and the
        fast f32 serving lane (the fused megakernel when
        ``predictor_kwargs={"method": "fused"}``) must agree to f32
        round-off — a fused walk that silently fell back or mis-tiled
        fails here, before the swap."""
        rng = np.random.RandomState(0xC0FFEE ^ (len(trees) * 2654435761
                                                & 0x7FFFFFFF))
        Xp = rng.randn(int(probe_rows), F)
        want = np.zeros((int(probe_rows), K), np.float64)
        for i, t in enumerate(trees):
            want[:, i % K] += t.predict(Xp)
        got = np.asarray(mv.predictor.predict_raw(Xp, f64_exact=True))
        if got.shape != want.shape or not np.array_equal(got, want):
            raise PublishValidationError(
                f"{mv.tag}: golden-probe mismatch — device predictor "
                "diverges from the host-tree oracle on "
                f"{int(probe_rows)} probe rows")
        got32 = np.asarray(mv.predictor.predict_raw(Xp), np.float64)
        if got32.shape != want.shape or not np.allclose(
                got32, want, rtol=1e-4, atol=1e-5):
            raise PublishValidationError(
                f"{mv.tag}: golden-probe mismatch — fast f32 serving "
                "lane diverges from the host-tree oracle beyond f32 "
                f"round-off on {int(probe_rows)} probe rows")

    # -- public API ------------------------------------------------------
    def prepare(self, model, *, degrade_trees: int = 0,
                max_batch_rows: int = 1024,
                meta: Optional[Dict[str, Any]] = None,
                probe_rows: int = 64) -> ModelVersion:
        """Phase 1 of a publish: build, warm and VALIDATE a candidate
        version WITHOUT making it visible — the expensive, failable
        half.  Returns the warmed :class:`ModelVersion` for
        :meth:`commit`; raises (``PublishValidationError`` or the warm
        failure) with the active version untouched.  ``fleet.py`` runs
        this on EVERY replica before any replica swaps (two-phase
        publish): a single replica's validation failure aborts the
        whole fleet's publish with zero replicas moved."""
        trees, K, F = _booster_parts(model)
        if not trees:
            raise ValueError("publish() needs a trained model "
                             "(zero trees)")
        try:
            self._validate_trees(trees)
            mv = self._build(trees, K, F, degrade_trees)
            if meta:
                mv.meta.update(meta)
            # model-quality meta (ISSUE 14): every version carries its
            # gain/split feature importance so commit() can diff the
            # importance shift between versions, and the training
            # reference (when provided) is digest-stamped like every
            # other artifact
            imp_gain = np.zeros(F, np.float64)
            imp_split = np.zeros(F, np.int64)
            for t in trees:
                for i in range(t.num_leaves - 1):
                    f = int(t.split_feature[i])
                    if f < F:
                        imp_gain[f] += float(t.split_gain[i])
                        imp_split[f] += 1
            mv.meta["importance_gain"] = [round(float(v), 6)
                                          for v in imp_gain]
            mv.meta["importance_split"] = [int(v) for v in imp_split]
            ref = mv.meta.get("model_reference")
            if ref is not None:
                mv.meta["model_reference_digest"] = ref.digest
            mv.meta["n_warm"] = self._warm(mv, max_batch_rows)
            if probe_rows > 0:
                self._probe_check(mv, trees, K, F, probe_rows)
        except Exception as e:
            if self._metrics is not None:
                self._metrics.on_publish_reject()
            from ..obs import events as obs_events

            obs_events.publish(
                "serve.publish_reject",
                f"{type(e).__name__}: {e}", severity="error",
                n_trees=len(trees), replica=self.name)
            log_warning(f"serve: publish rejected pre-swap "
                        f"({type(e).__name__}: {e}); active version "
                        "keeps serving")
            raise
        return mv

    def commit(self, mv: ModelVersion) -> str:
        """Phase 2: atomically make a prepared version current (one
        reference swap under the lock — in-flight batches finish on the
        version they started with).  The incoming version's importance
        is diffed against the outgoing one (obs/model.importance_shift)
        so a publish that silently re-ranks what the model pays
        attention to is a visible number, not a mystery."""
        with self._lock:
            prev = self._active
            if self._active is not None:
                self._history.append(self._active)
                del self._history[:-self._keep]
            self._active = mv
        if prev is not None and prev.meta.get("importance_gain") \
                and mv.meta.get("importance_gain"):
            try:
                from ..obs import events as obs_events
                from ..obs.model import importance_shift

                shift = importance_shift(prev.meta["importance_gain"],
                                         mv.meta["importance_gain"])
                mv.meta["importance_shift"] = shift
                mv.meta["importance_shift_vs"] = prev.tag
                obs_events.publish(
                    "serve.importance_shift",
                    f"{prev.tag} -> {mv.tag}: importance L1 shift "
                    f"{shift['l1']}", tag=mv.tag, prev_tag=prev.tag,
                    l1=shift["l1"], top_mover=shift["top_mover"],
                    replica=self.name or "")
            except Exception:   # noqa: BLE001 — telemetry must never
                pass            # block a publish
        if self._metrics is not None:
            self._metrics.on_swap()
        log_info(f"serve: published {mv.tag} ({mv.n_trees} trees, "
                 f"{mv.meta.get('n_warm', 0)} warmed executables)")
        return mv.tag

    def publish(self, model, *, degrade_trees: int = 0,
                max_batch_rows: int = 1024,
                meta: Optional[Dict[str, Any]] = None,
                probe_rows: int = 64) -> str:
        """Build, warm and VALIDATE a new version, then atomically make
        it current.  Returns the version tag.  ``model`` is a Booster or
        a ``(trees, K, num_features)`` triple.

        Validation is the serving failure domain's front door: every
        candidate tree is structurally checked (validate_host_tree) and
        finite-checked, every warmed executable's output is
        finite-checked, and (``probe_rows`` > 0) the device predictor
        must reproduce the host-tree oracle bit-exactly on a seeded
        golden probe batch — all BEFORE the swap, so a corrupt model can
        never serve a single answer.  Failure raises
        :class:`PublishValidationError` and the active version keeps
        serving untouched.  (Equivalent to :meth:`prepare` +
        :meth:`commit`.)"""
        return self.commit(self.prepare(
            model, degrade_trees=degrade_trees,
            max_batch_rows=max_batch_rows, meta=meta,
            probe_rows=probe_rows))

    def rollback(self) -> str:
        """Swap back to the previous version (instant: its compiled cache
        was retained).  Returns the now-current tag."""
        with self._lock:
            if not self._history:
                raise RuntimeError("rollback(): no previous version")
            self._active = self._history.pop()
            tag = self._active.tag
        if self._metrics is not None:
            self._metrics.on_swap(rollback=True)
        log_info(f"serve: rolled back to {tag}")
        return tag

    def current(self) -> ModelVersion:
        """Atomic read of the active version; the dispatcher calls this
        once per batch so a swap never splits a batch across versions."""
        with self._lock:
            if self._active is None:
                raise RuntimeError("no model published yet")
            return self._active

    def current_tag(self) -> Optional[str]:
        with self._lock:
            return self._active.tag if self._active is not None else None

    def versions(self) -> List[str]:
        with self._lock:
            out = [m.tag for m in self._history]
            if self._active is not None:
                out.append(self._active.tag)
            return out
