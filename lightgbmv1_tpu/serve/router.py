"""Self-healing request router over a replica fleet.

Fronts N replica :class:`~lightgbmv1_tpu.serve.server.Server`s with the
three behaviors that turn "a replica died" into "nobody noticed":

* **health-check ejection / readmission** — a poller thread reads each
  replica's ``health()`` (the same payload ``/healthz`` serves, so the
  decision is externally observable) every ``health_period_ms``;
  ``eject_after`` consecutive bad checks eject a replica from the
  candidate set, ``readmit_after`` consecutive good checks readmit it.
  ``wedged`` (a watchdog-overdue in-flight batch) counts as unhealthy:
  a stuck dispatcher is dead to traffic even though its process polls
  200.
* **bounded retry onto another replica** — a retryable failure
  (ServerClosed, DispatcherStalled/Died, a transport drop, a transient
  ServeError) is retried on a DIFFERENT replica, up to ``retry_max``
  extra attempts and never past the request deadline.  Retry is safe by
  construction: predict is pure, so re-execution cannot double-apply
  anything (the idempotency argument the reference's Predictor gets for
  free and a mutating service would have to build).
* **hedging** — when an attempt has not answered within ``hedge_ms``,
  a second attempt launches on another replica and the FIRST completion
  wins; the loser's eventual result is discarded.  Router metrics and
  SLO record EXACTLY ONE outcome per request (the coordinator thread is
  the only writer), so a hedged race never double-counts — each
  replica's own metrics still record its honest per-replica work.

Deadline semantics: ``deadline_ms`` (or the per-call ``timeout_ms``)
bounds the WHOLE request including retries and hedges; exhaustion
raises :class:`RequestTimeout`, which the HTTP layer maps to 504 —
never a 500, because running out of time is the client's contract, not
a server bug.

Fault seams (utils/faults.py): ``rpc_drop`` (raise = the connection to
a replica dropped before dispatch) and ``rpc_delay`` (stall = a slow
link) fire per attempt with the replica name as site — the chaos
scenarios script replica-targeted network faults deterministically.

The router duck-types the Server surface ``ServeHTTP`` consumes
(``submit`` / ``metrics`` / ``metrics_snapshot`` / ``slo_snapshot`` /
``health`` / ``version``), so the stdlib HTTP front-end serves a fleet
unchanged.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from ..utils import faults
from ..utils.log import log_info, log_warning
from .metrics import ServeMetrics
from .server import (DEFAULT_TENANT, DispatcherDied, DispatcherStalled,
                     RequestTimeout, ServeError, ServeResult, Server,
                     ServerClosed, ServerOverloaded, UnknownTenant)
from .slo import SLOConfig, SLOTracker


@dataclass
class RouterConfig:
    """Routing policy knobs (mirrored by the ``router_*`` names in
    config.py for the CLI path; defaults match)."""

    health_period_ms: float = 25.0   # health poll period
    eject_after: int = 2             # consecutive bad checks -> eject
    readmit_after: int = 2           # consecutive good checks -> readmit
    retry_max: int = 2               # extra attempts after the first
    hedge_ms: float = 0.0            # hedge launch delay; 0 = off
    max_hedges: int = 1              # concurrent extra attempts
    deadline_ms: float = 0.0         # whole-request budget; 0 = off
    metrics_window: int = 8192
    slo: Optional[SLOConfig] = None

    def __post_init__(self):
        self.health_period_ms = max(float(self.health_period_ms), 1.0)
        self.eject_after = max(int(self.eject_after), 1)
        self.readmit_after = max(int(self.readmit_after), 1)
        self.retry_max = max(int(self.retry_max), 0)
        self.hedge_ms = max(float(self.hedge_ms), 0.0)
        self.max_hedges = max(int(self.max_hedges), 0)
        self.deadline_ms = max(float(self.deadline_ms), 0.0)
        if self.slo is None:
            self.slo = SLOConfig()


class _Replica:
    __slots__ = ("server", "healthy", "consec_bad", "consec_good",
                 "ejections", "readmissions")

    def __init__(self, server: Server):
        self.server = server
        self.healthy = True
        self.consec_bad = 0
        self.consec_good = 0
        self.ejections = 0
        self.readmissions = 0

    @property
    def name(self) -> str:
        return self.server.name or f"r@{id(self.server):x}"


# outcomes a DIFFERENT replica can plausibly serve — retried elsewhere.
# ServerOverloaded is retryable too (another replica's queue may have
# room) but is tracked separately so an all-replicas-shedding fleet
# surfaces as overload, not as a generic error.
_RETRYABLE = (ServerClosed, DispatcherStalled, DispatcherDied,
              faults.FaultInjected, ServeError, RuntimeError)


class Router:
    """Health-checked, retrying, hedging front over fleet replicas.

    ``replicas`` is a :class:`~lightgbmv1_tpu.serve.fleet.Fleet` or a
    list of Servers.  The router does not own the replicas — closing
    the fleet is the owner's job; ``close()`` only stops the health
    poller."""

    def __init__(self, replicas, config: Optional[RouterConfig] = None):
        servers = (replicas.replicas
                   if hasattr(replicas, "replicas") else list(replicas))
        if not servers:
            raise ValueError("Router needs at least one replica")
        self.config = config or RouterConfig()
        self._replicas = [_Replica(s) for s in servers]
        self._t_start = time.monotonic()
        self._rr = 0
        self._lock = threading.Lock()
        # placement map (serve/placement.py): tenant -> tuple of replica
        # names its traffic is pinned to; a tenant with no entry routes
        # over every replica (the pre-placement behavior)
        self._placement: Dict[str, tuple] = {}
        self.metrics = ServeMetrics(window=self.config.metrics_window)
        self.slo = SLOTracker(self.config.slo)
        reg = self.metrics.registry
        self._c_hedges = reg.counter(
            "router_hedges_total", "Hedge attempts launched")
        self._c_hedge_wins = reg.counter(
            "router_hedge_wins_total",
            "Requests answered by a hedge attempt, not the primary")
        self._c_ejections = reg.counter(
            "router_ejections_total", "Replica health-check ejections",
            label_names=("replica",))
        self._c_readmissions = reg.counter(
            "router_readmissions_total",
            "Replica health-check readmissions",
            label_names=("replica",))
        self._closed = False
        self._health_stop = threading.Event()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="router-health", daemon=True)
        self._health_thread.start()
        log_info(f"router: fronting {len(self._replicas)} replica(s) "
                 f"[{', '.join(r.name for r in self._replicas)}], "
                 f"retry_max={self.config.retry_max}, "
                 f"hedge_ms={self.config.hedge_ms}")

    # -- health ----------------------------------------------------------
    def _eject(self, rep: _Replica, reason: str) -> None:
        """Idempotent ejection with first-class telemetry — used by the
        health poller AND the submit path (a replica that turns out
        closed at dispatch must stop receiving traffic NOW, not a poll
        period later)."""
        from ..obs import events as obs_events

        with self._lock:
            if not rep.healthy:
                return
            rep.healthy = False
        rep.ejections += 1
        self._c_ejections.labels(replica=rep.name).inc()
        obs_events.publish(
            "router.replica_ejected", f"{rep.name} ejected: {reason}",
            severity="error", replica=rep.name, reason=reason)
        log_warning(f"router: ejected {rep.name} ({reason})")

    def _readmit(self, rep: _Replica) -> None:
        from ..obs import events as obs_events

        with self._lock:
            if rep.healthy:
                return
            rep.healthy = True
        rep.readmissions += 1
        self._c_readmissions.labels(replica=rep.name).inc()
        obs_events.publish(
            "router.replica_readmitted",
            f"{rep.name} healthy for {rep.consec_good} checks — "
            "readmitted", severity="info", replica=rep.name)
        log_info(f"router: readmitted {rep.name}")

    def _health_loop(self) -> None:
        period = self.config.health_period_ms / 1e3
        while not self._health_stop.wait(period):
            for rep in self._replicas:
                try:
                    h = rep.server.health()
                    ok = bool(h.get("ok"))
                except Exception:   # noqa: BLE001 — unreachable = bad
                    ok = False
                if ok:
                    rep.consec_good += 1
                    rep.consec_bad = 0
                    if (not rep.healthy and rep.consec_good
                            >= self.config.readmit_after):
                        self._readmit(rep)
                else:
                    rep.consec_bad += 1
                    rep.consec_good = 0
                    if (rep.healthy and rep.consec_bad
                            >= self.config.eject_after):
                        self._eject(
                            rep, f"failed {rep.consec_bad} consecutive "
                            "health checks")

    # -- placement (serve/placement.py drives these) ---------------------
    def set_placement(self, tenant: str, names) -> None:
        """Pin one tenant's traffic to a replica subset.  Unknown
        replica names are rejected (a typo must not silently blackhole
        a tenant); an empty subset clears the pin."""
        names = tuple(names or ())
        known = {r.name for r in self._replicas}
        bad = [n for n in names if n not in known]
        if bad:
            raise ValueError(f"unknown replica(s) {bad} in placement "
                             f"for tenant {tenant!r}")
        with self._lock:
            if names:
                self._placement[tenant] = names
            else:
                self._placement.pop(tenant, None)

    def placement(self) -> Dict[str, tuple]:
        with self._lock:
            return dict(self._placement)

    def _pick(self, tried: set,
              tenant: str = DEFAULT_TENANT) -> Optional[_Replica]:
        """Next candidate: round-robin over healthy untried replicas,
        falling back to unhealthy untried ones (a request with no
        healthy candidate left still deserves a hail-mary — the health
        view may simply be stale).  A tenant with a placement pin only
        sees its pinned subset."""
        with self._lock:
            allowed = self._placement.get(tenant)
            n = len(self._replicas)
            for healthy_only in (True, False):
                for k in range(n):
                    rep = self._replicas[(self._rr + k) % n]
                    if rep.name in tried:
                        continue
                    if allowed is not None and rep.name not in allowed:
                        continue
                    if healthy_only and not rep.healthy:
                        continue
                    self._rr = (self._rr + k + 1) % n
                    return rep
        return None

    # -- request path ----------------------------------------------------
    def _attempt(self, rep: _Replica, rows: np.ndarray,
                 budget_ms: Optional[float], trace_id: Optional[str],
                 tenant: str, out: "queue.Queue", idx: int) -> None:
        try:
            # chaos seams: a dropped or slow link to THIS replica
            faults.fire("rpc_delay", site=rep.name)
            faults.fire("rpc_drop", site=rep.name)
            res = rep.server.submit(rows, timeout_ms=budget_ms,
                                    trace_id=trace_id, tenant=tenant)
            out.put(("ok", idx, rep, res))
        except BaseException as e:  # noqa: BLE001 — classified by caller
            out.put(("err", idx, rep, e))

    def submit(self, rows, timeout_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               tenant: str = DEFAULT_TENANT) -> ServeResult:
        """Route one request; retries and hedges under the deadline.
        Raises :class:`RequestTimeout` on budget exhaustion (HTTP 504),
        :class:`ServerOverloaded` when every tried replica shed, or the
        last replica error when no candidate remains."""
        if self._closed:
            raise ServerClosed("router is shut down")
        X = np.asarray(rows, np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        cfg = self.config
        t0 = time.monotonic()
        budget_ms = (timeout_ms if timeout_ms is not None
                     else (cfg.deadline_ms or None))
        if budget_ms is not None and budget_ms <= 0:
            budget_ms = None
        deadline = t0 + budget_ms / 1e3 if budget_ms else None
        self.metrics.on_submit(X.shape[0], 0)

        results: "queue.Queue" = queue.Queue()
        tried: set = set()
        in_flight = 0
        attempts = 0
        hedges = 0
        retries_left = cfg.retry_max
        last_err: Optional[BaseException] = None
        all_shed = True

        def remaining_ms() -> Optional[float]:
            if deadline is None:
                return None
            return max((deadline - time.monotonic()) * 1e3, 0.0)

        hedge_attempts: set = set()

        def launch(is_hedge: bool = False) -> bool:
            nonlocal in_flight, attempts
            rep = self._pick(tried, tenant)
            if rep is None:
                return False
            tried.add(rep.name)
            if is_hedge:
                hedge_attempts.add(attempts)
            threading.Thread(
                target=self._attempt,
                args=(rep, X, remaining_ms(), trace_id, tenant, results,
                      attempts),
                name=f"router-attempt-{rep.name}", daemon=True).start()
            attempts += 1
            in_flight += 1
            return True

        if not launch():
            raise ServerClosed("router has no replicas")
        while True:
            # wait for the next completion, the hedge instant, or the
            # deadline — whichever is first
            wait_s = None
            rem = remaining_ms()
            if rem is not None:
                wait_s = rem / 1e3
            with self._lock:
                pinned = self._placement.get(tenant)
            pool = len(pinned) if pinned is not None \
                else len(self._replicas)
            can_hedge = (cfg.hedge_ms > 0 and hedges < cfg.max_hedges
                         and len(tried) < pool)
            if can_hedge:
                elapsed_ms = (time.monotonic() - t0) * 1e3
                hedge_in = max(cfg.hedge_ms * (hedges + 1)
                               - elapsed_ms, 0.0) / 1e3
                wait_s = (hedge_in if wait_s is None
                          else min(wait_s, hedge_in))
            try:
                kind, idx, rep, payload = results.get(
                    timeout=wait_s if wait_s is None or wait_s > 0
                    else 0.001)
            except queue.Empty:
                rem = remaining_ms()
                if rem is not None and rem <= 0:
                    # deadline exhausted MID-HEDGE: the client gets its
                    # 504 now; stragglers complete into the void and are
                    # never counted (single-writer accounting)
                    self.metrics.on_timeout()
                    self.slo.record(False, trace_id=trace_id or "")
                    raise RequestTimeout(
                        f"router deadline ({budget_ms:.0f} ms) expired "
                        f"after {attempts} attempt(s)")
                if can_hedge and launch(is_hedge=True):
                    hedges += 1
                    self._c_hedges.inc()
                continue
            in_flight -= 1
            if kind == "ok":
                res: ServeResult = payload
                lat_ms = (time.monotonic() - t0) * 1e3
                if idx in hedge_attempts:
                    self._c_hedge_wins.inc()
                self.metrics.on_complete(lat_ms, res.degraded,
                                         trace_id=res.trace_id)
                self.slo.record(True, latency_ms=lat_ms,
                                trace_id=res.trace_id)
                return res
            err: BaseException = payload
            if isinstance(err, (ValueError, TypeError, UnknownTenant)):
                # client input error — identical on every replica (an
                # unknown tenant is the caller's mistake, not a replica
                # fault: retrying elsewhere cannot create the lineage)
                self.metrics.on_error()
                raise err
            if isinstance(err, RequestTimeout):
                # the replica-side budget we passed expired in ITS queue
                self.metrics.on_timeout()
                self.slo.record(False, trace_id=trace_id or "")
                raise err
            last_err = err
            if not isinstance(err, ServerOverloaded):
                all_shed = False
            retryable = isinstance(err, _RETRYABLE + (ServerOverloaded,))
            if isinstance(err, ServerClosed):
                # died between health check and dispatch: stop offering
                # it traffic NOW, a poll period is too long to wait
                self._eject(rep, "ServerClosed at dispatch")
            if in_flight > 0:
                continue            # a hedge is still running — wait it out
            rem = remaining_ms()
            if retryable and retries_left > 0 and \
                    (rem is None or rem > 0) and launch():
                retries_left -= 1
                self.metrics.on_retry()
                continue
            # out of candidates, retries, or time
            if all_shed and isinstance(last_err, ServerOverloaded):
                self.metrics.on_shed()
                self.slo.record(False, trace_id=trace_id or "")
                raise last_err
            self.metrics.on_error()
            self.slo.record(False, trace_id=trace_id or "")
            if isinstance(last_err, Exception):
                raise last_err
            raise ServeError(str(last_err))

    # -- Server-compatible surface (ServeHTTP duck-typing) ---------------
    def version(self, tenant: str = DEFAULT_TENANT) -> Optional[str]:
        tags = {r.server.tenant_registry(tenant).current_tag()
                for r in self._replicas}
        return tags.pop() if len(tags) == 1 else None

    def tenant_names(self):
        return self._replicas[0].server.tenant_names()

    def tenants_snapshot(self) -> Dict[str, Any]:
        """GET /tenants on a fleet: per-replica tenant views keyed by
        replica name, the fleet-consensus version per tenant, and the
        placement map (which replicas each tenant's traffic is pinned
        to)."""
        per = {r.name: r.server.tenants_snapshot()["tenants"]
               for r in self._replicas}
        versions = {}
        for t in self.tenant_names():
            try:
                versions[t] = self.version(t)
            except UnknownTenant:
                versions[t] = None      # mid-add_tenant fan-out
        return {"replicas": per, "versions": versions,
                "placement": {t: list(v)
                              for t, v in self.placement().items()}}

    def replica_states(self) -> Dict[str, Dict[str, Any]]:
        return {r.name: {"healthy": r.healthy,
                         "consec_bad": r.consec_bad,
                         "ejections": r.ejections,
                         "readmissions": r.readmissions}
                for r in self._replicas}

    def metrics_snapshot(self) -> Dict[str, Any]:
        snap = self.metrics.snapshot()
        snap["version"] = self.version()
        snap["versions"] = sorted(
            {t for r in self._replicas
             for t in r.server.registry.versions()})
        snap["router"] = {
            "replicas": self.replica_states(),
            "hedges": int(self._c_hedges.get()),
            "hedge_wins": int(self._c_hedge_wins.get()),
        }
        return snap

    def slo_snapshot(self,
                     tenant: Optional[str] = None) -> Dict[str, Any]:
        if tenant is not None:
            # per-tenant burn rates live on the replicas (each tracks
            # its own traffic slice); the router view is their union
            per = {r.name: r.server.slo_snapshot(tenant=tenant)
                   for r in self._replicas}
            return {"tenant": tenant, "version": self.version(tenant),
                    "replicas": per}
        out = self.slo.snapshot()
        out["version"] = self.version()
        out["exemplars"] = [
            {"le": le, **ex} for le, ex in self.metrics.exemplars()]
        return out

    def drift_snapshot(self,
                       tenant: Optional[str] = None) -> Dict[str, Any]:
        """GET /drift on a fleet: per-replica skew evaluations (each
        replica samples its own traffic slice against the version's
        reference) keyed by replica name, plus the fleet-level view —
        armed if ANY replica is, alerting = union.  ``tenant`` narrows
        every per-replica evaluation to that tenant's detector."""
        per = {r.name: r.server.drift_snapshot(tenant=tenant)
               for r in self._replicas}
        alerting = sorted({f for d in per.values()
                           for f in d.get("alerting", [])})
        out = {"armed": any(d.get("armed") for d in per.values()),
               "version": self.version(tenant if tenant is not None
                                       else DEFAULT_TENANT),
               "alerting": alerting,
               "replicas": per}
        if tenant is not None:
            out["tenant"] = tenant
        return out

    def health(self) -> Dict[str, Any]:
        """Fleet-level liveness: ok while ANY replica is healthy (the
        router can still serve).  Per-replica payloads ride along so
        ``/healthz`` on the router shows exactly which replica the
        ejection logic is acting on and why."""
        from .. import __version__

        per = {r.name: r.server.health() for r in self._replicas}
        healthy = [r.name for r in self._replicas if r.healthy]
        return {"ok": bool(healthy), "version": self.version(),
                "healthy_replicas": healthy,
                "ejected_replicas": [r.name for r in self._replicas
                                     if not r.healthy],
                "replicas": per,
                "server_version": __version__,
                "uptime_s": round(time.monotonic() - self._t_start, 3)}

    def uptime_s(self) -> float:
        return time.monotonic() - self._t_start

    def close(self) -> None:
        """Stop the health poller (the fleet owns replica shutdown)."""
        self._closed = True
        self._health_stop.set()
        self._health_thread.join(timeout=2.0)

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def hedge_frac(snapshot: Dict[str, Any]) -> float:
    """``router_hedge_frac``: hedge launches per completed request, the
    BENCH-record rate ``measure_fleet`` watches (bench.py)."""
    router = snapshot.get("router", {})
    done = snapshot.get("completed") or 0
    return round(router.get("hedges", 0) / done, 4) if done else 0.0
