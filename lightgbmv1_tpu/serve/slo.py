"""Serving SLOs with multi-window burn-rate evaluation.

A raw p999 gauge tells an operator a replica is slow *right now*; it
cannot answer the question that actually pages someone: **are we
spending our error budget fast enough to miss the SLO this period?**
This module is the standard answer (the SRE-workbook multi-window
multi-burn-rate rule) applied to the two objectives the serving path
owns:

* **availability** — fraction of admitted-or-shed requests answered
  successfully (sheds, queue timeouts, batch errors and watchdog
  failures all spend budget: a request the client had to retry is a
  failure no matter which internal mechanism refused it);
* **latency** — fraction of *successful* requests answered under the
  objective threshold (failed requests are availability's problem;
  counting them here would double-bill one incident against two
  budgets).

**Burn rate** is error-fraction divided by the budget fraction
``(1 - target)``: burn 1.0 spends the budget exactly over the period,
burn 14.4 exhausts a 30-day budget in ~2 days.  Evaluation runs over
two windows — a slow window (the trend) and a fast window (the
confirmation that the problem is *still* happening) — and an alert
requires BOTH above threshold: the fast window alone pages on blips,
the slow window alone keeps paging long after recovery.  ``page`` uses
``fast_burn`` (default 14.4), ``warn`` uses ``slow_burn`` (default 6).

**Exemplars**: every completed request's latency lands in the serving
histogram with its trace id attached (obs/metrics.py per-bucket
worst-tail exemplars), and the tracker keeps the global worst-K
``(latency, trace_id)`` — so ``GET /slo`` hands the operator the exact
request ids to grep in an armed trace, closing the loop from "budget is
burning" to "this is the request that burned it".

State is a time-bucketed ring (``bucket_s`` resolution, sized to the
slow window): O(slow_window / bucket_s) memory, O(1) record, no
per-request allocation beyond the worst-K list.  All entry points take
an optional explicit ``now`` so tests replay traffic deterministically.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass
class SLOConfig:
    """SLO policy knobs (mirrored by the ``serve_slo_*`` config names)."""

    availability_target: float = 0.999   # fraction answered successfully
    latency_ms: float = 50.0             # latency objective threshold
    latency_target: float = 0.99         # fraction of good reqs under it
    fast_window_s: float = 60.0          # short confirmation window
    slow_window_s: float = 600.0         # long trend window
    fast_burn: float = 14.4              # page threshold (both windows)
    slow_burn: float = 6.0               # warn threshold (both windows)
    bucket_s: float = 1.0                # ring resolution
    worst_k: int = 8                     # exemplar trace ids retained

    def __post_init__(self):
        for name in ("availability_target", "latency_target"):
            v = float(getattr(self, name))
            if not 0.0 < v < 1.0:
                raise ValueError(f"{name} must be in (0, 1), got {v}")
            setattr(self, name, v)
        self.latency_ms = max(float(self.latency_ms), 0.0)
        self.bucket_s = max(float(self.bucket_s), 1e-3)
        self.fast_window_s = max(float(self.fast_window_s), self.bucket_s)
        self.slow_window_s = max(float(self.slow_window_s),
                                 self.fast_window_s)
        self.fast_burn = max(float(self.fast_burn), 0.0)
        self.slow_burn = max(float(self.slow_burn), 0.0)
        self.worst_k = max(int(self.worst_k), 0)


class _Bucket:
    __slots__ = ("idx", "total", "errors", "slow")

    def __init__(self):
        self.idx = -1
        self.total = 0
        self.errors = 0
        self.slow = 0

    def reset(self, idx: int) -> None:
        self.idx = idx
        self.total = 0
        self.errors = 0
        self.slow = 0


class SLOTracker:
    """Thread-safe request-outcome accumulator + burn-rate evaluator."""

    def __init__(self, config: Optional[SLOConfig] = None):
        self.config = config or SLOConfig()
        n = int(math.ceil(self.config.slow_window_s
                          / self.config.bucket_s)) + 1
        self._buckets = [_Bucket() for _ in range(n)]
        self._worst: List[Dict[str, object]] = []
        self._lock = threading.Lock()
        self._total = 0
        self._errors = 0

    # -- write path ------------------------------------------------------
    def record(self, ok: bool, latency_ms: Optional[float] = None,
               trace_id: str = "", now: Optional[float] = None) -> None:
        """One finished request: ``ok=False`` for shed / timeout / batch
        error / watchdog failure (availability budget), ``ok=True`` with
        its latency for an answered one (latency budget)."""
        cfg = self.config
        t = time.monotonic() if now is None else float(now)
        idx = int(t // cfg.bucket_s)
        with self._lock:
            b = self._buckets[idx % len(self._buckets)]
            if b.idx != idx:
                b.reset(idx)
            b.total += 1
            self._total += 1
            if not ok:
                b.errors += 1
                self._errors += 1
                return
            if latency_ms is None:
                return
            lat = float(latency_ms)
            if lat > cfg.latency_ms:
                b.slow += 1
            if cfg.worst_k and trace_id:
                w = self._worst
                if len(w) < cfg.worst_k or lat > w[-1]["latency_ms"]:
                    w.append({"latency_ms": round(lat, 3),
                              "trace_id": trace_id})
                    w.sort(key=lambda e: -e["latency_ms"])
                    del w[cfg.worst_k:]

    # -- read path -------------------------------------------------------
    def _window(self, window_s: float, now: float) -> Dict[str, int]:
        cfg = self.config
        lo = int((now - window_s) // cfg.bucket_s) + 1
        hi = int(now // cfg.bucket_s)
        total = errors = slow = 0
        for b in self._buckets:
            if lo <= b.idx <= hi:
                total += b.total
                errors += b.errors
                slow += b.slow
        return {"total": total, "errors": errors, "slow": slow}

    @staticmethod
    def _burn(frac: float, target: float) -> float:
        budget = 1.0 - target
        return frac / budget if budget > 0 else 0.0

    def evaluate(self, now: Optional[float] = None) -> Dict[str, object]:
        """Multi-window burn-rate evaluation; alert booleans require
        BOTH windows over threshold (see module docstring)."""
        cfg = self.config
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            wins = {"fast": {"window_s": cfg.fast_window_s,
                             **self._window(cfg.fast_window_s, t)},
                    "slow": {"window_s": cfg.slow_window_s,
                             **self._window(cfg.slow_window_s, t)}}
            worst = [dict(e) for e in self._worst]
            lifetime = {"total": self._total, "errors": self._errors}
        avail = {}
        lat = {}
        for name, w in wins.items():
            total, errors, slow = w["total"], w["errors"], w["slow"]
            err_frac = errors / total if total else 0.0
            good = total - errors
            slow_frac = slow / good if good else 0.0
            avail[name] = {
                "window_s": w["window_s"], "total": total,
                "errors": errors, "sli": round(1.0 - err_frac, 6),
                "burn_rate": round(
                    self._burn(err_frac, cfg.availability_target), 4),
            }
            lat[name] = {
                "window_s": w["window_s"], "good": good, "slow": slow,
                "sli": round(1.0 - slow_frac, 6),
                "burn_rate": round(
                    self._burn(slow_frac, cfg.latency_target), 4),
            }

        def both_over(d, bar):
            return bool(d["fast"]["burn_rate"] >= bar
                        and d["slow"]["burn_rate"] >= bar)

        return {
            "availability": {"target": cfg.availability_target,
                             "windows": avail},
            "latency": {"target": cfg.latency_target,
                        "objective_ms": cfg.latency_ms,
                        "windows": lat},
            "alerts": {
                "availability_page": both_over(avail, cfg.fast_burn),
                "availability_warn": both_over(avail, cfg.slow_burn),
                "latency_page": both_over(lat, cfg.fast_burn),
                "latency_warn": both_over(lat, cfg.slow_burn),
            },
            "worst": worst,
            "lifetime": lifetime,
        }

    def snapshot(self, now: Optional[float] = None) -> Dict[str, object]:
        """The ``GET /slo`` payload: the evaluation plus the config echo
        (an operator reading the endpoint must not need the deploy repo
        to know what the targets ARE)."""
        out = self.evaluate(now=now)
        cfg = self.config
        out["config"] = {
            "availability_target": cfg.availability_target,
            "latency_ms": cfg.latency_ms,
            "latency_target": cfg.latency_target,
            "fast_window_s": cfg.fast_window_s,
            "slow_window_s": cfg.slow_window_s,
            "fast_burn": cfg.fast_burn,
            "slow_burn": cfg.slow_burn,
        }
        return out
