"""Replicated serving fleet with coordinated two-phase publish.

One :class:`~lightgbmv1_tpu.serve.server.Server` is one failure domain:
a wedged dispatcher or a killed replica is 100% unavailability.  A
fleet is N replicas — each with its OWN registry, dispatcher, metrics
and SLO tracker (no shared mutable state between replicas, so one
replica's death cannot corrupt another) — fronted by
:class:`~lightgbmv1_tpu.serve.router.Router`, which owns health-check
ejection and per-request retry/hedging.

The piece that must be COORDINATED is publish.  Publishing replica-by-
replica with the single-server ``publish()`` would leave the fleet
mixed-version whenever a middle replica rejects the candidate — some
replicas answering with the new model, some with the old, and no tag a
client can trust.  The fleet publish is therefore two-phase over the
registry's prepare/commit split (registry.py):

* **phase 1 — warm all**: every replica builds + warms + validates the
  candidate (``registry.prepare``), compile work OFF every serving
  path.  ANY replica's validation failure aborts the whole publish:
  prepared versions are discarded, NO replica has swapped, and every
  replica keeps serving the prior version bit-exactly
  (:class:`FleetPublishError` carries the per-replica causes).
* **phase 2 — swap all**: only after every replica holds a warmed,
  probe-validated version does each commit run (one reference swap per
  replica).  A commit-phase failure (defensive: commits are reference
  swaps and should not fail) rolls the already-committed replicas back
  so the fleet never stays split.

Replica version tags stay aligned across the fleet because every
replica's registry sees the same publish/abort sequence (a failed
prepare burns the same seq number on every replica).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..utils.log import log_info, log_warning
from .registry import ModelVersion
from .server import DEFAULT_TENANT, ServeConfig, Server


class FleetPublishError(RuntimeError):
    """The two-phase fleet publish aborted: at least one replica failed
    warm/validation.  No replica swapped; the prior version keeps
    serving everywhere.  ``causes`` maps replica name -> error."""

    def __init__(self, msg: str, causes: Optional[Dict[str, str]] = None):
        super().__init__(msg)
        self.causes = dict(causes or {})


class Fleet:
    """N replica Servers sharing a ServeConfig, with two-phase publish.

    The fleet OWNS its replicas (``close()`` closes them); the router
    only references them.  ``model`` (optional) is published fleet-wide
    at construction."""

    def __init__(self, model=None, *, n_replicas: int = 2,
                 config: Optional[ServeConfig] = None,
                 names: Optional[List[str]] = None):
        n = max(int(n_replicas), 1)
        self.config = config or ServeConfig()
        names = list(names) if names else [f"r{i}" for i in range(n)]
        if len(names) != n:
            raise ValueError(f"{len(names)} names for {n} replicas")
        self.replicas: List[Server] = [
            Server(None, config=self.config, name=nm) for nm in names]
        if model is not None:
            self.publish(model)

    # -- lookups ---------------------------------------------------------
    def replica(self, name: str) -> Server:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"no replica {name!r}")

    def names(self) -> List[str]:
        return [r.name for r in self.replicas]

    def version(self, tenant: str = DEFAULT_TENANT) -> Optional[str]:
        """The fleet's consensus version tag for one tenant lineage
        (None when replicas disagree or nothing is published — a mixed
        fleet must be VISIBLE, not averaged away)."""
        tags = {r.tenant_registry(tenant).current_tag()
                for r in self.replicas}
        return tags.pop() if len(tags) == 1 else None

    def healths(self) -> Dict[str, Dict[str, Any]]:
        return {r.name: r.health() for r in self.replicas}

    # -- tenants ---------------------------------------------------------
    def add_tenant(self, name: str, *, weight: float = 1.0,
                   slo=None, predictor_kwargs=None) -> None:
        """Stand a named tenant lineage up on EVERY replica (idempotent
        per replica, so a partially-added tenant heals on retry)."""
        for r in self.replicas:
            r.add_tenant(name, weight=weight, slo=slo,
                         predictor_kwargs=predictor_kwargs)

    def remove_tenant(self, name: str) -> None:
        for r in self.replicas:
            r.remove_tenant(name)

    def tenant_names(self) -> List[str]:
        return self.replicas[0].tenant_names()

    def tenants_snapshot(self) -> Dict[str, Any]:
        """Per-replica tenant snapshots keyed by replica name, plus the
        fleet-consensus version per tenant."""
        per_replica = {r.name: r.tenants_snapshot()["tenants"]
                       for r in self.replicas}
        versions = {t: self.version(t) for t in self.tenant_names()}
        return {"replicas": per_replica, "versions": versions}

    # -- coordinated publish ---------------------------------------------
    def publish(self, model, tenant: str = DEFAULT_TENANT,
                **meta) -> str:
        """Two-phase fleet publish into one tenant's lineage; returns
        the fleet-wide version tag.  Raises :class:`FleetPublishError`
        (no replica swapped, no OTHER tenant touched) when any
        replica's prepare fails."""
        from ..obs import events as obs_events

        cfg = self.config
        prepared: Dict[str, ModelVersion] = {}
        causes: Dict[str, str] = {}
        # phase 1: warm + validate on EVERY replica (even after a
        # failure — every replica's seq must advance identically so
        # tags stay aligned fleet-wide)
        for r in self.replicas:
            try:
                prepared[r.name] = r.tenant_registry(tenant).prepare(
                    model, degrade_trees=cfg.degrade_trees,
                    max_batch_rows=cfg.max_batch_rows,
                    meta=meta or None, probe_rows=cfg.probe_rows)
            except Exception as e:  # noqa: BLE001 — collected, aborts
                causes[r.name] = f"{type(e).__name__}: {e}"
        if causes:
            obs_events.publish(
                "fleet.publish_abort",
                f"{len(causes)}/{len(self.replicas)} replicas failed "
                "warm/validation — fleet publish aborted, prior version "
                "keeps serving everywhere",
                severity="error", causes=causes,
                tenant=tenant or "default")
            log_warning(f"fleet: publish aborted in phase 1 ({causes}); "
                        "no replica swapped")
            raise FleetPublishError(
                f"fleet publish aborted: {causes}", causes)
        # phase 2: commit everywhere; defensively roll back on the
        # (should-be-impossible) mid-commit failure
        committed: List[Server] = []
        try:
            for r in self.replicas:
                r.tenant_registry(tenant).commit(prepared[r.name])
                committed.append(r)
        except Exception as e:  # noqa: BLE001
            for r in committed:
                try:
                    r.tenant_registry(tenant).rollback()
                except Exception:   # noqa: BLE001
                    pass
            obs_events.publish(
                "fleet.publish_abort",
                f"commit-phase failure on replica "
                f"{self.replicas[len(committed)].name}: rolled "
                f"{len(committed)} committed replica(s) back",
                severity="error")
            raise FleetPublishError(
                f"fleet commit failed after {len(committed)} swaps "
                f"({type(e).__name__}: {e}); rolled back") from e
        tag = prepared[self.replicas[0].name].tag
        log_info(f"fleet: published {tag} on "
                 f"{len(self.replicas)} replicas (two-phase)")
        return tag

    def rollback(self, tenant: str = DEFAULT_TENANT) -> str:
        """Fleet-wide rollback of one tenant's lineage (each replica's
        retained previous version; instant)."""
        tags = {r.tenant_registry(tenant).rollback()
                for r in self.replicas}
        if len(tags) != 1:
            log_warning(f"fleet: rollback left mixed versions {tags}")
        return sorted(tags)[0]

    def close(self) -> None:
        for r in self.replicas:
            r.close()

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
