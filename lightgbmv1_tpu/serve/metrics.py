"""Serve metrics — a thin adapter over the unified obs registry.

Until ISSUE 9 this module kept its own ad-hoc counters; the store is now
:class:`lightgbmv1_tpu.obs.metrics.Registry` — every serving counter,
gauge and the latency histogram are ordinary registry metrics, so
``GET /metrics`` can serve Prometheus text exposition straight from the
same store (serve/http.py content negotiation) while ``snapshot()``
keeps emitting the EXACT JSON dict the pre-obs module did —
``bench.py``'s serve block, ``tools/perf_report.py``'s "Serving"
section and the serve tests consume those keys unchanged.

Latency quantiles stay exact over the most recent ``window``
completions: the registry histogram retains a bounded raw-sample window
(``sample_window``) alongside its Prometheus buckets, so the p999 the
JSON reports and the bucket series Prometheus scrapes come from the
same observations.

Each ``ServeMetrics`` gets its OWN registry by default (one registry
per replica is the Prometheus model, and concurrent test servers stay
isolated); pass ``registry=`` to aggregate several servers into one.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..obs.metrics import DEFAULT_MS_BUCKETS, Registry

_COUNTERS = (
    ("submitted", "serve_submitted_total", "Requests admitted to the queue"),
    ("completed", "serve_completed_total", "Requests answered"),
    ("shed", "serve_shed_total", "Requests shed by admission control"),
    ("timeouts", "serve_timeouts_total", "Requests expired in queue"),
    ("errors", "serve_errors_total", "Requests failed by batch errors"),
    ("degraded", "serve_degraded_total",
     "Requests answered by the truncated-tree overload predictor"),
    ("swaps", "serve_swaps_total", "Model version swaps (incl. rollbacks)"),
    ("rollbacks", "serve_rollbacks_total", "Registry rollbacks"),
    ("retries", "serve_retries_total", "Transient batch errors retried"),
    ("breaker_trips", "serve_breaker_trips_total",
     "Circuit-breaker auto-rollbacks"),
    ("watchdog_failures", "serve_watchdog_failures_total",
     "Requests failed by the stalled-batch watchdog"),
    ("dispatcher_restarts", "serve_dispatcher_restarts_total",
     "Dead dispatcher threads restarted"),
    ("publish_rejects", "serve_publish_rejects_total",
     "Candidate versions refused by publish validation"),
    ("batches", "serve_batches_total", "Device batches dispatched"),
    ("batch_rows", "serve_batch_rows_total",
     "Real rows across dispatched batches"),
    ("batch_capacity", "serve_batch_capacity_total",
     "Bucket capacity across dispatched batches"),
)


def _quantile(child, q: float) -> Optional[float]:
    return child.quantile(q)


class ServeMetrics:
    """Thread-safe serving telemetry over one obs Registry;
    ``snapshot()`` is the one JSON read surface (everything else is
    write-only on the hot path) and ``registry.prometheus_text()`` the
    exposition surface."""

    def __init__(self, window: int = 8192,
                 registry: Optional[Registry] = None):
        self.window = max(int(window), 16)
        self.registry = registry if registry is not None else Registry()
        self._c = {attr: self.registry.counter(name, help_text)
                   for attr, name, help_text in _COUNTERS}
        self._queue_depth = self.registry.gauge(
            "serve_queue_depth", "Backlogged rows at last submit/batch")
        self._queue_depth_max = self.registry.gauge(
            "serve_queue_depth_max", "High-water backlog (rows)")
        self._latency = self.registry.histogram(
            "serve_latency_ms", "End-to-end request latency (ms)",
            buckets=DEFAULT_MS_BUCKETS, sample_window=self.window)
        self._lock = threading.Lock()   # guards only the QPS timestamps
        self._t0: Optional[float] = None
        self._t_last: Optional[float] = None

    def reset(self) -> None:
        self.registry.reset(
            [m.name for m in self._c.values()]
            + ["serve_queue_depth", "serve_queue_depth_max",
               "serve_latency_ms"])
        with self._lock:
            self._t0 = None
            self._t_last = None

    # -- hot-path writers ------------------------------------------------
    def on_submit(self, n_rows: int, queue_depth: int) -> None:
        with self._lock:
            if self._t0 is None:
                self._t0 = time.monotonic()
        self._c["submitted"].inc()
        self._queue_depth.set(queue_depth)
        self._queue_depth_max.set_max(queue_depth)

    def on_shed(self) -> None:
        self._c["shed"].inc()

    def on_timeout(self) -> None:
        self._c["timeouts"].inc()

    def on_error(self) -> None:
        self._c["errors"].inc()

    def on_swap(self, rollback: bool = False) -> None:
        self._c["swaps"].inc()
        if rollback:
            self._c["rollbacks"].inc()

    def on_retry(self) -> None:
        self._c["retries"].inc()

    def on_breaker(self) -> None:
        self._c["breaker_trips"].inc()

    def on_watchdog(self, n: int = 1) -> None:
        self._c["watchdog_failures"].inc(n)

    def on_dispatcher_restart(self) -> None:
        self._c["dispatcher_restarts"].inc()

    def on_publish_reject(self) -> None:
        self._c["publish_rejects"].inc()

    def on_batch(self, rows: int, bucket: int, queue_depth: int) -> None:
        """One dispatched device batch: ``rows`` real rows padded into a
        ``bucket``-row executable (occupancy = rows / bucket)."""
        self._c["batches"].inc()
        self._c["batch_rows"].inc(rows)
        self._c["batch_capacity"].inc(max(bucket, 1))
        self._queue_depth.set(queue_depth)

    def on_complete(self, latency_ms: float, degraded: bool = False,
                    trace_id: str = "") -> None:
        with self._lock:
            self._t_last = time.monotonic()
        self._c["completed"].inc()
        if degraded:
            self._c["degraded"].inc()
        # the trace id rides as the bucket's worst-tail exemplar: the
        # slowest request in every latency bucket stays greppable from
        # the exposition and GET /slo
        self._latency.observe(
            latency_ms,
            exemplar={"trace_id": trace_id} if trace_id else None)

    def exemplars(self):
        """``[(le, exemplar_dict)]`` of the latency histogram's
        per-bucket worst-tail trace ids."""
        return self._latency.exemplars()

    def value(self, attr: str) -> int:
        """Point read of one counter (e.g. ``dispatcher_restarts`` for
        the /healthz observability fields) without building the full
        snapshot."""
        return int(self._c[attr].get())

    # -- read surface ----------------------------------------------------
    def prometheus_text(self, exemplars: bool = False) -> str:
        return self.registry.prometheus_text(exemplars=exemplars)

    def snapshot(self) -> Dict[str, object]:
        """One JSON-able dict; the serve_* BENCH fields are computed from
        exactly these keys (bench.py measure_serve).  Key set and value
        semantics are byte-compatible with the pre-registry module."""
        v = {attr: int(c.get()) for attr, c in self._c.items()}
        lat = self._latency._solo()
        with self._lock:
            span = ((self._t_last - self._t0)
                    if self._t0 is not None and self._t_last is not None
                    and self._t_last > self._t0 else None)
        total = v["submitted"] + v["shed"]
        return {
            "submitted": v["submitted"],
            "completed": v["completed"],
            "shed": v["shed"],
            "timeouts": v["timeouts"],
            "errors": v["errors"],
            "degraded": v["degraded"],
            "swaps": v["swaps"],
            "rollbacks": v["rollbacks"],
            "retries": v["retries"],
            "breaker_trips": v["breaker_trips"],
            "watchdog_failures": v["watchdog_failures"],
            "dispatcher_restarts": v["dispatcher_restarts"],
            "publish_rejects": v["publish_rejects"],
            "batches": v["batches"],
            "qps": (round(v["completed"] / span, 2) if span else None),
            "p50_ms": _quantile(lat, 0.50),
            "p99_ms": _quantile(lat, 0.99),
            "p999_ms": _quantile(lat, 0.999),
            "batch_occupancy": (round(v["batch_rows"]
                                      / v["batch_capacity"], 4)
                                if v["batch_capacity"] else None),
            "mean_batch_rows": (round(v["batch_rows"] / v["batches"], 1)
                                if v["batches"] else None),
            "queue_depth": int(self._queue_depth.get()),
            "queue_depth_max": int(self._queue_depth_max.get()),
            "shed_frac": (round(v["shed"] / total, 4) if total else 0.0),
            "latency_window": lat.window_len(),
        }
