"""Serve metrics core — the observability half of the online subsystem.

The reference ships no serving telemetry at all (its Predictor is a batch
file->file application); a service answering live traffic needs the four
questions answered continuously: how much (QPS), how fast (latency
quantiles), how full (batch occupancy / queue depth), and how degraded
(sheds, timeouts, degraded answers).  This module keeps those counters
cheap enough to update per request under the batcher lock and snapshots
them as one JSON-able dict — ``bench.py``'s serve block and
``tools/perf_report.py``'s "Serving" section render the same fields.

Latency quantiles come from a fixed-size ring of the most recent
``window`` completions (exact over the window, O(window log window) only
at snapshot time) — a bounded-memory stand-in for a streaming sketch
that is exact for the smoke/bench populations we record.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


def _quantile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


class ServeMetrics:
    """Thread-safe counters + a latency ring; ``snapshot()`` is the one
    read surface (everything else is write-only on the hot path)."""

    def __init__(self, window: int = 8192):
        self._lock = threading.Lock()
        self.window = max(int(window), 16)
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._lat_ms: List[float] = []
            self._lat_pos = 0
            self.submitted = 0
            self.completed = 0
            self.shed = 0
            self.timeouts = 0
            self.errors = 0
            self.degraded = 0
            self.swaps = 0
            self.rollbacks = 0
            self.retries = 0            # transient batch errors retried
            self.breaker_trips = 0      # circuit-breaker auto-rollbacks
            self.watchdog_failures = 0  # requests failed by the watchdog
            self.dispatcher_restarts = 0
            self.publish_rejects = 0    # candidate versions refused
            self.batches = 0
            self.batch_rows = 0
            self.batch_capacity = 0
            self.queue_depth = 0
            self.queue_depth_max = 0
            self._t0: Optional[float] = None
            self._t_last: Optional[float] = None

    # -- hot-path writers ------------------------------------------------
    def on_submit(self, n_rows: int, queue_depth: int) -> None:
        with self._lock:
            now = time.monotonic()
            if self._t0 is None:
                self._t0 = now
            self.submitted += 1
            self.queue_depth = queue_depth
            if queue_depth > self.queue_depth_max:
                self.queue_depth_max = queue_depth

    def on_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def on_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def on_error(self) -> None:
        with self._lock:
            self.errors += 1

    def on_swap(self, rollback: bool = False) -> None:
        with self._lock:
            self.swaps += 1
            if rollback:
                self.rollbacks += 1

    def on_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def on_breaker(self) -> None:
        with self._lock:
            self.breaker_trips += 1

    def on_watchdog(self, n: int = 1) -> None:
        with self._lock:
            self.watchdog_failures += n

    def on_dispatcher_restart(self) -> None:
        with self._lock:
            self.dispatcher_restarts += 1

    def on_publish_reject(self) -> None:
        with self._lock:
            self.publish_rejects += 1

    def on_batch(self, rows: int, bucket: int, queue_depth: int) -> None:
        """One dispatched device batch: ``rows`` real rows padded into a
        ``bucket``-row executable (occupancy = rows / bucket)."""
        with self._lock:
            self.batches += 1
            self.batch_rows += rows
            self.batch_capacity += max(bucket, 1)
            self.queue_depth = queue_depth

    def on_complete(self, latency_ms: float, degraded: bool = False) -> None:
        with self._lock:
            self.completed += 1
            self._t_last = time.monotonic()
            if degraded:
                self.degraded += 1
            if len(self._lat_ms) < self.window:
                self._lat_ms.append(latency_ms)
            else:
                self._lat_ms[self._lat_pos] = latency_ms
                self._lat_pos = (self._lat_pos + 1) % self.window

    # -- read surface ----------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """One JSON-able dict; the serve_* BENCH fields are computed from
        exactly these keys (bench.py measure_serve)."""
        with self._lock:
            lat = sorted(self._lat_ms)
            span = ((self._t_last - self._t0)
                    if self._t0 is not None and self._t_last is not None
                    and self._t_last > self._t0 else None)
            total = self.submitted + self.shed
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "degraded": self.degraded,
                "swaps": self.swaps,
                "rollbacks": self.rollbacks,
                "retries": self.retries,
                "breaker_trips": self.breaker_trips,
                "watchdog_failures": self.watchdog_failures,
                "dispatcher_restarts": self.dispatcher_restarts,
                "publish_rejects": self.publish_rejects,
                "batches": self.batches,
                "qps": (round(self.completed / span, 2) if span else None),
                "p50_ms": _quantile(lat, 0.50),
                "p99_ms": _quantile(lat, 0.99),
                "p999_ms": _quantile(lat, 0.999),
                "batch_occupancy": (round(self.batch_rows
                                          / self.batch_capacity, 4)
                                    if self.batch_capacity else None),
                "mean_batch_rows": (round(self.batch_rows / self.batches, 1)
                                    if self.batches else None),
                "queue_depth": self.queue_depth,
                "queue_depth_max": self.queue_depth_max,
                "shed_frac": (round(self.shed / total, 4) if total else 0.0),
                "latency_window": len(lat),
            }
