"""Deadline-aware micro-batching server over the batched inference engine.

The reference's Predictor is an offline application: OMP threads walk a
file of rows as fast as the cores allow (predictor.hpp:29-160).  Online
traffic inverts the problem — requests arrive one at a time from many
clients, and the device engine (models/predict.py) only earns its keep
when rows are batched into its power-of-two compile buckets.  The piece
in between is this module's micro-batcher, and its one policy knob is
explicit: a batch dispatches when it FILLS (``max_batch_rows``, device
occupancy wins) or when its OLDEST request has waited
``max_batch_delay_ms`` (p99 latency wins) — the classic occupancy/latency
trade made visible instead of emergent.

Admission control is a bounded queue priced in ROWS: a submit that would
push the backlog past ``queue_depth_rows`` is shed immediately with
:class:`ServerOverloaded` (the caller knows NOW, instead of everyone
queueing into an OOM).  Under a configured backlog fraction the dispatcher
degrades to the version's truncated-tree predictor (fewer trees =
strictly less walk work per row) and flags the response ``degraded`` —
cheaper answers beat failed answers during an overload spike.

All device work happens on the single dispatcher thread;
``Server.submit()`` is thread-safe and blocks the calling thread until
its rows come back.  Every response echoes the model-version tag that
computed it (see registry.py for the hot-swap contract).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import dump as obs_dump
from ..obs import events as obs_events
from ..obs import trace
from ..utils import faults
from ..utils.log import log_info, log_warning
from .metrics import ServeMetrics
from .registry import ModelRegistry, ModelVersion
from .slo import SLOConfig, SLOTracker


class ServeError(RuntimeError):
    """Base class of the serving-path failures."""


class ServerOverloaded(ServeError):
    """Admission control shed this request (bounded queue was full)."""


class RequestTimeout(ServeError):
    """The request's deadline expired while it sat in the queue."""


class ServerClosed(ServeError):
    """The server is shut down; no further requests are accepted."""


class DispatcherStalled(ServeError):
    """The watchdog declared the in-flight device batch stalled (or the
    dispatcher thread dead) and failed this request instead of letting
    it hang the queue.  HTTP maps it to 503 — the client should retry
    against another replica."""


class DispatcherDied(ServeError):
    """The dispatcher thread exited with this request in flight; the
    watchdog restarts the dispatcher and fails the stranded requests."""


class UnknownTenant(ServeError):
    """The request named a tenant this server does not host.  HTTP maps
    it to 404 — an unknown lineage is a client addressing error, not an
    overload or a server fault."""


# the default tenant: the single-model contract every pre-tenancy caller
# uses.  Its registry/SLO ARE the server's top-level ``registry``/``slo``
# attributes, so solo deployments behave bit-identically.
DEFAULT_TENANT = ""


def _tenant_label(name: str) -> str:
    """Prometheus label value for a tenant ("" reads as 'default')."""
    return name or "default"


@dataclass
class ServeConfig:
    """Serving policy knobs (mirrored by the ``serve_*`` names in
    config.py for the CLI path; defaults match)."""

    max_batch_rows: int = 1024          # bucket to fill before dispatch
    max_batch_delay_ms: float = 2.0     # oldest-request deadline budget
    queue_depth_rows: int = 4096        # admission bound (rows, not reqs)
    timeout_ms: float = 0.0             # per-request timeout; 0 = off
    degrade_trees: int = 0              # truncated-tree overload predictor
    degrade_queue_frac: float = 0.5     # backlog fraction that triggers it
    f64_scores: bool = False            # exact f64 reconstruction per batch
    metrics_window: int = 8192
    # -- failure domains (PR 6) ----------------------------------------
    retry_max: int = 2                  # transient batch errors retried
    retry_backoff_ms: float = 5.0       # exponential base between attempts
    breaker_failures: int = 3           # consecutive failed batches that
                                        # auto-roll back a bad publish
                                        # (0 = breaker off)
    watchdog_ms: float = 0.0            # stalled-batch deadline; 0 = off
    probe_rows: int = 64                # publish golden-probe batch size
                                        # (0 = structural checks only)
    # -- SLOs (serve/slo.py): always-on burn-rate tracking ---------------
    slo: Optional[SLOConfig] = None     # None = default SLOConfig()
    # -- train/serve skew detection (ISSUE 14; obs/drift.py) -------------
    # HARD-OFF default: drift_sample_rows=0 keeps the serving path at
    # one integer compare.  Armed, the dispatcher copies at most
    # drift_per_batch_rows rows per device batch into a bounded ring;
    # GET /drift re-bins the window through the active version's own
    # mappers (ModelVersion.meta["model_reference"]) and judges PSI
    drift_sample_rows: int = 0
    drift_per_batch_rows: int = 64
    drift_min_rows: int = 256
    drift_psi_threshold: float = 0.25
    drift_top_k: int = 8
    drift_psi_groups: int = 16
    drift_sample_stride: int = 4    # sample every Nth device batch
    # -- registry history bound (ISSUE 20 satellite): current + last N
    # versions retained per registry; rollback depth == keep_versions
    keep_versions: int = 4
    predictor_kwargs: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.max_batch_rows = max(int(self.max_batch_rows), 1)
        self.max_batch_delay_ms = max(float(self.max_batch_delay_ms), 0.0)
        self.queue_depth_rows = max(int(self.queue_depth_rows),
                                    self.max_batch_rows)
        self.timeout_ms = max(float(self.timeout_ms), 0.0)
        self.degrade_trees = max(int(self.degrade_trees), 0)
        self.degrade_queue_frac = min(max(
            float(self.degrade_queue_frac), 0.0), 1.0)
        self.retry_max = max(int(self.retry_max), 0)
        self.retry_backoff_ms = max(float(self.retry_backoff_ms), 0.0)
        self.breaker_failures = max(int(self.breaker_failures), 0)
        self.watchdog_ms = max(float(self.watchdog_ms), 0.0)
        self.probe_rows = max(int(self.probe_rows), 0)
        self.drift_sample_rows = max(int(self.drift_sample_rows), 0)
        self.drift_per_batch_rows = max(int(self.drift_per_batch_rows), 1)
        self.drift_min_rows = max(int(self.drift_min_rows), 1)
        self.drift_psi_threshold = max(float(self.drift_psi_threshold),
                                       1e-9)
        self.drift_top_k = max(int(self.drift_top_k), 1)
        self.drift_psi_groups = max(int(self.drift_psi_groups), 2)
        self.drift_sample_stride = max(int(self.drift_sample_stride), 1)
        self.keep_versions = max(int(self.keep_versions), 1)
        if self.slo is None:
            self.slo = SLOConfig()


@dataclass
class ServeResult:
    """One completed request: raw scores plus the serving provenance."""

    values: np.ndarray          # (n, K) raw scores
    version: str                # model-version tag that computed them
    latency_ms: float
    degraded: bool = False
    batch_rows: int = 0         # rows in the device batch that carried it
    trace_id: str = ""          # propagated end-to-end (X-Trace-Id)
    queue_ms: float = 0.0       # enqueue -> batch collected
    walk_ms: float = 0.0        # device predict leg of the carrying batch


class _Request:
    __slots__ = ("rows", "n", "t_enq", "deadline", "event", "result",
                 "error", "trace_id", "state")

    def __init__(self, rows: np.ndarray, deadline: Optional[float],
                 trace_id: Optional[str] = None, state=None):
        self.rows = rows
        self.n = rows.shape[0]
        self.t_enq = time.monotonic()
        self.deadline = deadline
        self.event = threading.Event()
        self.result: Optional[ServeResult] = None
        self.error: Optional[BaseException] = None
        # every request carries a trace id whether or not the tracer is
        # armed — the X-Trace-Id echo and the latency decomposition in
        # ServeResult are always-on; only SPAN RECORDING is gated
        self.trace_id = trace_id or trace.new_trace_id()
        # the tenant state that owns this request (_TenantState) —
        # batches are single-tenant, so the dispatcher reads the model,
        # SLO tracker and drift detector off the request, never a global
        self.state = state


class _TenantState:
    """One hosted model lineage: its own registry (versioning/rollback),
    SLO tracker, drift detector anchor, and queue-row accounting for
    fair-share admission.  The DEFAULT tenant ("") aliases the server's
    top-level ``registry``/``slo`` so single-model callers see exactly
    the pre-tenancy object graph."""

    __slots__ = ("name", "registry", "slo", "weight", "queue_rows",
                 "share_rows", "drift", "drift_tag",
                 "submitted", "completed", "shed", "errors")

    def __init__(self, name: str, registry: ModelRegistry,
                 slo: SLOTracker, weight: float = 1.0):
        self.name = name
        self.registry = registry
        self.slo = slo
        self.weight = max(float(weight), 0.0)
        self.queue_rows = 0
        self.share_rows = 0         # fair-share admission cap (rows)
        self.drift = None
        self.drift_tag: Optional[str] = None
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.errors = 0


class Server:
    """In-process serving front-end: thread-safe ``submit()``, versioned
    ``publish()``/``rollback()``, bounded queue, one dispatcher thread."""

    def __init__(self, model=None, config: Optional[ServeConfig] = None,
                 registry: Optional[ModelRegistry] = None,
                 name: str = ""):
        self.config = config or ServeConfig()
        self.name = str(name)       # replica identity in a fleet ("" solo)
        self._t_start = time.monotonic()
        self._last_wedge_unix: Optional[float] = None
        self.metrics = ServeMetrics(window=self.config.metrics_window)
        # always-on SLO burn-rate tracking (serve/slo.py): every
        # completed / shed / timed-out / failed request spends or
        # preserves error budget; GET /slo reads the evaluation
        self.slo = SLOTracker(self.config.slo)
        self.registry = registry or ModelRegistry(
            metrics=self.metrics,
            predictor_kwargs=self.config.predictor_kwargs,
            name=self.name, history=self.config.keep_versions)
        # tenant table: the default tenant "" aliases the top-level
        # registry/slo; add_tenant() grows named lineages.  Per-tenant
        # request outcomes ride one labeled counter (the obs registry's
        # cardinality cap collapses a tenant explosion into _overflow)
        self._tenants: Dict[str, _TenantState] = {
            DEFAULT_TENANT: _TenantState(DEFAULT_TENANT, self.registry,
                                         self.slo)}
        self._recompute_shares()
        self._tenant_requests = self.metrics.registry.counter(
            "serve_tenant_requests_total",
            "Per-tenant request outcomes",
            label_names=("tenant", "outcome"))
        self._tenant_queue_gauge = self.metrics.registry.gauge(
            "serve_tenant_queue_rows", "Backlogged rows per tenant",
            label_names=("tenant",))
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._queue_rows = 0
        self._closed = False
        # failure-domain state: the in-flight batch the watchdog observes
        # ((t_start, requests) or None), and the consecutive-failure
        # count feeding the circuit breaker
        self._inflight: Optional[tuple] = None
        self._consec_failures = 0
        # train/serve skew detection (obs/drift.py): built lazily per
        # ACTIVE version on the dispatcher thread, so publish/rollback/
        # breaker swaps re-anchor the detector to the new version's own
        # reference automatically; None until armed AND a version with
        # a model_reference serves a batch
        self._drift = None
        self._drift_tag: Optional[str] = None
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serve-dispatcher", daemon=True)
        # a forensic bundle dumped while this replica lives should carry
        # its per-replica metrics next to the process-wide registry
        obs_dump.add_metrics_source(f"server-{id(self):x}",
                                    self.metrics_snapshot)
        if model is not None:
            self.publish(model)
        self._dispatcher.start()
        self._watchdog: Optional[threading.Thread] = None
        if self.config.watchdog_ms > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="serve-watchdog",
                daemon=True)
            self._watchdog.start()

    # -- tenant lifecycle (ISSUE 20) -------------------------------------
    def _recompute_shares(self) -> None:
        """Fair-share admission caps: each tenant owns
        ``queue_depth_rows * weight / total_weight`` backlog rows
        (floored at one full batch so every tenant can always make
        progress).  A single-tenant server's cap equals the full queue
        depth — pre-tenancy admission behavior bit-identically."""
        depth = self.config.queue_depth_rows
        states = list(self._tenants.values())
        total_w = sum(st.weight for st in states) or 1.0
        if len(states) == 1:
            states[0].share_rows = depth
            return
        for st in states:
            st.share_rows = max(int(depth * st.weight / total_w),
                                self.config.max_batch_rows)

    def add_tenant(self, name: str, *, weight: float = 1.0,
                   slo: Optional[SLOConfig] = None,
                   predictor_kwargs: Optional[Dict[str, Any]] = None
                   ) -> "_TenantState":
        """Register a named model lineage: its own registry (named
        ``replica:tenant`` so chaos plans and warm events are tenant-
        addressable), its own SLO tracker, and a fair-share weight.
        Idempotent on re-add (weight is updated)."""
        if not name:
            raise ValueError("tenant name must be non-empty (the default "
                             "tenant exists already)")
        with self._cond:
            st = self._tenants.get(name)
            if st is not None:
                st.weight = max(float(weight), 0.0)
                self._recompute_shares()
                return st
            pk = dict(self.config.predictor_kwargs)
            pk.update(predictor_kwargs or {})
            reg = ModelRegistry(
                metrics=self.metrics, predictor_kwargs=pk,
                name=(f"{self.name}:{name}" if self.name else name),
                history=self.config.keep_versions)
            st = _TenantState(
                name, reg, SLOTracker(slo or self.config.slo),
                weight=weight)
            self._tenants[name] = st
            self._recompute_shares()
        obs_events.publish("serve.tenant_added",
                           f"tenant {name} registered",
                           tenant=name, weight=st.weight,
                           replica=self.name or "")
        return st

    def remove_tenant(self, name: str) -> None:
        """Drop a named lineage (pending requests for it fail at their
        next dispatch with UnknownTenant; queued rows are released)."""
        if not name:
            raise ValueError("cannot remove the default tenant")
        with self._cond:
            st = self._tenants.pop(name, None)
            if st is None:
                raise UnknownTenant(f"no tenant {name!r}")
            stranded = [r for r in self._queue if r.state is st]
            for r in stranded:
                self._queue.remove(r)
            self._queue_rows -= sum(r.n for r in stranded)
            self._recompute_shares()
        for r in stranded:
            r.error = UnknownTenant(f"tenant {name!r} removed")
            r.event.set()
        obs_events.publish("serve.tenant_removed",
                           f"tenant {name} dropped", tenant=name,
                           replica=self.name or "")

    def tenant_names(self) -> List[str]:
        with self._cond:
            return sorted(self._tenants)

    def _tenant_state(self, tenant: str) -> "_TenantState":
        st = self._tenants.get(tenant)
        if st is None:
            raise UnknownTenant(
                f"no tenant {tenant!r} on this server "
                f"(hosted: {sorted(self._tenants) or ['<default>']})")
        return st

    def tenant_registry(self, tenant: str = DEFAULT_TENANT
                        ) -> ModelRegistry:
        """The named tenant's registry (fleet.py's two-phase publish
        drives prepare/commit on it directly)."""
        return self._tenant_state(tenant).registry

    def _slo_record(self, st: "_TenantState", ok: bool,
                    latency_ms: Optional[float] = None,
                    trace_id: str = "") -> None:
        """Record into the tenant's SLO tracker AND the server-wide one
        (the default tenant's tracker IS the server-wide tracker — never
        double-counted)."""
        st.slo.record(ok, latency_ms=latency_ms, trace_id=trace_id)
        if st.slo is not self.slo:
            self.slo.record(ok, latency_ms=latency_ms, trace_id=trace_id)

    def _tenant_outcome(self, st: "_TenantState", outcome: str) -> None:
        self._tenant_requests.labels(
            tenant=_tenant_label(st.name), outcome=outcome).inc()

    # -- model lifecycle -------------------------------------------------
    def publish(self, model, tenant: str = DEFAULT_TENANT, **meta) -> str:
        """Prebin/stack/warm/VALIDATE the new ensemble OFF the serving
        path, then atomically swap it in (registry.py).  In-flight
        batches finish on the old version; the tag is echoed in every
        response.  A candidate that fails validation (structural, finite,
        or golden-probe — see registry.publish) raises
        ``PublishValidationError`` and never serves a single answer.
        ``tenant`` publishes into that lineage's registry — other
        tenants' active versions are untouchable by construction (their
        registries are separate objects)."""
        return self._tenant_state(tenant).registry.publish(
            model, degrade_trees=self.config.degrade_trees,
            max_batch_rows=self.config.max_batch_rows, meta=meta or None,
            probe_rows=self.config.probe_rows)

    def rollback(self, tenant: str = DEFAULT_TENANT) -> str:
        return self._tenant_state(tenant).registry.rollback()

    def version(self, tenant: str = DEFAULT_TENANT) -> Optional[str]:
        return self._tenant_state(tenant).registry.current_tag()

    # -- request path ----------------------------------------------------
    def submit(self, rows, timeout_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               tenant: str = DEFAULT_TENANT) -> ServeResult:
        """Block until the rows are scored; raises
        :class:`ServerOverloaded` (queue full), :class:`RequestTimeout`
        (deadline expired in queue), :class:`ServerClosed`, or
        :class:`UnknownTenant`.  ``trace_id`` (e.g. an inbound
        ``X-Trace-Id`` header) is carried through queue -> batch -> walk
        and echoed in the result; one is minted when absent.

        Fair-share admission: a tenant's backlog is capped at ITS share
        of the queue (``_recompute_shares``) before the global depth is
        even consulted — an overloaded tenant sheds its OWN traffic
        first, and a well-behaved tenant's admission headroom is
        untouched by a noisy neighbor."""
        st = self._tenant_state(tenant)
        mv = st.registry.current()            # raises before queueing when
        X = np.asarray(rows, np.float64)      # nothing is published yet
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.ndim != 2 or X.shape[1] != mv.num_features:
            raise ValueError(
                f"submit() rows have {X.shape[-1] if X.ndim else 0} "
                f"features; the serving model has {mv.num_features}")
        t_ms = self.config.timeout_ms if timeout_ms is None else timeout_ms
        deadline = (time.monotonic() + t_ms / 1e3) if t_ms > 0 else None
        req = _Request(X, deadline, trace_id, state=st)
        with self._cond:
            if self._closed:
                raise ServerClosed("server is shut down")
            over_share = st.queue_rows + req.n > st.share_rows
            over_depth = (self._queue_rows + req.n
                          > self.config.queue_depth_rows)
            if over_share or over_depth:
                self.metrics.on_shed()
                st.shed += 1
                self._tenant_outcome(st, "shed")
                self._slo_record(st, False, trace_id=req.trace_id)
                obs_events.publish(
                    "serve.shed",
                    ("tenant over fair share" if over_share
                     else "admission queue full"),
                    severity="warning", rows=req.n,
                    backlog=self._queue_rows,
                    tenant=_tenant_label(st.name),
                    tenant_backlog=st.queue_rows,
                    trace_id=req.trace_id)
                raise ServerOverloaded(
                    f"queue full for tenant "
                    f"{_tenant_label(st.name)!r} ({st.queue_rows} of "
                    f"{st.share_rows} fair-share rows backlogged; "
                    f"{self._queue_rows} fleet-wide, depth "
                    f"{self.config.queue_depth_rows})")
            self._queue.append(req)
            self._queue_rows += req.n
            st.queue_rows += req.n
            st.submitted += 1
            self._tenant_queue_gauge.labels(
                tenant=_tenant_label(st.name)).set(st.queue_rows)
            self.metrics.on_submit(req.n, self._queue_rows)
            self._cond.notify()
        req.event.wait()
        if req.error is not None:
            raise req.error
        assert req.result is not None
        return req.result

    def metrics_snapshot(self) -> Dict[str, Any]:
        snap = self.metrics.snapshot()
        snap["version"] = self.registry.current_tag()
        snap["versions"] = self.registry.versions()
        return snap

    def slo_snapshot(self, tenant: Optional[str] = None) -> Dict[str, Any]:
        """The ``GET /slo`` payload: burn-rate evaluation + per-bucket
        worst-tail exemplar trace ids from the latency histogram, so an
        alerting burn rate hands the operator the request ids to grep
        in an armed trace.  ``tenant`` scopes the evaluation to that
        lineage's own tracker (``GET /slo?tenant=``)."""
        if tenant is None:
            out = self.slo.snapshot()
            out["version"] = self.registry.current_tag()
            out["exemplars"] = [
                {"le": le, **ex} for le, ex in self.metrics.exemplars()]
            return out
        st = self._tenant_state(tenant)
        out = st.slo.snapshot()
        out["tenant"] = _tenant_label(st.name)
        out["version"] = st.registry.current_tag()
        return out

    def tenants_snapshot(self) -> Dict[str, Any]:
        """The ``GET /tenants`` payload: every hosted lineage's version
        lineage, fair-share position, queue occupancy, request outcomes
        and SLO alert state — the placement controller's per-replica
        signal read."""
        with self._cond:
            states = list(self._tenants.values())
        tenants = {}
        for st in states:
            ev = st.slo.evaluate()
            alerts = ev.get("alerts", {})
            burn = max(
                ev["availability"]["windows"]["fast"]["burn_rate"],
                ev["latency"]["windows"]["fast"]["burn_rate"])
            tenants[_tenant_label(st.name)] = {
                "version": st.registry.current_tag(),
                "versions": st.registry.versions(),
                "weight": st.weight,
                "share_rows": st.share_rows,
                "queue_rows": st.queue_rows,
                "occupancy": (round(st.queue_rows / st.share_rows, 4)
                              if st.share_rows else 0.0),
                "submitted": st.submitted,
                "completed": st.completed,
                "shed": st.shed,
                "errors": st.errors,
                "slo_page": bool(alerts.get("availability_page")
                                 or alerts.get("latency_page")),
                "slo_warn": bool(alerts.get("availability_warn")
                                 or alerts.get("latency_warn")),
                "burn_rate": burn,
            }
        return {"replica": self.name or "", "tenants": tenants}

    # -- train/serve skew detection (obs/drift.py) -----------------------
    def _drift_for(self, st: "_TenantState", mv: ModelVersion):
        """The tenant's active-version DriftDetector (dispatcher thread
        only): rebuilt when the served tag changes — publish, rollback
        and breaker swaps RE-ANCHOR the detector to the new version's
        own reference automatically, per tenant.  A version published
        without a ``model_reference`` disables detection until the next
        version that carries one."""
        if st.drift_tag == mv.tag:
            return st.drift
        ref = mv.meta.get("model_reference")
        det = None
        if ref is not None:
            from ..obs.drift import DriftConfig, DriftDetector

            cfg = self.config
            det = DriftDetector(
                ref,
                DriftConfig(sample_rows=cfg.drift_sample_rows,
                            per_batch_rows=cfg.drift_per_batch_rows,
                            min_rows=cfg.drift_min_rows,
                            psi_threshold=cfg.drift_psi_threshold,
                            top_k=cfg.drift_top_k,
                            psi_groups=cfg.drift_psi_groups,
                            sample_stride=cfg.drift_sample_stride),
                registry=self.metrics.registry,
                version_tag=(f"{_tenant_label(st.name)}:{mv.tag}"
                             if st.name else mv.tag))
        st.drift = det
        st.drift_tag = mv.tag
        return det

    def drift_snapshot(self, tenant: Optional[str] = None
                       ) -> Dict[str, Any]:
        """The ``GET /drift`` payload: arming state + the active
        detector's evaluation (per-feature PSI top-K, skew counters,
        score drift) — or the reason there is nothing to judge.
        ``tenant`` scopes to that lineage's own detector
        (``GET /drift?tenant=``); default = the default tenant."""
        st = self._tenant_state(DEFAULT_TENANT if tenant is None
                                else tenant)
        out: Dict[str, Any] = {
            "armed": self.config.drift_sample_rows > 0,
            "version": st.registry.current_tag(),
        }
        if tenant is not None:
            out["tenant"] = _tenant_label(st.name)
        det = st.drift
        if not out["armed"]:
            out["reason"] = "drift_sample_rows=0 (sampling off)"
        elif det is None:
            out["reason"] = ("no model_reference published yet"
                             if out["version"] is not None
                             else "no model published yet")
        else:
            out.update(det.snapshot())
        return out

    def dispatcher_alive(self) -> bool:
        return self._dispatcher.is_alive() and not self._closed

    def uptime_s(self) -> float:
        return time.monotonic() - self._t_start

    def wedged(self) -> bool:
        """True while an in-flight device batch has exceeded the
        watchdog deadline — the dispatcher thread is alive but stuck,
        the state a router must eject on even though the process
        answers health checks."""
        if self.config.watchdog_ms <= 0:
            return False
        infl = self._inflight
        return (infl is not None
                and (time.monotonic() - infl[0])
                > self.config.watchdog_ms / 1e3)

    def health(self) -> Dict[str, Any]:
        """Liveness the /healthz endpoint reports: a wedged or dead
        dispatcher and an empty registry are NOT healthy, even though
        the process is up.  ``version`` stays the ACTIVE MODEL tag (the
        pre-obs contract every client reads); ``server_version`` is the
        package build and ``uptime_s`` the replica age.

        The router's ejection decision is observable here (ISSUE 11):
        ``dispatcher_restarts`` counts watchdog-revived dispatcher
        threads, ``last_wedge_unix`` stamps the most recent
        watchdog-declared stall, and ``wedged`` flags a CURRENTLY-stuck
        in-flight batch — ``ok`` is False while wedged, so a stuck
        replica falls out of its load balancer before its queue
        backs up."""
        from .. import __version__

        alive = self.dispatcher_alive()
        wedged = self.wedged()
        tag = self.registry.current_tag()
        return {"ok": bool(alive and tag is not None and not wedged),
                "version": tag,
                "dispatcher_alive": alive, "published": tag is not None,
                "wedged": wedged,
                "dispatcher_restarts": self.metrics.value(
                    "dispatcher_restarts"),
                "last_wedge_unix": self._last_wedge_unix,
                "name": self.name,
                "server_version": __version__,
                "uptime_s": round(self.uptime_s(), 3)}

    def close(self) -> None:
        """Stop the dispatcher; pending requests fail with ServerClosed."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            pending = list(self._queue)
            self._queue.clear()
            self._queue_rows = 0
            for st in self._tenants.values():
                st.queue_rows = 0
            self._cond.notify_all()
        for req in pending:
            req.error = ServerClosed("server shut down with request queued")
            req.event.set()
        self._dispatcher.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- dispatcher ------------------------------------------------------
    def _collect_batch(self) -> Optional[List[_Request]]:
        """Deadline-aware collection: return a batch when the pending rows
        fill ``max_batch_rows`` or the oldest request's delay budget is
        spent; otherwise keep waiting on the condition.

        Batches are SINGLE-TENANT: the oldest request's tenant defines
        the batch and only that tenant's requests ride it (they share one
        model version and one SLO domain); other tenants' requests keep
        their queue order for the next collection.  A solo-tenant server
        collects exactly as before."""
        cfg = self.config
        delay_s = cfg.max_batch_delay_ms / 1e3
        with self._cond:
            while True:
                if self._closed:
                    return None
                if self._queue:
                    now = time.monotonic()
                    dispatch_at = self._queue[0].t_enq + delay_s
                    if (self._queue_rows >= cfg.max_batch_rows
                            or now >= dispatch_at):
                        st = self._queue[0].state
                        batch: List[_Request] = []
                        keep: deque = deque()
                        rows = 0
                        while self._queue:
                            r = self._queue.popleft()
                            if r.state is st and (
                                    not batch
                                    or rows + r.n <= cfg.max_batch_rows):
                                batch.append(r)
                                rows += r.n
                            else:
                                keep.append(r)
                        self._queue = keep
                        self._queue_rows -= rows
                        if st is not None:
                            st.queue_rows = max(st.queue_rows - rows, 0)
                            self._tenant_queue_gauge.labels(
                                tenant=_tenant_label(st.name)).set(
                                    st.queue_rows)
                        return batch
                    self._cond.wait(dispatch_at - now)
                else:
                    self._cond.wait(0.1)

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._collect_batch()
            if batch is None:
                return
            try:
                self._run_batch(batch)
                self._consec_failures = 0
            except faults.ThreadKilled as e:
                # injected dispatcher death: fail this batch's requests
                # and let the thread die — the watchdog notices the
                # corpse and restarts (the recovery under test)
                self._fail_batch(batch, DispatcherDied(str(e)))
                log_warning("serve: dispatcher thread died "
                            f"({e}); watchdog will restart")
                return
            except BaseException as e:  # noqa: BLE001 — a poisoned batch
                # must fail ITS requests, never kill the dispatcher.
                # Breaker accounting runs BEFORE the requests are woken:
                # a client that saw its submit fail must also see the
                # breaker state that failure produced (the old order
                # raced clients against the trip)
                self._consec_failures += 1
                self._maybe_trip_breaker(
                    batch[0].state if batch else None)
                self._fail_batch(batch, e)
                log_warning(f"serve: batch failed after retries "
                            f"({type(e).__name__}: {e})")

    def _fail_batch(self, batch: List[_Request], err: BaseException) -> None:
        n_failed = 0
        for req in batch:
            if not req.event.is_set():
                self.metrics.on_error()
                st = req.state or self._tenants[DEFAULT_TENANT]
                st.errors += 1
                self._tenant_outcome(st, "error")
                self._slo_record(st, False, trace_id=req.trace_id)
                req.error = (err if isinstance(err, Exception)
                             else ServeError(str(err)))
                req.event.set()
                n_failed += 1
        if n_failed:
            obs_events.publish(
                "serve.batch_failed",
                f"{type(err).__name__}: {err}", severity="error",
                requests=n_failed)

    def _maybe_trip_breaker(self, st: Optional["_TenantState"] = None
                            ) -> None:
        """Circuit breaker: ``breaker_failures`` CONSECUTIVE failed
        batches auto-roll the registry back to the previous version — a
        bad publish that slipped past validation (or a version whose
        executables started failing) un-ships itself instead of failing
        every batch forever.  Batches are single-tenant, so the
        rollback targets the FAILING tenant's registry — a bad tenant
        publish un-ships itself without touching its neighbors."""
        bf = self.config.breaker_failures
        if bf <= 0 or self._consec_failures < bf:
            return
        self._consec_failures = 0
        registry = (st or self._tenants[DEFAULT_TENANT]).registry
        try:
            tag = registry.rollback()
        except Exception as e:  # noqa: BLE001 — nothing to roll back to
            obs_events.publish(
                "serve.breaker_trip", "no previous version to roll "
                "back to", severity="error", failures=bf)
            log_warning(f"serve: circuit breaker tripped with no "
                        f"previous version to roll back to ({e})")
            return
        self.metrics.on_breaker()
        obs_events.publish(
            "serve.breaker_trip", f"auto-rolled back to {tag}",
            severity="error", failures=bf, rolled_back_to=tag)
        log_warning(f"serve: circuit breaker tripped after {bf} "
                    f"consecutive batch failures — rolled back to {tag}")

    def _predict_with_retry(self, bp, X: np.ndarray) -> np.ndarray:
        """Bounded retry with exponential backoff around the device
        batch: transient errors (a failed H2D, a flaky dispatch) are
        retried ``retry_max`` times before the batch is failed."""
        cfg = self.config
        attempt = 0
        while True:
            try:
                # chaos seam: injected dispatch faults land inside the
                # retried region, exactly like a real transient error
                faults.fire("dispatch", site="batch")
                return np.asarray(bp.predict_raw(
                    X, f64_exact=cfg.f64_scores))
            except faults.ThreadKilled:
                raise
            except Exception as e:  # noqa: BLE001
                if attempt >= cfg.retry_max:
                    raise
                attempt += 1
                self.metrics.on_retry()
                log_warning(f"serve: batch attempt {attempt} failed "
                            f"({type(e).__name__}: {e}); retrying")
                time.sleep(cfg.retry_backoff_ms * (2 ** (attempt - 1))
                           / 1e3)

    def _run_batch(self, batch: List[_Request]) -> None:
        now = time.monotonic()
        st = (batch[0].state if batch and batch[0].state is not None
              else self._tenants[DEFAULT_TENANT])
        live: List[_Request] = []
        for req in batch:
            if req.deadline is not None and now > req.deadline:
                self.metrics.on_timeout()
                self._tenant_outcome(st, "timeout")
                self._slo_record(st, False, trace_id=req.trace_id)
                req.error = RequestTimeout(
                    f"deadline expired after "
                    f"{(now - req.t_enq) * 1e3:.1f} ms in queue")
                req.event.set()
            else:
                live.append(req)
        if not live:
            return
        mv: ModelVersion = st.registry.current()
        with self._cond:
            backlog = self._queue_rows
        degraded = (mv.degraded is not None
                    and backlog >= self.config.degrade_queue_frac
                    * self.config.queue_depth_rows)
        bp = mv.degraded if degraded else mv.predictor
        X = (live[0].rows if len(live) == 1
             else np.concatenate([r.rows for r in live], axis=0))
        n = X.shape[0]
        t_collect = time.monotonic()
        walk_t0_ns = trace.now_ns() if trace.enabled() else 0
        self._inflight = (time.monotonic(), live)
        try:
            # chaos seam: replica_wedge stalls THIS replica's dispatcher
            # with the batch in flight — the watchdog (and the router's
            # health checks) see exactly what a stuck device produces
            faults.fire("replica_wedge", site=self.name or "server")
            out = self._predict_with_retry(bp, X)
        finally:
            self._inflight = None
        self.metrics.on_batch(n, bp.bucket_for(n), backlog)
        if self.config.drift_sample_rows > 0:
            # armed skew sampling (one strided row copy per batch; the
            # <= 2% armed-overhead contract is measured by bench.py
            # measure_drift); disarmed cost is this one compare
            det = self._drift_for(st, mv)
            if det is not None:
                try:
                    det.offer(X, np.asarray(out))
                except Exception as e:  # noqa: BLE001 — telemetry must
                    log_warning(f"serve: drift sampling failed "
                                f"({type(e).__name__}: {e})")  # never
                    st.drift = None                            # fail a
                    st.drift_tag = mv.tag                      # batch
        done = time.monotonic()
        walk_ms = (done - t_collect) * 1e3
        if trace.enabled():
            # one batch span + per-request queue/walk spans, every one
            # carrying its propagated trace id — a p999 outlier in the
            # export decomposes by grepping its X-Trace-Id
            walk_dur_ns = trace.now_ns() - walk_t0_ns
            trace.add_span("serve.batch", walk_t0_ns, walk_dur_ns,
                           cat="serve",
                           args={"rows": n, "version": mv.tag,
                                 "degraded": degraded,
                                 "requests": len(live)})
            for req in live:
                q_ns = int(max(t_collect - req.t_enq, 0.0) * 1e9)
                trace.add_span("serve.queue", walk_t0_ns - q_ns, q_ns,
                               cat="serve",
                               args={"trace_id": req.trace_id})
                trace.add_span("serve.walk", walk_t0_ns, walk_dur_ns,
                               cat="serve",
                               args={"trace_id": req.trace_id,
                                     "batch_rows": n})
        lo = 0
        for req in live:
            vals = out[lo: lo + req.n]
            lo += req.n
            if req.event.is_set():
                # the watchdog already failed this request (stalled
                # batch): its client is gone — never double-complete
                continue
            lat_ms = (done - req.t_enq) * 1e3
            req.result = ServeResult(
                values=vals, version=mv.tag, latency_ms=lat_ms,
                degraded=degraded, batch_rows=n, trace_id=req.trace_id,
                queue_ms=max((t_collect - req.t_enq) * 1e3, 0.0),
                walk_ms=walk_ms)
            self.metrics.on_complete(lat_ms, degraded,
                                     trace_id=req.trace_id)
            st.completed += 1
            self._tenant_outcome(st, "ok")
            self._slo_record(st, True, latency_ms=lat_ms,
                             trace_id=req.trace_id)
            req.event.set()

    # -- watchdog --------------------------------------------------------
    def _watchdog_loop(self) -> None:
        """Detects the two ways a dispatcher hangs the queue: a STALLED
        in-flight batch (device wedged — its requests fail with 503
        instead of blocking their clients forever) and a DEAD dispatcher
        thread (restarted, stranded requests failed)."""
        limit_s = self.config.watchdog_ms / 1e3
        period = max(limit_s / 4.0, 0.005)
        while True:
            time.sleep(period)
            if self._closed:
                return
            infl = self._inflight
            if infl is not None:
                t_start, live = infl
                if time.monotonic() - t_start > limit_s:
                    n_failed = 0
                    for req in live:
                        if not req.event.is_set():
                            req.error = DispatcherStalled(
                                f"device batch exceeded the "
                                f"{self.config.watchdog_ms:.0f} ms "
                                "watchdog deadline")
                            req.event.set()
                            self._slo_record(
                                req.state
                                or self._tenants[DEFAULT_TENANT],
                                False, trace_id=req.trace_id)
                            n_failed += 1
                    if n_failed:
                        self._last_wedge_unix = time.time()
                        self.metrics.on_watchdog(n_failed)
                        obs_events.publish(
                            "serve.watchdog_stall",
                            f"stalled batch failed {n_failed} "
                            "request(s)", severity="error",
                            requests=n_failed,
                            watchdog_ms=self.config.watchdog_ms)
                        # a wedged device batch is a crash-grade moment:
                        # give the armed flight recorder its dump (the
                        # process survives, the evidence must too)
                        obs_dump.dump(
                            "watchdog_stall",
                            error=f"device batch exceeded "
                                  f"{self.config.watchdog_ms:.0f} ms")
                        log_warning(
                            f"serve: watchdog failed {n_failed} "
                            "request(s) of a stalled batch")
            if not self._dispatcher.is_alive() and not self._closed:
                obs_events.publish(
                    "serve.dispatcher_restart",
                    "dispatcher thread dead — restarting",
                    severity="error")
                log_warning("serve: dispatcher thread dead — restarting")
                self.metrics.on_dispatcher_restart()
                self._dispatcher = threading.Thread(
                    target=self._dispatch_loop, name="serve-dispatcher",
                    daemon=True)
                self._dispatcher.start()


def serve_config_from(config) -> ServeConfig:
    """Map the global Config's ``serve_*`` knobs onto a
    :class:`ServeConfig` (shared by the single-server and fleet CLI
    paths)."""
    return ServeConfig(
        max_batch_rows=config.serve_max_batch_rows,
        max_batch_delay_ms=config.serve_max_batch_delay_ms,
        queue_depth_rows=config.serve_queue_depth,
        timeout_ms=config.serve_timeout_ms,
        degrade_trees=config.serve_degrade_trees,
        f64_scores=config.predict_f64_scores,
        drift_sample_rows=config.drift_sample_rows,
        drift_per_batch_rows=config.drift_per_batch_rows,
        drift_min_rows=config.drift_min_rows,
        drift_psi_threshold=config.drift_psi_threshold,
        drift_top_k=config.drift_top_k,
        drift_psi_groups=config.drift_psi_groups,
        drift_sample_stride=config.drift_sample_stride,
        retry_max=config.serve_retry_max,
        retry_backoff_ms=config.serve_retry_backoff_ms,
        breaker_failures=config.serve_breaker_failures,
        watchdog_ms=config.serve_watchdog_ms,
        probe_rows=config.serve_probe_rows,
        keep_versions=config.registry_keep_versions,
        slo=SLOConfig(
            availability_target=config.serve_slo_availability_target,
            latency_ms=config.serve_slo_latency_ms,
            latency_target=config.serve_slo_latency_target,
            fast_window_s=config.serve_slo_fast_window_s,
            slow_window_s=config.serve_slo_slow_window_s,
        ),
        predictor_kwargs={
            "bucket_min": config.predict_bucket_min,
            "cache_entries": config.predict_cache_entries,
            **({"method": config.predict_method}
               if config.predict_method in ("depthwise", "pallas",
                                            "fused", "scan") else {}),
            "code_layout": config.predict_code_layout,
        },
    )


def build_server(booster, config) -> Server:
    """CLI glue: a :class:`Server` from a Booster + the global Config's
    ``serve_*`` knobs (cli.py task=serve)."""
    sc = serve_config_from(config)
    server = Server(booster, config=sc)
    log_info(f"serve: model {server.version()} online "
             f"({booster.num_trees()} trees, "
             f"batch<= {sc.max_batch_rows} rows, "
             f"delay {sc.max_batch_delay_ms} ms, "
             f"queue {sc.queue_depth_rows} rows)")
    return server
