"""Deadline-aware micro-batching server over the batched inference engine.

The reference's Predictor is an offline application: OMP threads walk a
file of rows as fast as the cores allow (predictor.hpp:29-160).  Online
traffic inverts the problem — requests arrive one at a time from many
clients, and the device engine (models/predict.py) only earns its keep
when rows are batched into its power-of-two compile buckets.  The piece
in between is this module's micro-batcher, and its one policy knob is
explicit: a batch dispatches when it FILLS (``max_batch_rows``, device
occupancy wins) or when its OLDEST request has waited
``max_batch_delay_ms`` (p99 latency wins) — the classic occupancy/latency
trade made visible instead of emergent.

Admission control is a bounded queue priced in ROWS: a submit that would
push the backlog past ``queue_depth_rows`` is shed immediately with
:class:`ServerOverloaded` (the caller knows NOW, instead of everyone
queueing into an OOM).  Under a configured backlog fraction the dispatcher
degrades to the version's truncated-tree predictor (fewer trees =
strictly less walk work per row) and flags the response ``degraded`` —
cheaper answers beat failed answers during an overload spike.

All device work happens on the single dispatcher thread;
``Server.submit()`` is thread-safe and blocks the calling thread until
its rows come back.  Every response echoes the model-version tag that
computed it (see registry.py for the hot-swap contract).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import dump as obs_dump
from ..obs import events as obs_events
from ..obs import trace
from ..utils import faults
from ..utils.log import log_info, log_warning
from .metrics import ServeMetrics
from .registry import ModelRegistry, ModelVersion
from .slo import SLOConfig, SLOTracker


class ServeError(RuntimeError):
    """Base class of the serving-path failures."""


class ServerOverloaded(ServeError):
    """Admission control shed this request (bounded queue was full)."""


class RequestTimeout(ServeError):
    """The request's deadline expired while it sat in the queue."""


class ServerClosed(ServeError):
    """The server is shut down; no further requests are accepted."""


class DispatcherStalled(ServeError):
    """The watchdog declared the in-flight device batch stalled (or the
    dispatcher thread dead) and failed this request instead of letting
    it hang the queue.  HTTP maps it to 503 — the client should retry
    against another replica."""


class DispatcherDied(ServeError):
    """The dispatcher thread exited with this request in flight; the
    watchdog restarts the dispatcher and fails the stranded requests."""


@dataclass
class ServeConfig:
    """Serving policy knobs (mirrored by the ``serve_*`` names in
    config.py for the CLI path; defaults match)."""

    max_batch_rows: int = 1024          # bucket to fill before dispatch
    max_batch_delay_ms: float = 2.0     # oldest-request deadline budget
    queue_depth_rows: int = 4096        # admission bound (rows, not reqs)
    timeout_ms: float = 0.0             # per-request timeout; 0 = off
    degrade_trees: int = 0              # truncated-tree overload predictor
    degrade_queue_frac: float = 0.5     # backlog fraction that triggers it
    f64_scores: bool = False            # exact f64 reconstruction per batch
    metrics_window: int = 8192
    # -- failure domains (PR 6) ----------------------------------------
    retry_max: int = 2                  # transient batch errors retried
    retry_backoff_ms: float = 5.0       # exponential base between attempts
    breaker_failures: int = 3           # consecutive failed batches that
                                        # auto-roll back a bad publish
                                        # (0 = breaker off)
    watchdog_ms: float = 0.0            # stalled-batch deadline; 0 = off
    probe_rows: int = 64                # publish golden-probe batch size
                                        # (0 = structural checks only)
    # -- SLOs (serve/slo.py): always-on burn-rate tracking ---------------
    slo: Optional[SLOConfig] = None     # None = default SLOConfig()
    # -- train/serve skew detection (ISSUE 14; obs/drift.py) -------------
    # HARD-OFF default: drift_sample_rows=0 keeps the serving path at
    # one integer compare.  Armed, the dispatcher copies at most
    # drift_per_batch_rows rows per device batch into a bounded ring;
    # GET /drift re-bins the window through the active version's own
    # mappers (ModelVersion.meta["model_reference"]) and judges PSI
    drift_sample_rows: int = 0
    drift_per_batch_rows: int = 64
    drift_min_rows: int = 256
    drift_psi_threshold: float = 0.25
    drift_top_k: int = 8
    drift_psi_groups: int = 16
    drift_sample_stride: int = 4    # sample every Nth device batch
    predictor_kwargs: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.max_batch_rows = max(int(self.max_batch_rows), 1)
        self.max_batch_delay_ms = max(float(self.max_batch_delay_ms), 0.0)
        self.queue_depth_rows = max(int(self.queue_depth_rows),
                                    self.max_batch_rows)
        self.timeout_ms = max(float(self.timeout_ms), 0.0)
        self.degrade_trees = max(int(self.degrade_trees), 0)
        self.degrade_queue_frac = min(max(
            float(self.degrade_queue_frac), 0.0), 1.0)
        self.retry_max = max(int(self.retry_max), 0)
        self.retry_backoff_ms = max(float(self.retry_backoff_ms), 0.0)
        self.breaker_failures = max(int(self.breaker_failures), 0)
        self.watchdog_ms = max(float(self.watchdog_ms), 0.0)
        self.probe_rows = max(int(self.probe_rows), 0)
        self.drift_sample_rows = max(int(self.drift_sample_rows), 0)
        self.drift_per_batch_rows = max(int(self.drift_per_batch_rows), 1)
        self.drift_min_rows = max(int(self.drift_min_rows), 1)
        self.drift_psi_threshold = max(float(self.drift_psi_threshold),
                                       1e-9)
        self.drift_top_k = max(int(self.drift_top_k), 1)
        self.drift_psi_groups = max(int(self.drift_psi_groups), 2)
        self.drift_sample_stride = max(int(self.drift_sample_stride), 1)
        if self.slo is None:
            self.slo = SLOConfig()


@dataclass
class ServeResult:
    """One completed request: raw scores plus the serving provenance."""

    values: np.ndarray          # (n, K) raw scores
    version: str                # model-version tag that computed them
    latency_ms: float
    degraded: bool = False
    batch_rows: int = 0         # rows in the device batch that carried it
    trace_id: str = ""          # propagated end-to-end (X-Trace-Id)
    queue_ms: float = 0.0       # enqueue -> batch collected
    walk_ms: float = 0.0        # device predict leg of the carrying batch


class _Request:
    __slots__ = ("rows", "n", "t_enq", "deadline", "event", "result",
                 "error", "trace_id")

    def __init__(self, rows: np.ndarray, deadline: Optional[float],
                 trace_id: Optional[str] = None):
        self.rows = rows
        self.n = rows.shape[0]
        self.t_enq = time.monotonic()
        self.deadline = deadline
        self.event = threading.Event()
        self.result: Optional[ServeResult] = None
        self.error: Optional[BaseException] = None
        # every request carries a trace id whether or not the tracer is
        # armed — the X-Trace-Id echo and the latency decomposition in
        # ServeResult are always-on; only SPAN RECORDING is gated
        self.trace_id = trace_id or trace.new_trace_id()


class Server:
    """In-process serving front-end: thread-safe ``submit()``, versioned
    ``publish()``/``rollback()``, bounded queue, one dispatcher thread."""

    def __init__(self, model=None, config: Optional[ServeConfig] = None,
                 registry: Optional[ModelRegistry] = None,
                 name: str = ""):
        self.config = config or ServeConfig()
        self.name = str(name)       # replica identity in a fleet ("" solo)
        self._t_start = time.monotonic()
        self._last_wedge_unix: Optional[float] = None
        self.metrics = ServeMetrics(window=self.config.metrics_window)
        # always-on SLO burn-rate tracking (serve/slo.py): every
        # completed / shed / timed-out / failed request spends or
        # preserves error budget; GET /slo reads the evaluation
        self.slo = SLOTracker(self.config.slo)
        self.registry = registry or ModelRegistry(
            metrics=self.metrics,
            predictor_kwargs=self.config.predictor_kwargs,
            name=self.name)
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._queue_rows = 0
        self._closed = False
        # failure-domain state: the in-flight batch the watchdog observes
        # ((t_start, requests) or None), and the consecutive-failure
        # count feeding the circuit breaker
        self._inflight: Optional[tuple] = None
        self._consec_failures = 0
        # train/serve skew detection (obs/drift.py): built lazily per
        # ACTIVE version on the dispatcher thread, so publish/rollback/
        # breaker swaps re-anchor the detector to the new version's own
        # reference automatically; None until armed AND a version with
        # a model_reference serves a batch
        self._drift = None
        self._drift_tag: Optional[str] = None
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serve-dispatcher", daemon=True)
        # a forensic bundle dumped while this replica lives should carry
        # its per-replica metrics next to the process-wide registry
        obs_dump.add_metrics_source(f"server-{id(self):x}",
                                    self.metrics_snapshot)
        if model is not None:
            self.publish(model)
        self._dispatcher.start()
        self._watchdog: Optional[threading.Thread] = None
        if self.config.watchdog_ms > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="serve-watchdog",
                daemon=True)
            self._watchdog.start()

    # -- model lifecycle -------------------------------------------------
    def publish(self, model, **meta) -> str:
        """Prebin/stack/warm/VALIDATE the new ensemble OFF the serving
        path, then atomically swap it in (registry.py).  In-flight
        batches finish on the old version; the tag is echoed in every
        response.  A candidate that fails validation (structural, finite,
        or golden-probe — see registry.publish) raises
        ``PublishValidationError`` and never serves a single answer."""
        return self.registry.publish(
            model, degrade_trees=self.config.degrade_trees,
            max_batch_rows=self.config.max_batch_rows, meta=meta or None,
            probe_rows=self.config.probe_rows)

    def rollback(self) -> str:
        return self.registry.rollback()

    def version(self) -> Optional[str]:
        return self.registry.current_tag()

    # -- request path ----------------------------------------------------
    def submit(self, rows, timeout_ms: Optional[float] = None,
               trace_id: Optional[str] = None) -> ServeResult:
        """Block until the rows are scored; raises
        :class:`ServerOverloaded` (queue full), :class:`RequestTimeout`
        (deadline expired in queue) or :class:`ServerClosed`.
        ``trace_id`` (e.g. an inbound ``X-Trace-Id`` header) is carried
        through queue -> batch -> walk and echoed in the result; one is
        minted when absent."""
        mv = self.registry.current()          # raises before queueing when
        X = np.asarray(rows, np.float64)      # nothing is published yet
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.ndim != 2 or X.shape[1] != mv.num_features:
            raise ValueError(
                f"submit() rows have {X.shape[-1] if X.ndim else 0} "
                f"features; the serving model has {mv.num_features}")
        t_ms = self.config.timeout_ms if timeout_ms is None else timeout_ms
        deadline = (time.monotonic() + t_ms / 1e3) if t_ms > 0 else None
        req = _Request(X, deadline, trace_id)
        with self._cond:
            if self._closed:
                raise ServerClosed("server is shut down")
            if self._queue_rows + req.n > self.config.queue_depth_rows:
                self.metrics.on_shed()
                self.slo.record(False, trace_id=req.trace_id)
                obs_events.publish(
                    "serve.shed", "admission queue full",
                    severity="warning", rows=req.n,
                    backlog=self._queue_rows, trace_id=req.trace_id)
                raise ServerOverloaded(
                    f"queue full ({self._queue_rows} rows backlogged, "
                    f"depth {self.config.queue_depth_rows})")
            self._queue.append(req)
            self._queue_rows += req.n
            self.metrics.on_submit(req.n, self._queue_rows)
            self._cond.notify()
        req.event.wait()
        if req.error is not None:
            raise req.error
        assert req.result is not None
        return req.result

    def metrics_snapshot(self) -> Dict[str, Any]:
        snap = self.metrics.snapshot()
        snap["version"] = self.registry.current_tag()
        snap["versions"] = self.registry.versions()
        return snap

    def slo_snapshot(self) -> Dict[str, Any]:
        """The ``GET /slo`` payload: burn-rate evaluation + per-bucket
        worst-tail exemplar trace ids from the latency histogram, so an
        alerting burn rate hands the operator the request ids to grep
        in an armed trace."""
        out = self.slo.snapshot()
        out["version"] = self.registry.current_tag()
        out["exemplars"] = [
            {"le": le, **ex} for le, ex in self.metrics.exemplars()]
        return out

    # -- train/serve skew detection (obs/drift.py) -----------------------
    def _drift_for(self, mv: ModelVersion):
        """The active version's DriftDetector (dispatcher thread only):
        rebuilt when the served tag changes, shared otherwise.  A
        version published without a ``model_reference`` disables
        detection until the next version that carries one."""
        if self._drift_tag == mv.tag:
            return self._drift
        ref = mv.meta.get("model_reference")
        det = None
        if ref is not None:
            from ..obs.drift import DriftConfig, DriftDetector

            cfg = self.config
            det = DriftDetector(
                ref,
                DriftConfig(sample_rows=cfg.drift_sample_rows,
                            per_batch_rows=cfg.drift_per_batch_rows,
                            min_rows=cfg.drift_min_rows,
                            psi_threshold=cfg.drift_psi_threshold,
                            top_k=cfg.drift_top_k,
                            psi_groups=cfg.drift_psi_groups,
                            sample_stride=cfg.drift_sample_stride),
                registry=self.metrics.registry, version_tag=mv.tag)
        self._drift = det
        self._drift_tag = mv.tag
        return det

    def drift_snapshot(self) -> Dict[str, Any]:
        """The ``GET /drift`` payload: arming state + the active
        detector's evaluation (per-feature PSI top-K, skew counters,
        score drift) — or the reason there is nothing to judge."""
        out: Dict[str, Any] = {
            "armed": self.config.drift_sample_rows > 0,
            "version": self.registry.current_tag(),
        }
        det = self._drift
        if not out["armed"]:
            out["reason"] = "drift_sample_rows=0 (sampling off)"
        elif det is None:
            out["reason"] = ("no model_reference published yet"
                             if out["version"] is not None
                             else "no model published yet")
        else:
            out.update(det.snapshot())
        return out

    def dispatcher_alive(self) -> bool:
        return self._dispatcher.is_alive() and not self._closed

    def uptime_s(self) -> float:
        return time.monotonic() - self._t_start

    def wedged(self) -> bool:
        """True while an in-flight device batch has exceeded the
        watchdog deadline — the dispatcher thread is alive but stuck,
        the state a router must eject on even though the process
        answers health checks."""
        if self.config.watchdog_ms <= 0:
            return False
        infl = self._inflight
        return (infl is not None
                and (time.monotonic() - infl[0])
                > self.config.watchdog_ms / 1e3)

    def health(self) -> Dict[str, Any]:
        """Liveness the /healthz endpoint reports: a wedged or dead
        dispatcher and an empty registry are NOT healthy, even though
        the process is up.  ``version`` stays the ACTIVE MODEL tag (the
        pre-obs contract every client reads); ``server_version`` is the
        package build and ``uptime_s`` the replica age.

        The router's ejection decision is observable here (ISSUE 11):
        ``dispatcher_restarts`` counts watchdog-revived dispatcher
        threads, ``last_wedge_unix`` stamps the most recent
        watchdog-declared stall, and ``wedged`` flags a CURRENTLY-stuck
        in-flight batch — ``ok`` is False while wedged, so a stuck
        replica falls out of its load balancer before its queue
        backs up."""
        from .. import __version__

        alive = self.dispatcher_alive()
        wedged = self.wedged()
        tag = self.registry.current_tag()
        return {"ok": bool(alive and tag is not None and not wedged),
                "version": tag,
                "dispatcher_alive": alive, "published": tag is not None,
                "wedged": wedged,
                "dispatcher_restarts": self.metrics.value(
                    "dispatcher_restarts"),
                "last_wedge_unix": self._last_wedge_unix,
                "name": self.name,
                "server_version": __version__,
                "uptime_s": round(self.uptime_s(), 3)}

    def close(self) -> None:
        """Stop the dispatcher; pending requests fail with ServerClosed."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            pending = list(self._queue)
            self._queue.clear()
            self._queue_rows = 0
            self._cond.notify_all()
        for req in pending:
            req.error = ServerClosed("server shut down with request queued")
            req.event.set()
        self._dispatcher.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- dispatcher ------------------------------------------------------
    def _collect_batch(self) -> Optional[List[_Request]]:
        """Deadline-aware collection: return a batch when the pending rows
        fill ``max_batch_rows`` or the oldest request's delay budget is
        spent; otherwise keep waiting on the condition."""
        cfg = self.config
        delay_s = cfg.max_batch_delay_ms / 1e3
        with self._cond:
            while True:
                if self._closed:
                    return None
                if self._queue:
                    now = time.monotonic()
                    dispatch_at = self._queue[0].t_enq + delay_s
                    if (self._queue_rows >= cfg.max_batch_rows
                            or now >= dispatch_at):
                        batch: List[_Request] = []
                        rows = 0
                        while self._queue and (
                                not batch
                                or rows + self._queue[0].n
                                <= cfg.max_batch_rows):
                            r = self._queue.popleft()
                            batch.append(r)
                            rows += r.n
                        self._queue_rows -= rows
                        return batch
                    self._cond.wait(dispatch_at - now)
                else:
                    self._cond.wait(0.1)

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._collect_batch()
            if batch is None:
                return
            try:
                self._run_batch(batch)
                self._consec_failures = 0
            except faults.ThreadKilled as e:
                # injected dispatcher death: fail this batch's requests
                # and let the thread die — the watchdog notices the
                # corpse and restarts (the recovery under test)
                self._fail_batch(batch, DispatcherDied(str(e)))
                log_warning("serve: dispatcher thread died "
                            f"({e}); watchdog will restart")
                return
            except BaseException as e:  # noqa: BLE001 — a poisoned batch
                # must fail ITS requests, never kill the dispatcher.
                # Breaker accounting runs BEFORE the requests are woken:
                # a client that saw its submit fail must also see the
                # breaker state that failure produced (the old order
                # raced clients against the trip)
                self._consec_failures += 1
                self._maybe_trip_breaker()
                self._fail_batch(batch, e)
                log_warning(f"serve: batch failed after retries "
                            f"({type(e).__name__}: {e})")

    def _fail_batch(self, batch: List[_Request], err: BaseException) -> None:
        n_failed = 0
        for req in batch:
            if not req.event.is_set():
                self.metrics.on_error()
                self.slo.record(False, trace_id=req.trace_id)
                req.error = (err if isinstance(err, Exception)
                             else ServeError(str(err)))
                req.event.set()
                n_failed += 1
        if n_failed:
            obs_events.publish(
                "serve.batch_failed",
                f"{type(err).__name__}: {err}", severity="error",
                requests=n_failed)

    def _maybe_trip_breaker(self) -> None:
        """Circuit breaker: ``breaker_failures`` CONSECUTIVE failed
        batches auto-roll the registry back to the previous version — a
        bad publish that slipped past validation (or a version whose
        executables started failing) un-ships itself instead of failing
        every batch forever."""
        bf = self.config.breaker_failures
        if bf <= 0 or self._consec_failures < bf:
            return
        self._consec_failures = 0
        try:
            tag = self.registry.rollback()
        except Exception as e:  # noqa: BLE001 — nothing to roll back to
            obs_events.publish(
                "serve.breaker_trip", "no previous version to roll "
                "back to", severity="error", failures=bf)
            log_warning(f"serve: circuit breaker tripped with no "
                        f"previous version to roll back to ({e})")
            return
        self.metrics.on_breaker()
        obs_events.publish(
            "serve.breaker_trip", f"auto-rolled back to {tag}",
            severity="error", failures=bf, rolled_back_to=tag)
        log_warning(f"serve: circuit breaker tripped after {bf} "
                    f"consecutive batch failures — rolled back to {tag}")

    def _predict_with_retry(self, bp, X: np.ndarray) -> np.ndarray:
        """Bounded retry with exponential backoff around the device
        batch: transient errors (a failed H2D, a flaky dispatch) are
        retried ``retry_max`` times before the batch is failed."""
        cfg = self.config
        attempt = 0
        while True:
            try:
                # chaos seam: injected dispatch faults land inside the
                # retried region, exactly like a real transient error
                faults.fire("dispatch", site="batch")
                return np.asarray(bp.predict_raw(
                    X, f64_exact=cfg.f64_scores))
            except faults.ThreadKilled:
                raise
            except Exception as e:  # noqa: BLE001
                if attempt >= cfg.retry_max:
                    raise
                attempt += 1
                self.metrics.on_retry()
                log_warning(f"serve: batch attempt {attempt} failed "
                            f"({type(e).__name__}: {e}); retrying")
                time.sleep(cfg.retry_backoff_ms * (2 ** (attempt - 1))
                           / 1e3)

    def _run_batch(self, batch: List[_Request]) -> None:
        now = time.monotonic()
        live: List[_Request] = []
        for req in batch:
            if req.deadline is not None and now > req.deadline:
                self.metrics.on_timeout()
                self.slo.record(False, trace_id=req.trace_id)
                req.error = RequestTimeout(
                    f"deadline expired after "
                    f"{(now - req.t_enq) * 1e3:.1f} ms in queue")
                req.event.set()
            else:
                live.append(req)
        if not live:
            return
        mv: ModelVersion = self.registry.current()
        with self._cond:
            backlog = self._queue_rows
        degraded = (mv.degraded is not None
                    and backlog >= self.config.degrade_queue_frac
                    * self.config.queue_depth_rows)
        bp = mv.degraded if degraded else mv.predictor
        X = (live[0].rows if len(live) == 1
             else np.concatenate([r.rows for r in live], axis=0))
        n = X.shape[0]
        t_collect = time.monotonic()
        walk_t0_ns = trace.now_ns() if trace.enabled() else 0
        self._inflight = (time.monotonic(), live)
        try:
            # chaos seam: replica_wedge stalls THIS replica's dispatcher
            # with the batch in flight — the watchdog (and the router's
            # health checks) see exactly what a stuck device produces
            faults.fire("replica_wedge", site=self.name or "server")
            out = self._predict_with_retry(bp, X)
        finally:
            self._inflight = None
        self.metrics.on_batch(n, bp.bucket_for(n), backlog)
        if self.config.drift_sample_rows > 0:
            # armed skew sampling (one strided row copy per batch; the
            # <= 2% armed-overhead contract is measured by bench.py
            # measure_drift); disarmed cost is this one compare
            det = self._drift_for(mv)
            if det is not None:
                try:
                    det.offer(X, np.asarray(out))
                except Exception as e:  # noqa: BLE001 — telemetry must
                    log_warning(f"serve: drift sampling failed "
                                f"({type(e).__name__}: {e})")  # never
                    self._drift = None                         # fail a
                    self._drift_tag = mv.tag                   # batch
        done = time.monotonic()
        walk_ms = (done - t_collect) * 1e3
        if trace.enabled():
            # one batch span + per-request queue/walk spans, every one
            # carrying its propagated trace id — a p999 outlier in the
            # export decomposes by grepping its X-Trace-Id
            walk_dur_ns = trace.now_ns() - walk_t0_ns
            trace.add_span("serve.batch", walk_t0_ns, walk_dur_ns,
                           cat="serve",
                           args={"rows": n, "version": mv.tag,
                                 "degraded": degraded,
                                 "requests": len(live)})
            for req in live:
                q_ns = int(max(t_collect - req.t_enq, 0.0) * 1e9)
                trace.add_span("serve.queue", walk_t0_ns - q_ns, q_ns,
                               cat="serve",
                               args={"trace_id": req.trace_id})
                trace.add_span("serve.walk", walk_t0_ns, walk_dur_ns,
                               cat="serve",
                               args={"trace_id": req.trace_id,
                                     "batch_rows": n})
        lo = 0
        for req in live:
            vals = out[lo: lo + req.n]
            lo += req.n
            if req.event.is_set():
                # the watchdog already failed this request (stalled
                # batch): its client is gone — never double-complete
                continue
            lat_ms = (done - req.t_enq) * 1e3
            req.result = ServeResult(
                values=vals, version=mv.tag, latency_ms=lat_ms,
                degraded=degraded, batch_rows=n, trace_id=req.trace_id,
                queue_ms=max((t_collect - req.t_enq) * 1e3, 0.0),
                walk_ms=walk_ms)
            self.metrics.on_complete(lat_ms, degraded,
                                     trace_id=req.trace_id)
            self.slo.record(True, latency_ms=lat_ms,
                            trace_id=req.trace_id)
            req.event.set()

    # -- watchdog --------------------------------------------------------
    def _watchdog_loop(self) -> None:
        """Detects the two ways a dispatcher hangs the queue: a STALLED
        in-flight batch (device wedged — its requests fail with 503
        instead of blocking their clients forever) and a DEAD dispatcher
        thread (restarted, stranded requests failed)."""
        limit_s = self.config.watchdog_ms / 1e3
        period = max(limit_s / 4.0, 0.005)
        while True:
            time.sleep(period)
            if self._closed:
                return
            infl = self._inflight
            if infl is not None:
                t_start, live = infl
                if time.monotonic() - t_start > limit_s:
                    n_failed = 0
                    for req in live:
                        if not req.event.is_set():
                            req.error = DispatcherStalled(
                                f"device batch exceeded the "
                                f"{self.config.watchdog_ms:.0f} ms "
                                "watchdog deadline")
                            req.event.set()
                            self.slo.record(False, trace_id=req.trace_id)
                            n_failed += 1
                    if n_failed:
                        self._last_wedge_unix = time.time()
                        self.metrics.on_watchdog(n_failed)
                        obs_events.publish(
                            "serve.watchdog_stall",
                            f"stalled batch failed {n_failed} "
                            "request(s)", severity="error",
                            requests=n_failed,
                            watchdog_ms=self.config.watchdog_ms)
                        # a wedged device batch is a crash-grade moment:
                        # give the armed flight recorder its dump (the
                        # process survives, the evidence must too)
                        obs_dump.dump(
                            "watchdog_stall",
                            error=f"device batch exceeded "
                                  f"{self.config.watchdog_ms:.0f} ms")
                        log_warning(
                            f"serve: watchdog failed {n_failed} "
                            "request(s) of a stalled batch")
            if not self._dispatcher.is_alive() and not self._closed:
                obs_events.publish(
                    "serve.dispatcher_restart",
                    "dispatcher thread dead — restarting",
                    severity="error")
                log_warning("serve: dispatcher thread dead — restarting")
                self.metrics.on_dispatcher_restart()
                self._dispatcher = threading.Thread(
                    target=self._dispatch_loop, name="serve-dispatcher",
                    daemon=True)
                self._dispatcher.start()


def serve_config_from(config) -> ServeConfig:
    """Map the global Config's ``serve_*`` knobs onto a
    :class:`ServeConfig` (shared by the single-server and fleet CLI
    paths)."""
    return ServeConfig(
        max_batch_rows=config.serve_max_batch_rows,
        max_batch_delay_ms=config.serve_max_batch_delay_ms,
        queue_depth_rows=config.serve_queue_depth,
        timeout_ms=config.serve_timeout_ms,
        degrade_trees=config.serve_degrade_trees,
        f64_scores=config.predict_f64_scores,
        drift_sample_rows=config.drift_sample_rows,
        drift_per_batch_rows=config.drift_per_batch_rows,
        drift_min_rows=config.drift_min_rows,
        drift_psi_threshold=config.drift_psi_threshold,
        drift_top_k=config.drift_top_k,
        drift_psi_groups=config.drift_psi_groups,
        drift_sample_stride=config.drift_sample_stride,
        retry_max=config.serve_retry_max,
        retry_backoff_ms=config.serve_retry_backoff_ms,
        breaker_failures=config.serve_breaker_failures,
        watchdog_ms=config.serve_watchdog_ms,
        probe_rows=config.serve_probe_rows,
        slo=SLOConfig(
            availability_target=config.serve_slo_availability_target,
            latency_ms=config.serve_slo_latency_ms,
            latency_target=config.serve_slo_latency_target,
            fast_window_s=config.serve_slo_fast_window_s,
            slow_window_s=config.serve_slo_slow_window_s,
        ),
        predictor_kwargs={
            "bucket_min": config.predict_bucket_min,
            "cache_entries": config.predict_cache_entries,
            **({"method": config.predict_method}
               if config.predict_method in ("depthwise", "pallas",
                                            "fused", "scan") else {}),
            "code_layout": config.predict_code_layout,
        },
    )


def build_server(booster, config) -> Server:
    """CLI glue: a :class:`Server` from a Booster + the global Config's
    ``serve_*`` knobs (cli.py task=serve)."""
    sc = serve_config_from(config)
    server = Server(booster, config=sc)
    log_info(f"serve: model {server.version()} online "
             f"({booster.num_trees()} trees, "
             f"batch<= {sc.max_batch_rows} rows, "
             f"delay {sc.max_batch_delay_ms} ms, "
             f"queue {sc.queue_depth_rows} rows)")
    return server
