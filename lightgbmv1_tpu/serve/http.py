"""Stdlib HTTP front-end over :class:`~lightgbmv1_tpu.serve.Server`.

Deliberately dependency-free (``http.server`` + ``json``): the process
already holds the device runtime, so the HTTP layer only needs to decode
rows, call ``Server.submit()`` and map the admission-control outcomes
onto status codes — 200 scored, 503 shed / stalled / closed, 504
deadline expired, 400 malformed.  Every client-input failure mode
(malformed JSON, a non-object body, missing/non-list ``rows``,
non-numeric cells, wrong feature count) answers a structured 400 — an
unhandled 500 on bad input is a bug, and an unexpected server-side
exception answers a structured 500, never a traceback page.  Each
handler thread blocks inside ``submit()`` like any other in-process
client, so HTTP requests micro-batch together with (and against) direct
callers.

Endpoints:

* ``POST /predict``  body ``{"rows": [[...], ...]}`` ->
  ``{"values": [[...], ...], "version": "v2", "degraded": false,
  "latency_ms": 1.9, "trace_id": "..."}``.  Every response echoes an
  ``X-Trace-Id`` header — the inbound header when the client sent one,
  a freshly minted id otherwise — and the id rides the request through
  admission queue -> micro-batch -> predictor walk, so an armed tracer
  (obs/trace.py) decomposes any response's latency by grepping the id.
* ``GET /metrics``   content negotiation over ONE store
  (obs/metrics.py): the JSON ServeMetrics snapshot by default (the
  pre-obs contract), Prometheus text exposition when the request has
  ``Accept: text/plain`` or ``?format=prometheus``.
* ``GET /drift``     train/serve skew evaluation (obs/drift.py): the
  active version's per-feature PSI vs its training reference, unseen-
  bin/NaN counters and prediction-score drift; ``armed: false`` (with a
  reason) when drift sampling is off or no reference was published.
* ``GET /healthz``   liveness, not process-up: 200 with
  ``{"ok": true, "version", "dispatcher_alive", "published",
  "server_version", "uptime_s"}`` only when the dispatcher thread is
  alive AND a model is published; 503 otherwise — a wedged replica must
  fall out of its load balancer.  ``version`` is the ACTIVE MODEL tag,
  ``server_version`` the package build.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .server import (DEFAULT_TENANT, DispatcherStalled, RequestTimeout,
                     ServeError, Server, ServerClosed, ServerOverloaded,
                     UnknownTenant)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _query_param(query: str, key: str) -> str:
    """Minimal query-string lookup (no urllib dependency creep for one
    scalar): last ``key=value`` pair wins, '' when absent."""
    out = ""
    for part in query.split("&"):
        if part.startswith(key + "="):
            out = part[len(key) + 1:]
    return out


def _make_handler(server: Server):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: A003 — silence stderr
            pass

        def _reply(self, code: int, payload: dict,
                   headers: dict = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_text(self, code: int, text: str,
                        content_type: str) -> None:
            body = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _wants_prometheus(self) -> bool:
            if "format=prometheus" in (self.path.split("?", 1) + [""])[1]:
                return True
            accept = self.headers.get("Accept", "")
            return "text/plain" in accept or "openmetrics" in accept

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            route, query = (self.path.split("?", 1) + [""])[:2]
            tenant = _query_param(query, "tenant")
            try:
                if route == "/metrics":
                    if self._wants_prometheus():
                        # exemplar suffixes only for OpenMetrics
                        # consumers — they are not part of the 0.0.4
                        # text grammar
                        om = "openmetrics" in self.headers.get(
                            "Accept", "")
                        self._reply_text(
                            200,
                            server.metrics.prometheus_text(exemplars=om),
                            PROM_CONTENT_TYPE)
                    else:
                        self._reply(200, server.metrics_snapshot())
                elif route == "/slo":
                    # burn-rate evaluation + worst-tail exemplar trace
                    # ids (serve/slo.py) — the page/warn booleans an
                    # external alerter can poll without scraping
                    # histograms; ?tenant= narrows to one lineage
                    self._reply(200, server.slo_snapshot(
                        tenant=tenant) if tenant
                        else server.slo_snapshot())
                elif route == "/drift":
                    # train/serve skew evaluation (obs/drift.py):
                    # per-feature PSI vs the active version's training
                    # reference, skew counters and score drift —
                    # computed on READ, never on the serving path;
                    # ?tenant= narrows to that tenant's detector
                    self._reply(200, server.drift_snapshot(
                        tenant=tenant) if tenant
                        else server.drift_snapshot())
                elif route == "/tenants":
                    # the multi-tenant control surface: per-tenant
                    # version, fair-share occupancy, shed/error counts
                    # and SLO page/burn summary (serve/server.py
                    # tenants_snapshot; on a router, per-replica views
                    # plus the placement map)
                    self._reply(200, server.tenants_snapshot())
                elif route == "/healthz":
                    health = server.health()
                    self._reply(200 if health["ok"] else 503, health)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})
            except UnknownTenant as e:
                self._reply(404, {"error": str(e), "tenant": tenant})

        def do_POST(self):  # noqa: N802
            if self.path != "/predict":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            from ..obs import trace as _trace

            trace_id = (self.headers.get("X-Trace-Id", "").strip()
                        or _trace.new_trace_id())
            tid_hdr = {"X-Trace-Id": trace_id}
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(req, dict):
                    raise ValueError(
                        f"body must be a JSON object, got "
                        f"{type(req).__name__}")
                rows = req["rows"]
                if not isinstance(rows, list) or not rows:
                    raise ValueError("'rows' must be a non-empty list")
                tenant = req.get("tenant", DEFAULT_TENANT)
                if not isinstance(tenant, str):
                    raise ValueError("'tenant' must be a string")
            except KeyError as e:
                self._reply(400, {"error": f"missing field {e}"},
                            headers=tid_hdr)
                return
            except (ValueError, TypeError) as e:
                self._reply(400, {"error": f"bad request body: {e}"},
                            headers=tid_hdr)
                return
            try:
                res = server.submit(rows, trace_id=trace_id,
                                    tenant=tenant)
            except UnknownTenant as e:
                # the lineage does not exist — routing elsewhere cannot
                # create it, so this is the caller's 404, not a 503
                self._reply(404, {"error": str(e), "tenant": tenant},
                            headers=tid_hdr)
                return
            except ServerOverloaded as e:
                self._reply(503, {"error": str(e), "shed": True},
                            headers=tid_hdr)
                return
            except RequestTimeout as e:
                self._reply(504, {"error": str(e), "timeout": True},
                            headers=tid_hdr)
                return
            except (DispatcherStalled, ServerClosed) as e:
                # retryable-elsewhere: the replica is wedged or draining
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            headers=tid_hdr)
                return
            except (ValueError, TypeError) as e:
                # client-input failures from row coercion/shape checks
                # (non-numeric cells, wrong feature count, ragged rows)
                self._reply(400, {"error": f"{type(e).__name__}: {e}"},
                            headers=tid_hdr)
                return
            except ServeError as e:
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            headers=tid_hdr)
                return
            except RuntimeError as e:
                # e.g. "no model published yet" — not ready, not a bug
                self._reply(503, {"error": str(e)}, headers=tid_hdr)
                return
            except Exception as e:  # noqa: BLE001 — structured 500, not
                # an unhandled-traceback page
                self._reply(500, {"error": f"{type(e).__name__}: {e}"},
                            headers=tid_hdr)
                return
            payload = {
                "values": res.values.tolist(),
                "version": res.version,
                "degraded": res.degraded,
                "latency_ms": round(res.latency_ms, 3),
                "trace_id": res.trace_id,
                "queue_ms": round(res.queue_ms, 3),
                "walk_ms": round(res.walk_ms, 3),
            }
            if tenant:
                payload["tenant"] = tenant
            self._reply(200, payload, headers=tid_hdr)

    return Handler


class ServeHTTP:
    """Threaded HTTP listener bound to ``(host, port)``; ``port=0`` picks
    an ephemeral port (read it back from ``.port``)."""

    def __init__(self, server: Server, host: str = "127.0.0.1",
                 port: int = 0):
        self.server = server
        self._httpd = ThreadingHTTPServer((host, port),
                                          _make_handler(server))
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="serve-http", daemon=True)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "ServeHTTP":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
