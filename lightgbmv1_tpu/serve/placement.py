"""SLO-driven tenant placement over a replicated fleet (ISSUE 20).

With hundreds of tenants on one fleet, "every tenant routes to every
replica" stops being a policy — a hot tenant's queue pressure lands on
every replica at once and the fair-share shed is the ONLY isolation
left.  The placement controller adds the second lever: it pins each
tenant's traffic to a replica SUBSET (the router's placement map,
router.py) and migrates tenants between subsets from three signals it
reads off surfaces that already exist:

* **SLO burn rate** — the tenant's fast-window availability/latency
  burn from its own per-replica SLO trackers (server.py
  ``tenants_snapshot``): a tenant burning error budget on its current
  subset is a candidate to move.
* **queue occupancy** — the tenant's backlog as a fraction of its
  fair-share rows on each pinned replica: sustained occupancy near 1.0
  means the subset is undersized or overloaded.
* **warm-compile cost** — ``warm_compile_ms`` stamped into the active
  :class:`~lightgbmv1_tpu.serve.registry.ModelVersion` meta at publish:
  the price this tenant's executables cost to warm.  The fleet publish
  already warmed every replica off-path, so a move never compiles on
  the serving path — the cost is recorded as a decision input (and
  breaks target ties toward cheap-to-rewarm tenants) rather than
  gating correctness.

The controller's ONLY actuators are primitives that already exist:
the router's placement map (set_placement) for traffic, and the
registry's off-path prepare/commit warm for executables.  It never
touches a queue or a dispatcher.  Every migration is a first-class
``placement.move`` event carrying the full decision input — burn,
occupancy, loads, warm cost — so a fleet operator can replay WHY a
tenant moved from the event log alone.

Deliberately poll-driven (``step()``): the caller owns the cadence
(CLI loop, a test, a cron), the controller owns the decision.  A
``cooldown_s`` per tenant bounds churn — a tenant that just moved is
not reconsidered until its new subset's windows carry signal.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..utils.log import log_info


@dataclass
class PlacementConfig:
    """Mirrored by the ``placement_*`` knobs in config.py."""

    replicas_per_tenant: int = 1     # subset size each tenant is pinned to
    burn_threshold: float = 2.0      # fast-window burn rate marking "hot"
    occupancy_frac: float = 0.75     # queue occupancy marking "hot"
    cooldown_s: float = 30.0         # per-tenant re-move quiet period
    max_moves_per_step: int = 1      # churn bound per step() call

    def __post_init__(self):
        self.replicas_per_tenant = max(int(self.replicas_per_tenant), 1)
        if self.burn_threshold <= 0:
            raise ValueError("burn_threshold must be > 0")
        if not 0 < self.occupancy_frac <= 1:
            raise ValueError("occupancy_frac must be in (0, 1]")
        self.cooldown_s = max(float(self.cooldown_s), 0.0)
        self.max_moves_per_step = max(int(self.max_moves_per_step), 1)


class PlacementController:
    """Assigns tenants to replica subsets and migrates the hot ones.

    ``fleet`` supplies the signal reads (per-replica
    ``tenants_snapshot``) and ``router`` the actuator (its placement
    map filters ``_pick``)."""

    def __init__(self, fleet, router,
                 config: Optional[PlacementConfig] = None):
        self.fleet = fleet
        self.router = router
        self.config = config or PlacementConfig()
        n = len(fleet.replicas)
        if self.config.replicas_per_tenant > n:
            raise ValueError(
                f"replicas_per_tenant={self.config.replicas_per_tenant} "
                f"exceeds the fleet size {n}")
        self._last_move: Dict[str, float] = {}
        self.moves = 0

    # -- initial assignment ----------------------------------------------
    def assign(self) -> Dict[str, List[str]]:
        """Round-robin every NAMED tenant onto a subset of
        ``replicas_per_tenant`` replicas (the default tenant keeps
        routing everywhere).  Idempotent: tenants already pinned are
        left where they are — assign() heals the unpinned, it does not
        reshuffle."""
        names = [r.name for r in self.fleet.replicas]
        k = self.config.replicas_per_tenant
        placed = self.router.placement()
        offset = len(placed)
        out: Dict[str, List[str]] = {
            t: list(v) for t, v in placed.items()}
        for t in sorted(self.fleet.tenant_names()):
            if not t or t in placed:
                continue
            subset = [names[(offset + i) % len(names)] for i in range(k)]
            self.router.set_placement(t, subset)
            out[t] = subset
            offset += 1
        log_info(f"placement: assigned {len(out)} tenant(s) over "
                 f"{len(names)} replica(s), k={k}")
        return out

    # -- signal read -----------------------------------------------------
    def signals(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant decision inputs, worst-case across the replicas
        the tenant is currently pinned to (or all replicas when
        unpinned): fast-window burn rate, fair-share queue occupancy,
        SLO page state, the active version's warm-compile cost, and
        per-replica total backlog (the load the mover balances)."""
        per_replica = {r.name: r.tenants_snapshot()["tenants"]
                       for r in self.fleet.replicas}
        placement = self.router.placement()
        loads = {name: sum(t["queue_rows"] for t in tenants.values())
                 for name, tenants in per_replica.items()}
        out: Dict[str, Dict[str, Any]] = {}
        for t in self.fleet.tenant_names():
            if not t:
                continue
            pinned = list(placement.get(t, per_replica.keys()))
            views = [per_replica[n][t] for n in pinned
                     if t in per_replica[n]]
            if not views:
                continue
            warm = 0.0
            try:
                mv = self.fleet.replicas[0].tenant_registry(t).current()
                warm = float(mv.meta.get("warm_compile_ms") or 0.0)
            except Exception:   # noqa: BLE001 — nothing published yet
                pass
            out[t] = {
                "pinned": pinned,
                "burn_rate": max(v["burn_rate"] for v in views),
                "occupancy": max(v["occupancy"] for v in views),
                "slo_page": any(v["slo_page"] for v in views),
                "warm_compile_ms": warm,
                "replica_loads": loads,
            }
        return out

    # -- migration -------------------------------------------------------
    def _hot(self, sig: Dict[str, Any]) -> bool:
        cfg = self.config
        return (sig["burn_rate"] >= cfg.burn_threshold
                or sig["occupancy"] >= cfg.occupancy_frac)

    def step(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One control round: move up to ``max_moves_per_step`` hot
        tenants off their most-loaded pinned replica onto the
        least-loaded replica outside their subset.  Returns the move
        records (also published as ``placement.move`` events).  ``now``
        is injectable so tests drive the cooldown clock."""
        from ..obs import events as obs_events

        cfg = self.config
        t_now = time.monotonic() if now is None else float(now)
        sigs = self.signals()
        # hottest first: page > burn > occupancy
        hot = sorted(
            (t for t, s in sigs.items()
             if self._hot(s) and len(s["pinned"])
             < len(self.fleet.replicas)),
            key=lambda t: (not sigs[t]["slo_page"],
                           -sigs[t]["burn_rate"],
                           -sigs[t]["occupancy"], t))
        moves: List[Dict[str, Any]] = []
        for t in hot:
            if len(moves) >= cfg.max_moves_per_step:
                break
            last = self._last_move.get(t)
            if last is not None and t_now - last < cfg.cooldown_s:
                continue
            s = sigs[t]
            loads = s["replica_loads"]
            pinned = list(s["pinned"])
            src = max(pinned, key=lambda n: (loads.get(n, 0), n))
            candidates = [n for n in loads if n not in pinned]
            if not candidates:
                continue
            dst = min(candidates, key=lambda n: (loads[n], n))
            new_subset = [dst if n == src else n for n in pinned]
            self.router.set_placement(t, new_subset)
            self._last_move[t] = t_now
            self.moves += 1
            record = {
                "tenant": t, "from": src, "to": dst,
                "subset": new_subset,
                "burn_rate": round(s["burn_rate"], 4),
                "occupancy": round(s["occupancy"], 4),
                "slo_page": s["slo_page"],
                "warm_compile_ms": round(s["warm_compile_ms"], 3),
                "src_load_rows": loads.get(src, 0),
                "dst_load_rows": loads.get(dst, 0),
            }
            obs_events.publish(
                "placement.move",
                f"tenant {t}: {src} -> {dst} (burn "
                f"{record['burn_rate']}, occupancy "
                f"{record['occupancy']}, warm "
                f"{record['warm_compile_ms']} ms)",
                severity="warning" if s["slo_page"] else "info",
                **record)
            log_info(f"placement: moved {t} {src} -> {dst}")
            moves.append(record)
        return moves
