"""Online serving subsystem — the throughput-critical consumer half.

LightGBM's own framing (PAPERS.md) splits the system into an
offline-optimized trainer and an online consumer; PR 4 built the raw
device engine (models/predict.py) and this package turns it into a
service:

* :class:`Server` / :class:`ServeConfig` — deadline-aware micro-batching
  with bounded-queue admission control and overload degradation
  (server.py),
* :class:`ModelRegistry` — versioned atomic hot-swap with warm-off-path
  publish and instant rollback (registry.py),
* :class:`ServeMetrics` — QPS / latency quantiles / batch occupancy /
  queue + shed counters, one JSON snapshot (metrics.py),
* :class:`ServeHTTP` — stdlib HTTP front-end (http.py),
* :class:`SLOTracker` / :class:`SLOConfig` — availability + latency
  SLOs with multi-window burn-rate evaluation and worst-tail exemplar
  trace ids, surfaced at ``GET /slo`` (slo.py).

Front doors: ``Server.submit()`` in-process, ``ServeHTTP`` over the
wire, and CLI ``task=serve`` (cli.py).  ``tools/loadgen.py`` drives
open-loop Poisson traffic against any of them.
"""

from .metrics import ServeMetrics
from .registry import ModelRegistry, ModelVersion, PublishValidationError
from .server import (DEFAULT_TENANT, DispatcherDied, DispatcherStalled,
                     RequestTimeout, ServeConfig, ServeError, ServeResult,
                     Server, ServerClosed, ServerOverloaded, UnknownTenant,
                     build_server, serve_config_from)
from .http import ServeHTTP
from .slo import SLOConfig, SLOTracker
from .fleet import Fleet, FleetPublishError
from .router import Router, RouterConfig
from .tenants import (TenantRegistry, TenantSpec, compile_share_stats,
                      parse_manifest)
from .placement import PlacementConfig, PlacementController

__all__ = [
    "DEFAULT_TENANT",
    "DispatcherDied", "DispatcherStalled", "Fleet", "FleetPublishError",
    "ModelRegistry", "ModelVersion",
    "PlacementConfig", "PlacementController",
    "PublishValidationError", "RequestTimeout", "Router", "RouterConfig",
    "SLOConfig", "SLOTracker",
    "ServeConfig", "ServeError", "ServeHTTP", "ServeMetrics",
    "ServeResult", "Server", "ServerClosed", "ServerOverloaded",
    "TenantRegistry", "TenantSpec", "UnknownTenant",
    "build_server", "compile_share_stats", "parse_manifest",
    "serve_config_from",
]
