"""Multi-tenant model multiplexing over one server or fleet (ISSUE 20).

The reference's C API hosts many independent ``Booster`` handles in one
process (PAPER.md layer map: ``c_api.cpp``); the serve stack's analog is
hundreds of named model LINEAGES sharing one fleet's devices, compile
cache and admission queue.  :class:`TenantRegistry` is the control-plane
façade over the per-tenant machinery that already lives in the data
plane:

* **per-tenant versioning/rollback** — each tenant owns a full
  :class:`~lightgbmv1_tpu.serve.registry.ModelRegistry` per replica
  (named ``replica:tenant`` so warm events and chaos plans are
  tenant-addressable).  Publish rides the SAME two-phase prepare/commit
  the single-lineage fleet publish uses (fleet.py): a failed tenant
  publish aborts with ZERO replicas swapped and cannot disturb any
  other tenant's active version — their registries are separate objects
  by construction.
* **cross-tenant compile-bucket sharing** — tenants are registered with
  ``shared_cache=True`` predictors (models/predict.py): the jit cache
  is keyed on ``(tree-shape signature, bucket, kind)``, NOT tenant
  identity, so tenants whose stacked-tree shapes match serve through
  ONE compiled executable.  ``compile_share_stats()`` exposes the hit
  rate; PR 12's per-label compile/retrace counters
  (obs/xla.compile_stats) prove the second tenant's warm added zero
  compiles.
* **fair-share admission** — ``weight`` flows to the server's
  per-tenant row accounting (server.py ``_recompute_shares``): an
  overloaded tenant sheds its OWN traffic first.

The backend is duck-typed: a :class:`~lightgbmv1_tpu.serve.Server`, a
:class:`~lightgbmv1_tpu.serve.Fleet`, or anything exposing
``add_tenant / remove_tenant / tenant_names / publish / rollback /
version / tenants_snapshot``.

Tenant manifests (CLI ``task=serve tenant_manifest=...``) use the
compact ``name[:weight][,name[:weight]...]`` grammar —
``"acme:3,globex"`` is tenant ``acme`` at weight 3 and ``globex`` at
the default weight 1.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..utils.log import log_info
from .slo import SLOConfig


@dataclass
class TenantSpec:
    """One tenant's declaration: identity, fair-share weight, optional
    per-tenant SLO targets and predictor overrides."""

    name: str
    weight: float = 1.0
    slo: Optional[SLOConfig] = None
    predictor_kwargs: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if not self.name or not isinstance(self.name, str):
            raise ValueError("tenant name must be a non-empty string")
        if "," in self.name or ":" in self.name:
            raise ValueError(
                f"tenant name {self.name!r} may not contain ',' or ':' "
                "(manifest grammar delimiters)")
        self.weight = float(self.weight)
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, got "
                f"{self.weight}")


def parse_manifest(spec: str) -> List[TenantSpec]:
    """``"acme:3,globex"`` -> ``[TenantSpec("acme", 3.0),
    TenantSpec("globex", 1.0)]``.  Duplicate names are rejected — a
    manifest that silently last-writer-wins a weight is a config bug."""
    out: List[TenantSpec] = []
    seen = set()
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, w = entry.partition(":")
        name = name.strip()
        try:
            weight = float(w) if w.strip() else 1.0
        except ValueError:
            raise ValueError(
                f"tenant manifest entry {entry!r}: weight {w!r} is not "
                "a number") from None
        if name in seen:
            raise ValueError(f"tenant {name!r} appears twice in the "
                             "manifest")
        seen.add(name)
        out.append(TenantSpec(name, weight))
    return out


def compile_share_stats() -> Dict[str, Any]:
    """The cross-tenant executable-sharing scoreboard: hit/miss/entry
    counts of the shape-keyed shared jit cache (models/predict.py) plus
    ``share_frac`` = hits / lookups — the ``tenant_compile_share_frac``
    BENCH rate.  A fleet of same-shape tenants converges toward 1.0;
    0.0 means every tenant compiled privately."""
    from ..models.predict import shared_cache_stats

    stats = dict(shared_cache_stats())
    lookups = stats["hits"] + stats["misses"]
    stats["share_frac"] = (round(stats["hits"] / lookups, 4)
                           if lookups else 0.0)
    return stats


class TenantRegistry:
    """Control plane for named model lineages over one backend.

    ``shared_compile=True`` (default) registers every tenant's
    predictors with the shape-keyed shared jit cache so same-shape
    tenants reuse one executable; a caller-supplied
    ``predictor_kwargs`` in the spec still wins (a tenant can opt out
    of sharing explicitly)."""

    def __init__(self, backend, *, shared_compile: bool = True):
        self.backend = backend
        self.shared_compile = bool(shared_compile)
        self._specs: Dict[str, TenantSpec] = {}

    # -- lifecycle -------------------------------------------------------
    def add(self, spec, *, weight: Optional[float] = None,
            slo: Optional[SLOConfig] = None,
            predictor_kwargs: Optional[Dict[str, Any]] = None
            ) -> TenantSpec:
        """Register a tenant (idempotent; re-add updates the weight).
        ``spec`` is a :class:`TenantSpec` or a bare name."""
        if not isinstance(spec, TenantSpec):
            spec = TenantSpec(str(spec),
                              weight=1.0 if weight is None else weight,
                              slo=slo,
                              predictor_kwargs=dict(
                                  predictor_kwargs or {}))
        pk = dict(spec.predictor_kwargs)
        if self.shared_compile:
            pk.setdefault("shared_cache", True)
        self.backend.add_tenant(spec.name, weight=spec.weight,
                                slo=spec.slo, predictor_kwargs=pk)
        self._specs[spec.name] = spec
        return spec

    def add_manifest(self, manifest: str) -> List[TenantSpec]:
        specs = parse_manifest(manifest)
        for s in specs:
            self.add(s)
        if specs:
            log_info(f"tenants: manifest registered "
                     f"{[s.name for s in specs]}")
        return specs

    def remove(self, name: str) -> None:
        self.backend.remove_tenant(name)
        self._specs.pop(name, None)

    def names(self) -> List[str]:
        return [n for n in self.backend.tenant_names() if n]

    def spec(self, name: str) -> Optional[TenantSpec]:
        return self._specs.get(name)

    # -- model lifecycle (two-phase on a fleet backend) ------------------
    def publish(self, name: str, model, **meta) -> str:
        """Publish into ONE tenant's lineage.  On a fleet backend this
        is the two-phase prepare/commit (fleet.py): any replica's
        validation failure aborts with zero replicas swapped — and
        because every tenant's registry is a separate object, a failed
        publish for tenant A cannot touch tenant B's active version."""
        return self.backend.publish(model, tenant=name, **meta)

    def rollback(self, name: str) -> str:
        return self.backend.rollback(tenant=name)

    def version(self, name: str) -> Optional[str]:
        return self.backend.version(tenant=name)

    # -- observability ---------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The backend's ``GET /tenants`` payload plus the
        compile-sharing scoreboard."""
        out = self.backend.tenants_snapshot()
        out["compile_share"] = compile_share_stats()
        return out

    compile_share_stats = staticmethod(compile_share_stats)
