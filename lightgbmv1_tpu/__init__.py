"""lightgbmv1_tpu — a TPU-native gradient-boosted decision tree framework.

A from-scratch re-design of LightGBM (the reference at
dreaming-panda/LightGBMv1) for TPU hardware: histograms on the MXU via
one-hot matmuls and Pallas kernels, on-device leaf-wise tree growth under
jit, and multi-chip data/feature parallelism via jax.sharding + shard_map
with XLA collectives over ICI — no sockets, no MPI.

The Python API mirrors the reference's python-package (Dataset / Booster /
train / cv / sklearn wrappers) so existing LightGBM scripts port with an
import change.
"""

from .config import Config
from .utils.log import LightGBMError, register_callback, set_verbosity

__version__ = "0.1.0"

__all__ = [
    "Config",
    "LightGBMError",
    "register_callback",
    "set_verbosity",
    "Dataset",
    "Booster",
    "train",
    "cv",
    "CVBooster",
    "LGBMModel",
    "LGBMRegressor",
    "LGBMClassifier",
    "LGBMRanker",
    "early_stopping",
    "log_evaluation",
    "record_evaluation",
    "reset_parameter",
    "plot_importance",
    "plot_metric",
    "plot_split_value_histogram",
    "plot_tree",
    "create_tree_digraph",
]


def __getattr__(name):
    # lazy imports keep `import lightgbmv1_tpu` light and avoid cycles
    if name in ("Dataset", "Booster"):
        from . import basic

        return getattr(basic, name)
    if name in ("train", "cv", "CVBooster"):
        from . import engine

        return getattr(engine, name)
    if name in ("LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"):
        from . import sklearn

        return getattr(sklearn, name)
    if name in ("early_stopping", "log_evaluation", "print_evaluation",
                "record_evaluation", "reset_parameter"):
        from . import callback

        return getattr(callback, name)
    if name in ("plot_importance", "plot_metric", "plot_split_value_histogram",
                "plot_tree", "create_tree_digraph"):
        from . import plotting

        return getattr(plotting, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
