"""Histogram construction ops.

TPU-native replacement for the reference's histogram machinery:

* reference CPU hot loop: ``DenseBin::ConstructHistogramInner``
  (src/io/dense_bin.hpp:98-141) — per-row gather-accumulate into
  (feature, bin) grad/hess pairs.
* reference GPU kernels: ``src/treelearner/ocl/histogram{16,64,256}.cl`` —
  per-workgroup local sub-histograms + atomic float adds + cross-workgroup
  reduction.

TPUs have no scatter-add worth using in the hot path, but they have an MXU.
The TPU formulation is a **one-hot matmul**: for a tile of rows, build

    leafG (3·L, tile)   — per-leaf-masked [grad, hess, count] rows
    onehot (tile, B)    — bin one-hot per feature

and accumulate ``leafG @ onehot -> (3·L, B)`` per feature on the MXU with
fp32 accumulation.  Batching the leaf dimension (all leaves of the current
frontier in one pass) is what keeps the matmul non-skinny; it replaces both
the reference's per-leaf histogram loop and its most-freq-bin elision.

Three interchangeable implementations (equality-tested against each other,
the analog of the reference's GPU/CPU comparator ``CompareHistograms``,
gpu_tree_learner.cpp:71-98):

* ``hist_leaves_scatter`` — jnp scatter-add; exact fp32; the oracle; fast on
  CPU for tests.
* ``hist_leaves_onehot``  — chunked one-hot matmuls in pure jnp (XLA maps
  them onto the MXU); bf16 / bf16x2 / f32 precision modes.
* ``hist_leaves_pallas``  — hand-tiled Pallas kernel (ops/hist_pallas.py).

Output layout: ``(L, F, B, 3)`` float32 — [sum_grad, sum_hess, count] per
(leaf, feature, bin). Counts are exact: the count channel multiplies one-hot
by 1.0 and MXU accumulation is fp32 (exact integers to 2^24).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# ---------------------------------------------------------------------------
# Scatter-add oracle
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("num_leaves", "num_bins"))
def hist_leaves_scatter(
    binned: jax.Array,      # (F, N) uint8/int16
    g3: jax.Array,          # (N, 3) f32 — [grad, hess, count(=sample weight mask)]
    leaf_id: jax.Array,     # (N,) int32
    num_leaves: int,
    num_bins: int,
) -> jax.Array:             # (L, F, B, 3) f32
    L, B = num_leaves, num_bins
    leaf_off = leaf_id.astype(jnp.int32) * B

    def per_feature(bins_f):
        idx = leaf_off + bins_f.astype(jnp.int32)
        h = jnp.zeros((L * B, 3), jnp.float32).at[idx].add(g3)
        return h.reshape(L, B, 3)

    h = lax.map(per_feature, binned)          # (F, L, B, 3)
    return h.transpose(1, 0, 2, 3)


# ---------------------------------------------------------------------------
# One-hot matmul path
# ---------------------------------------------------------------------------


def _matmul_hist(lg, onehot, precision: str):
    """(C, T) @ (T, B) with fp32 accumulation under the chosen input precision."""
    if precision == "f32":
        return jnp.dot(lg, onehot.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
    oh = onehot.astype(jnp.bfloat16)
    if precision == "bf16":
        return jnp.dot(lg.astype(jnp.bfloat16), oh,
                       preferred_element_type=jnp.float32)
    # bf16x2: split fp32 into two bf16 terms; one-hot is exact, so this
    # recovers ~fp32 accuracy at 2 MXU passes (cheaper than native f32).
    hi = lg.astype(jnp.bfloat16)
    lo = (lg - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return (
        jnp.dot(hi, oh, preferred_element_type=jnp.float32)
        + jnp.dot(lo, oh, preferred_element_type=jnp.float32)
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_bins", "precision", "row_chunk"),
)
def hist_leaves_onehot(
    binned: jax.Array,      # (F, N)
    g3: jax.Array,          # (N, 3)
    leaf_id: jax.Array,     # (N,)
    num_leaves: int,
    num_bins: int,
    precision: str = "bf16x2",
    row_chunk: int = 16384,
    init: Optional[jax.Array] = None,   # (Lp*3, F*B) carry — streamed
                                        # accumulation (hist_one_leaf_accum)
) -> jax.Array:             # (L, F, B, 3)
    F, N = binned.shape
    L, B = num_leaves, num_bins
    C = min(row_chunk, max(256, N))
    num_chunks = -(-N // C)
    pad = num_chunks * C - N
    # padded rows route to a sacrificial extra leaf slot
    Lp = L + 1
    binned_p = jnp.pad(binned, ((0, 0), (0, pad)))
    g3_p = jnp.pad(g3, ((0, pad), (0, 0)))
    leaf_p = jnp.pad(leaf_id, (0, pad), constant_values=L)

    binned_c = binned_p.reshape(F, num_chunks, C).transpose(1, 0, 2)  # (nc, F, C)
    g3_c = g3_p.reshape(num_chunks, C, 3)
    leaf_c = leaf_p.reshape(num_chunks, C)

    def chunk_body(acc, inputs):
        bins_ck, g3_ck, leaf_ck = inputs
        leaf_onehot = (
            leaf_ck[None, :] == lax.broadcasted_iota(jnp.int32, (Lp, 1), 0)
        ).astype(jnp.float32)                                   # (Lp, C)
        lg = (leaf_onehot[:, None, :] * g3_ck.T[None, :, :]).reshape(Lp * 3, C)
        # one-hot over ALL features at once, laid out (C, F*B) so the whole
        # chunk is a single large MXU matmul instead of F skinny ones
        onehot = (
            bins_ck.T[:, :, None].astype(jnp.int32)
            == lax.broadcasted_iota(jnp.int32, (1, 1, B), 2)
        ).reshape(C, F * B)                                     # (C, F*B)
        h = _matmul_hist(lg, onehot, precision)                 # (Lp*3, F*B)
        return acc + h, None

    if init is None:
        init = jnp.zeros((Lp * 3, F * B), jnp.float32)
    h, _ = lax.scan(chunk_body, init, (binned_c, g3_c, leaf_c))
    h = h.reshape(Lp, 3, F, B).transpose(0, 2, 3, 1)             # (Lp, F, B, 3)
    return h[:L]


# ---------------------------------------------------------------------------
# Single-leaf histogram (leaf-wise smaller-child pass)
# ---------------------------------------------------------------------------


def hist_one_leaf(
    binned: jax.Array,
    g3: jax.Array,
    leaf_id: jax.Array,
    target_leaf: jax.Array,
    num_bins: int,
    method: str = "scatter",
    precision: str = "bf16x2",
    packed: bool = False,
    num_features: int = 0,
    interpret: bool = False,
) -> jax.Array:             # (F, B, 3)
    """Histogram over the rows currently in ``target_leaf`` only — the
    smaller-child pass of the histogram-subtraction trick (reference:
    ``BeforeFindBestSplit`` serial_tree_learner.cpp:274-314 keeps the parent
    histogram with the larger leaf and computes only the smaller)."""
    with jax.named_scope("lgbm.hist"):
        mask = (leaf_id == target_leaf).astype(jnp.float32)
        g3m = g3 * mask[:, None]
        zeros = jnp.zeros_like(leaf_id)
        if method == "pallas":
            from .hist_pallas import hist_leaves_pallas

            # forward interpret only when SET: callers (and tests) may
            # bind it on hist_leaves_pallas itself via functools.partial
            kw = {"interpret": True} if interpret else {}
            return hist_leaves_pallas(binned, g3m, zeros, 1, num_bins,
                                      precision=precision, packed=packed,
                                      num_features=num_features, **kw)[0]
        if packed:
            raise ValueError(
                "4-bit packed bins require the pallas hist method")
        if method == "onehot":
            return hist_leaves_onehot(binned, g3m, zeros, 1, num_bins,
                                      precision)[0]
        return hist_leaves_scatter(binned, g3m, zeros, 1, num_bins)[0]


# ---------------------------------------------------------------------------
# Streamed (row-block) accumulation — out-of-core training (data/ subsystem)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=())
def _scatter_accum(acc, binned, g3m):
    """Scatter one row block's masked gradient rows INTO ``acc`` (F, B, 3).

    Bit-exactness contract: XLA's scatter-add applies updates sequentially
    in index order, so scattering block b's rows into the accumulator
    CONTINUES the same left-fold of row-order additions that one
    ``hist_leaves_scatter`` pass over the concatenated rows performs —
    the streamed histogram is bit-identical to the resident one (pinned
    by tests/test_stream_train.py).  Summing per-block PARTIAL histograms
    instead would re-associate the f32 adds and break the parity."""
    def per_feature(args):
        af, bins_f = args
        return af.at[bins_f.astype(jnp.int32)].add(g3m)

    return lax.map(per_feature, (acc, binned))


def _onehot_layout(acc, num_bins):
    """(F, B, 3) accumulator -> the (Lp*3, F*B) layout of the
    hist_leaves_onehot chunk scan, leaf slot 0 (Lp = 2: slot 1 is the
    sacrificial pad-row slot, zero here)."""
    F, B, _ = acc.shape
    h = jnp.zeros((2, 3, F, B), jnp.float32).at[0].set(acc.transpose(2, 0, 1))
    return h.reshape(2 * 3, F * B)


@functools.partial(jax.jit, static_argnames=("num_bins", "precision"))
def _onehot_accum(acc, binned, g3m, num_bins, precision):
    F, B = binned.shape[0], num_bins
    h = hist_leaves_onehot(
        binned, g3m, jnp.zeros(binned.shape[1], jnp.int32), 1, num_bins,
        precision, 16384, init=_onehot_layout(acc, num_bins))
    return h[0]


def hist_one_leaf_accum(
    acc: jax.Array,         # (F, B, 3) running accumulator
    binned: jax.Array,      # (F, n) one row block's bins
    g3: jax.Array,          # (n, 3)
    leaf_id: jax.Array,     # (n,) int32 — this block's current leaf routing
    target_leaf,            # scalar
    num_bins: int,
    method: str = "scatter",
    precision: str = "bf16x2",
) -> jax.Array:
    """Streamed continuation of :func:`hist_one_leaf`: fold one row block
    into ``acc``.  Folding every block in fixed block-sequential order
    reproduces the resident full-matrix pass bit-for-bit on the
    ``scatter`` method (update-order continuation, see ``_scatter_accum``)
    and on ``onehot`` when the block size is a multiple of the 16384-row
    chunk (the resident pass's own accumulation granularity).  ``pallas``
    blocks fall back to partial-sum accumulation: deterministic at fixed
    block order, but not bit-equal to the resident kernel."""
    with jax.named_scope("lgbm.hist_stream"):
        mask = (leaf_id == target_leaf).astype(jnp.float32)
        g3m = g3 * mask[:, None]
        if method == "onehot":
            return _onehot_accum(acc, binned, g3m, num_bins, precision)
        if method == "pallas":
            return acc + hist_one_leaf(binned, g3m,
                                       jnp.zeros_like(leaf_id),
                                       jnp.asarray(0, jnp.int32), num_bins,
                                       method=method, precision=precision)
        return _scatter_accum(acc, binned, g3m)


@jax.jit
def sums_accum(acc, g3):
    """Streamed continuation of the sequential grower's ordered-scatter
    root-sum fold (models/grower.py sums_fn): scatter block rows into the
    (1, 3) carry slot — update order continues the resident fold exactly,
    so the streamed root statistics are bit-identical."""
    return acc.at[jnp.zeros(g3.shape[0], jnp.int32)].add(g3)


def hist_frontier(
    binned: jax.Array,
    g3: jax.Array,
    leaf_id: jax.Array,
    num_leaves: int,
    num_bins: int,
    method: str = "scatter",
    precision: str = "bf16x2",
    packed: bool = False,
    num_features: int = 0,
    interpret: bool = False,
) -> jax.Array:
    """All-leaves histogram in a single pass (level-wise grower).

    ``interpret`` reaches the Pallas kernel only: the CPU backend runs
    ``hist_method=pallas`` through the interpreter — the bit-parity lane
    the fused wave-round kernel (ops/wave_fused.py) is pinned against.

    Wrapped in ``jax.named_scope`` so device traces attribute histogram
    time the way the reference's USE_TIMETAG FunctionTimer tags host time
    (utils/common.h:1054-1138); capture a trace with ``profile_dir``."""
    with jax.named_scope("lgbm.hist"):
        if method == "pallas":
            from .hist_pallas import hist_leaves_pallas

            # forward interpret only when SET (see hist_one_leaf)
            kw = {"interpret": True} if interpret else {}
            return hist_leaves_pallas(binned, g3, leaf_id, num_leaves,
                                      num_bins, precision=precision,
                                      packed=packed,
                                      num_features=num_features, **kw)
        if packed:
            raise ValueError(
                "4-bit packed bins require the pallas hist method")
        if method == "onehot":
            return hist_leaves_onehot(binned, g3, leaf_id, num_leaves,
                                      num_bins, precision)
        return hist_leaves_scatter(binned, g3, leaf_id, num_leaves, num_bins)


def hist_wave(
    binned: jax.Array,
    g3: jax.Array,
    label: jax.Array,       # (N,) int32 — child slot per row; nslots = dead
    nslots: int,
    num_bins: int,
    method: str = "scatter",
    precision: str = "bf16x2",
    packed: bool = False,
    num_features: int = 0,
    interpret: bool = False,
) -> jax.Array:             # (nslots, F, B, 3)
    """Histograms of the rows labeled ``0..nslots-1`` in one pass; rows
    labeled ``nslots`` (not part of the current wave) contribute nothing.
    Used by the wave-batched leaf-wise grower (models/grower_wave.py): one
    sacrificial slot absorbs the dead rows, then is sliced away."""
    return hist_frontier(binned, g3, label, nslots + 1, num_bins,
                         method=method, precision=precision,
                         packed=packed, num_features=num_features,
                         interpret=interpret)[:nslots]


def hist_wave_quant(
    binned: jax.Array,
    g3: jax.Array,
    label: jax.Array,
    nslots: int,
    num_bins: int,
    key: jax.Array,
    method: str = "scatter",
    packed: bool = False,
    num_features: int = 0,
    axis_name=None,
    interpret: bool = False,
):
    """Stochastic-rounded int8 wave histogram: quantize the gradient rows
    (ops/quantize.sr_quantize_g3 — deterministic counter-based rounding
    keyed by ``key``) and accumulate the INTEGER histogram.

    ``axis_name`` (row-sharded learners): pmax the quantization range
    across the named mesh axis so every shard's integer histogram shares
    one scale and the cross-chip reduction can run on raw int32 partials
    (see sr_quantize_g3).

    Returns ``(hist_q, scales)``: ``hist_q`` (nslots, F, B, 3) holds exact
    integer sums of the quantized rows, ``scales`` (nslots, 3) the per-slot
    dequantization multipliers.  The caller keeps the histogram in integer
    units as long as possible — the wave grower folds dequantization into
    the smaller-child subtraction, and ops/split.py's gain scan accepts
    ``hist_scale`` to dequantize after its (exact, integer) cumsum.

    On the ``pallas`` method this runs the int8 MXU path (one pass, 2x
    bf16 throughput, int8→int32 hierarchical widening); ``scatter`` and
    ``onehot`` accumulate the same integer rows exactly in f32, so every
    method produces the identical integer histogram (the property the
    oracle test pins, tests/test_int8sr.py)."""
    from .quantize import sr_quantize_g3

    with jax.named_scope("lgbm.hist_q"):
        q3, scales = sr_quantize_g3(g3, label, nslots, key,
                                    axis_name=axis_name)
        prec = "int8sr" if method == "pallas" else "f32"
        h = hist_wave(binned, q3, label, nslots, num_bins, method=method,
                      precision=prec, packed=packed,
                      num_features=num_features, interpret=interpret)
        return h, scales


def default_hist_method(config_method: str = "auto",
                        bin_dtype=None) -> str:
    """Pick the histogram implementation.

    TPU default is the Pallas kernel (validated vs the scatter oracle in
    tests/test_histogram.py, the analog of the reference's CompareHistograms
    debug comparator, gpu_tree_learner.cpp:71-98).  int16-binned data
    (num_bins > 256) routes to the XLA one-hot path — the Pallas kernel is
    uint8-only (see hist_pallas.hist_leaves_pallas).

    ``"fused"`` (the wave-round megakernel, ops/wave_fused.py) resolves to
    its BASE method here — the implementation every non-fused pass (root
    pass, sequential/level-wise growers, streaming) runs: the same
    ``pallas`` arithmetic the fused kernel reuses, which is what makes
    ``hist_method=fused`` trees bit-comparable to ``hist_method=pallas``
    trees; int16 bins exclude the whole kernel family.  The fused
    wave-round dispatch itself lives in parallel/trainer.py.
    """
    if config_method == "fused":
        if bin_dtype is not None and jnp.dtype(bin_dtype).itemsize > 1:
            return "onehot"
        return "pallas"
    if config_method not in ("auto", "bench"):
        return config_method
    platform = jax.default_backend()
    if platform == "cpu":
        return "scatter"
    if bin_dtype is not None and jnp.dtype(bin_dtype).itemsize > 1:
        return "onehot"
    return "pallas"


def benchmark_hist_methods(binned_np, num_bins: int, precision: str,
                           packed: bool, num_features: int,
                           nslots: int = 16, max_rows: int = 131072,
                           candidates=None, must_include=None) -> str:
    """Time the applicable histogram implementations on the REAL matrix
    shapes and return the fastest — the role of the reference's
    ``Dataset::GetShareStates`` col-wise/row-wise auto-benchmark
    (src/io/dataset.cpp:590-684: time both once at init, log, pick).

    Used when ``hist_method=bench`` (always measure), and by ``auto`` for
    shapes where the static choice is ambiguous (trainer decides).  Timing
    runs on a row subset (the reference subsamples too) with a TWO-length
    in-jit scan differential — (wall(r2) - wall(r1)) / (r2 - r1) — so the
    per-dispatch latency of a tunneled device (~113 ms here) cancels
    instead of swamping the few-ms passes being compared.

    ``must_include`` seeds the candidate list with a method the user
    forced (``force_col_wise`` -> scatter, ``force_row_wise`` -> onehot):
    an explicit ``hist_method=bench`` used to time candidate lists that
    could never contain the forced method (scatter is excluded from
    device lists), silently ignoring the force — the reference fatals on
    such conflicts in ``CheckParamConflict``; here the forced method
    competes in the timing instead, so the force is honored when it wins
    and the measured evidence is on the log when it does not.

    Multi-process runs must NOT call this: per-host wall-clock could pick
    different methods on different hosts around the same collectives (the
    trainer falls back to the static pick there, like the reference's
    single GetShareStates decision)."""
    import time as _time

    import numpy as _np
    from jax import lax as _lax

    from ..utils.log import log_info, log_warning

    if candidates is None:
        if jax.default_backend() == "cpu":
            candidates = ["scatter", "onehot"]
        elif jnp.dtype(binned_np.dtype).itemsize > 1:
            # device scatter-add is a known non-starter (module docstring);
            # int16 bins exclude pallas -> onehot is the only device path
            candidates = ["onehot"]
        else:
            candidates = ["pallas", "onehot"]
    if packed:
        candidates = [m for m in candidates if m == "pallas"]
    if must_include and must_include not in candidates:
        if packed and must_include != "pallas":
            log_warning(f"hist_method=bench: forced method "
                        f"'{must_include}' cannot run on 4-bit packed "
                        "bins; force ignored")
        else:
            candidates = [must_include] + list(candidates)
    if len(candidates) <= 1:
        pick = candidates[0] if candidates else default_hist_method(
            "auto", binned_np.dtype)
        log_info(f"hist-method benchmark: single applicable candidate "
                 f"-> {pick}" + (" (4-bit packing pins the pallas kernel)"
                                 if packed else ""))
        return pick
    n = min(binned_np.shape[1], max_rows)
    binned = jnp.asarray(_np.ascontiguousarray(binned_np[:, :n]))
    rng = _np.random.RandomState(0)
    g3 = jnp.asarray(rng.randn(n, 3).astype(_np.float32))
    label = jnp.asarray(rng.randint(0, nslots + 1, n).astype(_np.int32))
    times = {}
    for m in candidates:
        try:
            def reps_for(r, m=m):
                @jax.jit
                def reps():
                    def body(c, i):
                        g = g3 * (1.0 + 1e-6 * i.astype(jnp.float32))
                        h = hist_wave(binned, g, label, nslots, num_bins,
                                      method=m, precision=precision,
                                      packed=packed,
                                      num_features=num_features)
                        return c + h.sum(), None
                    s, _ = _lax.scan(body, jnp.float32(0), jnp.arange(r))
                    return s
                return reps

            f1, f2 = reps_for(2), reps_for(10)
            jax.block_until_ready(f1())
            jax.block_until_ready(f2())
            diffs = []
            for _ in range(3):
                t0 = _time.perf_counter()
                jax.block_until_ready(f1())
                t1 = _time.perf_counter()
                jax.block_until_ready(f2())
                t2 = _time.perf_counter()
                diffs.append(((t2 - t1) - (t1 - t0)) / 8.0)
            times[m] = max(float(_np.median(diffs)), 1e-9)
        except Exception as e:  # noqa: BLE001 — a failing candidate loses
            log_warning(f"hist-method benchmark: {m} failed "
                        f"({type(e).__name__}); excluded")
            continue
    if not times:
        return default_hist_method("auto", binned_np.dtype)
    pick = min(times, key=times.get)
    log_info("hist-method benchmark (%s rows x %s cols, %s): %s -> %s"
             % (n, binned_np.shape[0], binned_np.dtype,
                ", ".join(f"{m}={v * 1e3:.2f}ms"
                          for m, v in sorted(times.items())), pick))
    return pick
