"""Stochastic-rounded gradient quantization for the histogram pass.

The round-5 precision experiment (tools/precision_expt.py, PERF.md) showed
plain int8 histograms recover the int8 MXU's 2x-bf16 throughput but lose
0.007 AUC at 500 iterations: round-to-nearest quantization of gradients is
BIASED per bin, and the bias compounds over the boosting recursion.  The
fix with real-world lineage is *stochastic rounding* — LightGBM's own
quantized-training work ("Quantized Training of Gradient Boosting Decision
Trees", Shi et al., NeurIPS 2022) rounds gradients up or down with
probability proportional to the fractional part, which makes every
quantized per-bin SUM an unbiased estimator of the fp32 sum:

    E[floor(x + U)] = x   for U ~ Uniform[0, 1)

so the split finder sees zero-mean noise instead of systematic drift.

Determinism contract: the rounding stream is a **counter-based PRNG**
(``jax.random`` threefry) keyed by fold-ins of (iteration, round) — the
grower folds its per-tree key (already unique per (iteration, class)) with
the round's leaf count, and this module draws the whole row block from
that key in one counter-indexed sweep.  Results are bit-reproducible given
the seed on every backend, and the NumPy reference in
tests/test_int8sr.py reproduces the quantization bit-for-bit from the
same uniforms.

Scale placement: the interface carries **per-slot scales** ``(nslots, 3)``
so a per-leaf refinement can drop in, but the implementation uses one
per-pass scale (the global |grad| / |hess| max over the pass's rows):
a per-slot segment-max is a scatter, and scatters measured ~8 ms at bench
shapes on this device (tools/microbench_gather.py) — more than the whole
deep histogram pass the quantization is trying to speed up.

Counts stay EXACT: the count/weight channel is quantized with a
power-of-two scale (deterministic round-to-nearest, exact for unit
weights), preserving the repo-wide "counts are exact" guarantee that
min_data_in_leaf gating relies on (ops/histogram.py module docstring).

ALL scales are powers of two — grad/hess too, snapped down from
amax/127 (sr_prequantize_g3).  Exact dequantization multiplies make the
parent-subtraction arithmetic rounding-order independent, which is what
lets the persistent wave loop's in-kernel commit stay bit-identical to
the host grower's subtraction (see the comment at the snap site).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

INT8_QMAX = 127.0


@functools.partial(jax.jit, static_argnames=("nslots", "axis_name"))
def sr_quantize_g3(g3: jax.Array, label: jax.Array, nslots: int,
                   key: jax.Array, axis_name=None):
    """Quantize ``g3`` (N, 3) [grad, hess, count] to int8-ranged integers
    with stochastic rounding on the grad/hess channels.

    Returns ``(q3, scales)``:

    * ``q3`` (N, 3) float32 holding exact integers in [-127, 127] — kept
      in f32 because the TPU VPU has no int8 vector select (the kernel's
      leaf-mask ``where`` runs in f32 and the int8 cast is the final op
      feeding the MXU, ops/hist_pallas.py).
    * ``scales`` (nslots, 3) float32 — dequantization multipliers per
      slot: real histogram = integer histogram * scales.  Currently every
      slot carries the same per-pass scale (see module docstring).

    ``label`` is accepted (and unused by the global-scale implementation)
    so a per-slot scale can be introduced without touching call sites.

    ``axis_name``: when the rows are a SHARD of a mesh axis (data/voting
    parallel learners), pass its name — the quantization range is then
    pmax'd across shards so every shard quantizes against the IDENTICAL
    scale.  That is what lets the cross-chip histogram reduction run in
    the raw INTEGER domain (int32 through lax.psum_scatter/psum,
    parallel/trainer.py) with one shared dequantization folded into the
    split scan; per-shard scales would make the integer partials
    incommensurable.  SR unbiasedness holds for any scale, so the global
    scale (>= each local amax) changes nothing statistically.
    """
    del label  # per-pass scales; see module docstring
    zg, qc, scales = sr_prequantize_g3(g3, nslots, axis_name=axis_name)
    u = jax.random.uniform(key, zg.shape, dtype=jnp.float32)  # [0, 1)
    q = jnp.clip(jnp.floor(zg + u), -INT8_QMAX, INT8_QMAX)
    q3 = jnp.concatenate([q, qc[:, None]], axis=1)
    return q3, scales


def sr_prequantize_g3(g3: jax.Array, nslots: int, axis_name=None):
    """The key-INDEPENDENT half of :func:`sr_quantize_g3`: scaled
    grad/hess rows ``zg = g * inv`` (N, 2), the exactly-rounded count
    channel ``qc`` (N,), and the (nslots, 3) dequantization scales.

    Factored out so the persistent wave-loop kernel
    (ops/wave_fused.make_fused_wave_loop) can host-precompute everything
    but the per-round uniform draw — the rounding stream stays
    ``clip(floor(zg + U), -127, 127)`` with U drawn per (iteration,
    round) key inside the loop, reproducing sr_quantize_g3's exact
    per-round bits.  The ops here are the literal ones sr_quantize_g3
    ran inline before the factoring (bit-parity contract)."""
    from jax import lax as _lax

    g = g3[:, :2].astype(jnp.float32)
    amax = jnp.max(jnp.abs(g), axis=0)                       # (2,)
    if axis_name is not None:
        amax = _lax.pmax(amax, axis_name)
    # grad/hess scales snap DOWN to a power of two (inv = 2^floor(log2(
    # 127/amax)), scale = 1/inv): a power-of-two dequantization multiply
    # is EXACT in f32, so `parent - q*scale` rounds identically whether a
    # compiler contracts the multiply into the subtraction (fma, one
    # rounding) or not (two roundings).  The three places that compute
    # subtracted children from the same quantized histogram — the host
    # grower (XLA), the fused kernel's scan, and the persistent wave
    # loop's commit (both Pallas) — sit in different fusion contexts, and
    # their bit-parity contract must not hang on a contraction heuristic
    # (optimization_barrier does not stop it).  Costs at most one bit of
    # int8 range; SR unbiasedness holds for any scale (module docstring).
    e2 = jnp.floor(jnp.log2(INT8_QMAX / amax))
    inv = jnp.where(amax > 0, jnp.exp2(e2), 0.0)
    scale = jnp.where(amax > 0, jnp.exp2(-e2), 0.0)
    zg = g * inv[None, :]

    # count channel: power-of-two scale, deterministic rounding => exact
    # integer counts for unit weights (inv_c = 64, the historical
    # _COUNT_SCALE) and safe for weighted rows
    c = g3[:, 2].astype(jnp.float32)
    cmax = jnp.max(jnp.abs(c))
    if axis_name is not None:
        cmax = _lax.pmax(cmax, axis_name)
    inv_c = jnp.where(
        cmax > 0,
        jnp.minimum(jnp.exp2(jnp.floor(jnp.log2(INT8_QMAX / cmax))), 64.0),
        1.0)
    qc = jnp.round(c * inv_c)

    scales = jnp.concatenate(
        [jnp.broadcast_to(scale[None, :], (nslots, 2)),
         jnp.full((nslots, 1), 1.0, jnp.float32) / inv_c], axis=1)
    return zg, qc, scales


def dequantize_hist(hist_q: jax.Array, scales: jax.Array) -> jax.Array:
    """(S, F, B, 3) integer histogram * (S, 3) per-slot scales -> real
    units.  One fused broadcast multiply — the explicit form of the
    dequantization the split scan / subtraction pass otherwise folds in."""
    return hist_q * scales[:, None, None, :]
