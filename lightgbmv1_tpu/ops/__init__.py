from .histogram import (
    default_hist_method,
    hist_frontier,
    hist_leaves_onehot,
    hist_leaves_scatter,
    hist_one_leaf,
)
from .split import (
    FeatureMeta,
    SplitParams,
    SplitResult,
    find_best_split,
    find_best_split_batch,
    make_feature_meta,
)

__all__ = [
    "default_hist_method",
    "hist_frontier",
    "hist_leaves_onehot",
    "hist_leaves_scatter",
    "hist_one_leaf",
    "FeatureMeta",
    "SplitParams",
    "SplitResult",
    "find_best_split",
    "find_best_split_batch",
    "make_feature_meta",
]
