"""Pallas TPU inference kernels — VMEM-pinned node tables.

The XLA depth-stepped walk (models/predict.serving_leaf_binned) re-reads
the stacked node tables from HBM on every one of its ``max_depth`` steps:
each gather of (feature, threshold-bin, children) streams the (T, L1)
tables again, and for deep ensembles the walk is table-bandwidth-bound,
not row-bound.  Two kernels fix that:

* ``serving_leaf_pallas`` (PR 4, ``predict_method=pallas``) pins ALL
  node tables in VMEM once per row tile — for a 500-tree, 255-leaf
  model the full table set is ~3.5 MB, comfortably inside the ~16 MB
  VMEM budget — so the ``depth`` gather steps run entirely out of
  on-chip memory and HBM traffic drops to the prebinned code tile in +
  the leaf-index tile out.  The (N, T) leaf intermediate still lands in
  HBM and the leaf-value gather/sum is a second XLA pass.

* ``serving_fused_pallas`` (``predict_method=fused``) is the serving
  megakernel: one launch per row tile walks every tree to its leaf AND
  accumulates the per-class raw scores in a VMEM-resident (TILE, K)
  block, so neither the (N, T) pointer intermediate nor the leaf-value
  gather ever touches HBM.  The grid is (row_tiles, tree_tiles) with
  the TREE dim innermost: the scores block's index map is constant over
  the tree dim (a revisited accumulator, the histogram kernels'
  pattern) and so is the codes block — Pallas fetches the row codes
  from HBM once per tile-sweep instead of once per depth step.  When
  the stacked tables exceed the VMEM budget, ``plan_predict_tiles``
  (the ``plan_wave_loop`` idiom: static, honest reason strings) tiles
  trees into VMEM-sized groups streamed via the grid's inner dim.  With
  4-bit packed serving codes (every feature <= 15 codes incl. the
  reserved NaN/zero codes) the decision lane decodes nibbles in-kernel
  (ops/hist_pallas.pack4bit layout), halving both the H2D stream and
  the per-tile code footprint.  An optional sigmoid/softmax epilogue
  runs on the accumulator in the same launch.

Scope: the PREBINNED, non-categorical serving path (where the table-pin
pays; categorical ensembles ride the XLA walk).  The pure-XLA walk is the
bit-parity pin: `tests/test_predict_engine.py` pins kernel-vs-XLA leaf
equality (interpret mode on CPU), and `BatchPredictor` falls back to the
XLA walk with a warning if Mosaic cannot lower the gathers on the local
backend — `predict_method=pallas`/``fused`` are opt-in.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..io.binning import MISSING_NAN, MISSING_ZERO


def _kernel(nl_ref, feat_ref, tbin_ref, zbin_ref, dl_ref, mt_ref, lc_ref,
            rc_ref, codes_ref, out_ref, *, n_steps, zero_code, nan_code):
    """Grid: (row_tiles,).  All table refs hold the FULL (T, L1) arrays in
    VMEM; ``codes_ref`` is this tile's (TILE, F) serving codes."""
    T, L1 = feat_ref.shape
    rows = codes_ref.shape[0]

    codes = codes_ref[...].astype(jnp.int32)              # (TILE, F)
    feat = feat_ref[...].reshape(-1)                      # (T*L1,)
    tbin = tbin_ref[...].reshape(-1)
    zbin = zbin_ref[...].reshape(-1)
    dl = dl_ref[...].reshape(-1)
    mt = mt_ref[...].reshape(-1)
    lc = lc_ref[...].reshape(-1)
    rc = rc_ref[...].reshape(-1)
    t_off = lax.broadcasted_iota(jnp.int32, (rows, T), 1) * L1

    def body(_, node):
        nd = jnp.maximum(node, 0)
        flat = nd + t_off                                  # (TILE, T)
        f = jnp.take(feat, flat, axis=0)
        b = jnp.take_along_axis(codes, f, axis=1)
        is_nan = b == nan_code
        is_zero = b == zero_code
        b0 = jnp.where(is_nan | is_zero, jnp.take(zbin, flat, axis=0), b)
        mtype = jnp.take(mt, flat, axis=0)
        is_missing = jnp.where(
            mtype == MISSING_NAN, is_nan,
            jnp.where(mtype == MISSING_ZERO, is_nan | is_zero, False))
        go_left = jnp.where(is_missing, jnp.take(dl, flat, axis=0) != 0,
                            b0 <= jnp.take(tbin, flat, axis=0))
        nxt = jnp.where(go_left, jnp.take(lc, flat, axis=0),
                        jnp.take(rc, flat, axis=0))
        return jnp.where(node >= 0, nxt, node)

    node0 = jnp.where(nl_ref[...] > 1,
                      jnp.zeros((rows, T), jnp.int32),
                      jnp.full((rows, T), -1, jnp.int32))
    node = lax.fori_loop(0, max(int(n_steps), 1), body, node0)
    out_ref[...] = -node - 1


def serving_leaf_pallas(arrays, codes, *, n_steps: int, zero_code: int,
                        nan_code: int, interpret: bool = False,
                        row_tile: int = 512):
    """(N, F) serving codes -> (N, T) leaf indices, node tables pinned in
    VMEM.  ``N`` must be a multiple of the row tile after the caller's
    bucket padding (buckets are powers of two >= 256, so any power-of-two
    tile <= N divides it)."""
    N, _ = codes.shape
    T, L1 = arrays.split_feature.shape
    tile = min(row_tile, N)
    while N % tile:
        tile //= 2
    grid = (N // tile,)

    def full(a, dtype=jnp.int32):
        return a.astype(dtype)

    tables = (
        full(arrays.num_leaves.reshape(1, T)),
        full(arrays.split_feature),
        full(arrays.threshold_bin),
        full(arrays.zero_bin),
        full(arrays.default_left),
        full(arrays.missing_type),
        full(arrays.left_child),
        full(arrays.right_child),
    )
    table_specs = [pl.BlockSpec(t.shape, lambda i: (0, 0)) for t in tables]
    kern = functools.partial(_kernel, n_steps=n_steps, zero_code=zero_code,
                             nan_code=nan_code)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=table_specs + [
            pl.BlockSpec((tile, codes.shape[1]), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tile, T), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, T), jnp.int32),
        interpret=interpret,
    )(*tables, codes)


# ---------------------------------------------------------------------------
# Serving megakernel: fused walk + accumulate with tree tiling
# ---------------------------------------------------------------------------

_PREDICT_VMEM_BUDGET = 14 * 2 ** 20


def plan_predict_tiles(*, T, L1, L, F, K, depth, has_cat=False,
                       prebin=True, packed=False, row_tile=512,
                       vmem_budget=_PREDICT_VMEM_BUDGET):
    """Static VMEM-budget planner for the serving megakernel (the
    ``plan_wave_loop`` idiom: decided entirely from shapes and knobs,
    every refusal one honest reason line, the returned dict recorded
    verbatim in the BENCH record so a capture shows WHY a model ran
    fused or fell back to the staged walk).

    Prices one (row_tile, tree_tile) kernel step: the tree tile's node
    tables (seven int32 (Tt, L1) tables + the (Tt, L) f32 leaf values +
    num_leaves), the row tile's serving codes (packed: half the
    columns), the (TILE, K) scores accumulator, and the walk's live
    (TILE, Tt) int32 working set.  ``tree_tile`` is the largest tree
    count whose step fits ``vmem_budget``; a single tree that does not
    fit refuses (staged walk).  Categorical bitset decisions and the
    raw-feature walk stay staged — the megakernel serves prebinned
    numeric codes only."""
    Fc = -(-int(F) // 2) if packed else int(F)
    per_tree = (7 * int(L1) + int(L) + 1) * 4
    codes_bytes = int(row_tile) * Fc * 4       # int32-widened decode lane
    acc_bytes = int(row_tile) * max(int(K), 1) * 4
    # the walk's live per-step arrays (node pointers + gathered operands),
    # all (row_tile, tree_tile) int32 — priced at 6 concurrently-live
    def step_bytes(tt):
        return (tt * per_tree + codes_bytes + acc_bytes
                + 6 * int(row_tile) * tt * 4)

    tree_tile = max(int(T), 1)
    while tree_tile > 1 and step_bytes(tree_tile) > vmem_budget:
        tree_tile = -(-tree_tile // 2)
    n_tiles = -(-max(int(T), 1) // tree_tile)
    plan = dict(eligible=False, reason="", tree_tile=int(tree_tile),
                n_tree_tiles=int(n_tiles), t_pad=int(n_tiles * tree_tile),
                row_tile=int(row_tile),
                table_tile_bytes=int(tree_tile * per_tree),
                codes_tile_bytes=int(codes_bytes), acc_bytes=int(acc_bytes),
                total_bytes=int(step_bytes(tree_tile)),
                packed=bool(packed), vmem_budget=int(vmem_budget))
    if not prebin:
        plan["reason"] = ("raw-feature walk: the fused kernel serves "
                          "prebinned serving codes only")
        return plan
    if has_cat:
        plan["reason"] = ("categorical bitset decision stays on the "
                          "staged walk")
        return plan
    if step_bytes(tree_tile) > vmem_budget:
        plan["reason"] = (
            f"one tree's tables + working set ({step_bytes(1)} B) exceed "
            f"the VMEM budget ({int(vmem_budget)} B)")
        return plan
    plan["eligible"] = True
    return plan


def _fused_kernel(nl_ref, feat_ref, tbin_ref, zbin_ref, dl_ref, mt_ref,
                  lc_ref, rc_ref, lv_ref, codes_ref, out_ref, *, n_steps,
                  zero_code, nan_code, K, n_tree_tiles, mode, packed,
                  transform):
    """Grid: (row_tiles, tree_tiles), TREE dim innermost.  The scores
    block's index map is constant over the tree dim, so Mosaic keeps it
    resident in VMEM as a revisited accumulator (zeroed at tree tile 0),
    and the codes block — also constant over the tree dim — is copied
    from HBM once per row tile, not once per depth step.  ``mode``:

    * ``"scores"`` — (TILE, K) per-class raw-score accumulator; leaf
      values gathered and class-summed in VMEM right after the walk
      (class of global tree g is ``g % K``, iteration-major tree order).
      ``transform`` (None | 'sigmoid' | 'softmax') runs on the finished
      accumulator at the last tree tile — the objective epilogue rides
      the same launch.
    * ``"leaf"`` — the (TILE, Tt) leaf indices are written out per tree
      tile (the node-exactness pin + the f64-exact reconstruction lane).

    ``packed``: ``codes_ref`` holds 4-bit packed rows (two features per
    byte, ops/hist_pallas.pack4bit nibble layout); the decision lane
    decodes with a constant shift + select — never a data-dependent
    shift amount, which Mosaic cannot lower."""
    Tt, L1 = feat_ref.shape
    rows = codes_ref.shape[0]
    t = pl.program_id(1)

    codes = codes_ref[...].astype(jnp.int32)
    feat = feat_ref[...].reshape(-1)
    tbin = tbin_ref[...].reshape(-1)
    zbin = zbin_ref[...].reshape(-1)
    dl = dl_ref[...].reshape(-1)
    mt = mt_ref[...].reshape(-1)
    lc = lc_ref[...].reshape(-1)
    rc = rc_ref[...].reshape(-1)
    t_off = lax.broadcasted_iota(jnp.int32, (rows, Tt), 1) * L1

    def body(_, node):
        nd = jnp.maximum(node, 0)
        flat = nd + t_off                                  # (TILE, Tt)
        f = jnp.take(feat, flat, axis=0)
        if packed:
            byte = jnp.take_along_axis(codes, f >> 1, axis=1)
            b = jnp.where((f & 1) == 1, byte >> 4, byte) & 15
        else:
            b = jnp.take_along_axis(codes, f, axis=1)
        is_nan = b == nan_code
        is_zero = b == zero_code
        b0 = jnp.where(is_nan | is_zero, jnp.take(zbin, flat, axis=0), b)
        mtype = jnp.take(mt, flat, axis=0)
        is_missing = jnp.where(
            mtype == MISSING_NAN, is_nan,
            jnp.where(mtype == MISSING_ZERO, is_nan | is_zero, False))
        go_left = jnp.where(is_missing, jnp.take(dl, flat, axis=0) != 0,
                            b0 <= jnp.take(tbin, flat, axis=0))
        nxt = jnp.where(go_left, jnp.take(lc, flat, axis=0),
                        jnp.take(rc, flat, axis=0))
        return jnp.where(node >= 0, nxt, node)

    node0 = jnp.where(nl_ref[...] > 1,
                      jnp.zeros((rows, Tt), jnp.int32),
                      jnp.full((rows, Tt), -1, jnp.int32))
    node = lax.fori_loop(0, max(int(n_steps), 1), body, node0)
    leaf = -node - 1

    if mode == "leaf":
        out_ref[...] = leaf
        return

    L = lv_ref.shape[1]
    lv = lv_ref[...].reshape(-1)
    l_off = lax.broadcasted_iota(jnp.int32, (rows, Tt), 1) * L
    vals = jnp.take(lv, jnp.maximum(leaf, 0) + l_off, axis=0)
    if K == 1:
        contrib = jnp.sum(vals, axis=1, keepdims=True)
    else:
        g = t * Tt + lax.broadcasted_iota(jnp.int32, (Tt, K), 0)
        onehot = (g % K == lax.broadcasted_iota(
            jnp.int32, (Tt, K), 1)).astype(jnp.float32)
        contrib = jnp.dot(vals, onehot,
                          preferred_element_type=jnp.float32)

    @pl.when(t == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += contrib

    if transform is not None:
        @pl.when(t == n_tree_tiles - 1)
        def _epilogue():
            acc = out_ref[...]
            if transform == "sigmoid":
                out_ref[...] = 1.0 / (1.0 + jnp.exp(-acc))
            else:                                          # softmax
                mx = jnp.max(acc, axis=1, keepdims=True)
                e = jnp.exp(acc - mx)
                out_ref[...] = e / jnp.sum(e, axis=1, keepdims=True)


def serving_fused_pallas(tables, codes, *, n_steps: int, zero_code: int,
                         nan_code: int, K: int, tree_tile: int,
                         mode: str = "scores", packed: bool = False,
                         transform=None, interpret: bool = False,
                         row_tile: int = 512):
    """The serving megakernel.  ``tables`` is a ServingArrays whose tree
    axis is padded to a multiple of ``tree_tile`` (models/tree.
    pad_tree_axis — zero trees park on leaf 0 with value 0.0, so scores
    are unchanged and leaf-mode callers slice the pad away); ``codes``
    is this batch's (N, F) serving codes, or (N, ceil(F/2)) packed
    bytes.  Returns (N, K) f32 scores or (N, T_pad) int32 leaves."""
    N = codes.shape[0]
    T, L1 = tables.split_feature.shape
    L = tables.leaf_value.shape[1]
    if T % tree_tile:
        raise ValueError(f"tree axis {T} not a multiple of the tree tile "
                         f"{tree_tile} (pad with pad_tree_axis)")
    n_tt = T // tree_tile
    tile = min(row_tile, N)
    while N % tile:
        tile //= 2
    grid = (N // tile, n_tt)

    ins = (
        tables.num_leaves.reshape(1, T).astype(jnp.int32),
        tables.split_feature.astype(jnp.int32),
        tables.threshold_bin.astype(jnp.int32),
        tables.zero_bin.astype(jnp.int32),
        tables.default_left.astype(jnp.int32),
        tables.missing_type.astype(jnp.int32),
        tables.left_child.astype(jnp.int32),
        tables.right_child.astype(jnp.int32),
        tables.leaf_value.astype(jnp.float32),
    )
    in_specs = (
        [pl.BlockSpec((1, tree_tile), lambda r, t: (0, t))]
        + [pl.BlockSpec((tree_tile, L1), lambda r, t: (t, 0))
           for _ in range(7)]
        + [pl.BlockSpec((tree_tile, L), lambda r, t: (t, 0)),
           pl.BlockSpec((tile, codes.shape[1]), lambda r, t: (r, 0))]
    )
    if mode == "leaf":
        out_spec = pl.BlockSpec((tile, tree_tile), lambda r, t: (r, t))
        out_shape = jax.ShapeDtypeStruct((N, T), jnp.int32)
    else:
        out_spec = pl.BlockSpec((tile, K), lambda r, t: (r, 0))
        out_shape = jax.ShapeDtypeStruct((N, K), jnp.float32)
    kern = functools.partial(
        _fused_kernel, n_steps=n_steps, zero_code=zero_code,
        nan_code=nan_code, K=K, n_tree_tiles=n_tt, mode=mode,
        packed=packed, transform=transform)
    return pl.pallas_call(
        kern, grid=grid, in_specs=in_specs, out_specs=out_spec,
        out_shape=out_shape, interpret=interpret,
    )(*ins, codes)
