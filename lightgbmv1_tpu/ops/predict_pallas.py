"""Pallas TPU inference kernel — VMEM-pinned node tables.

The XLA depth-stepped walk (models/predict.serving_leaf_binned) re-reads
the stacked node tables from HBM on every one of its ``max_depth`` steps:
each gather of (feature, threshold-bin, children) streams the (T, L1)
tables again, and for deep ensembles the walk is table-bandwidth-bound,
not row-bound.  This kernel pins ALL node tables (feature idx, serving
threshold bin, children, zero-bin, missing routing) in VMEM once per row
tile — for a 500-tree, 255-leaf model the full table set is ~3.5 MB,
comfortably inside the ~16 MB VMEM budget — so the ``depth`` gather steps
run entirely out of on-chip memory and HBM traffic drops to the prebinned
code tile in + the leaf-index tile out.

Scope: the PREBINNED, non-categorical serving path (where the table-pin
pays; categorical ensembles ride the XLA walk).  The pure-XLA walk is the
bit-parity pin: `tests/test_predict_engine.py` pins kernel-vs-XLA leaf
equality (interpret mode on CPU), and `BatchPredictor` falls back to the
XLA walk with a warning if Mosaic cannot lower the gathers on the local
backend — `predict_method=pallas` is opt-in.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..io.binning import MISSING_NAN, MISSING_ZERO


def _kernel(nl_ref, feat_ref, tbin_ref, zbin_ref, dl_ref, mt_ref, lc_ref,
            rc_ref, codes_ref, out_ref, *, n_steps, zero_code, nan_code):
    """Grid: (row_tiles,).  All table refs hold the FULL (T, L1) arrays in
    VMEM; ``codes_ref`` is this tile's (TILE, F) serving codes."""
    T, L1 = feat_ref.shape
    rows = codes_ref.shape[0]

    codes = codes_ref[...].astype(jnp.int32)              # (TILE, F)
    feat = feat_ref[...].reshape(-1)                      # (T*L1,)
    tbin = tbin_ref[...].reshape(-1)
    zbin = zbin_ref[...].reshape(-1)
    dl = dl_ref[...].reshape(-1)
    mt = mt_ref[...].reshape(-1)
    lc = lc_ref[...].reshape(-1)
    rc = rc_ref[...].reshape(-1)
    t_off = lax.broadcasted_iota(jnp.int32, (rows, T), 1) * L1

    def body(_, node):
        nd = jnp.maximum(node, 0)
        flat = nd + t_off                                  # (TILE, T)
        f = jnp.take(feat, flat, axis=0)
        b = jnp.take_along_axis(codes, f, axis=1)
        is_nan = b == nan_code
        is_zero = b == zero_code
        b0 = jnp.where(is_nan | is_zero, jnp.take(zbin, flat, axis=0), b)
        mtype = jnp.take(mt, flat, axis=0)
        is_missing = jnp.where(
            mtype == MISSING_NAN, is_nan,
            jnp.where(mtype == MISSING_ZERO, is_nan | is_zero, False))
        go_left = jnp.where(is_missing, jnp.take(dl, flat, axis=0) != 0,
                            b0 <= jnp.take(tbin, flat, axis=0))
        nxt = jnp.where(go_left, jnp.take(lc, flat, axis=0),
                        jnp.take(rc, flat, axis=0))
        return jnp.where(node >= 0, nxt, node)

    node0 = jnp.where(nl_ref[...] > 1,
                      jnp.zeros((rows, T), jnp.int32),
                      jnp.full((rows, T), -1, jnp.int32))
    node = lax.fori_loop(0, max(int(n_steps), 1), body, node0)
    out_ref[...] = -node - 1


def serving_leaf_pallas(arrays, codes, *, n_steps: int, zero_code: int,
                        nan_code: int, interpret: bool = False,
                        row_tile: int = 512):
    """(N, F) serving codes -> (N, T) leaf indices, node tables pinned in
    VMEM.  ``N`` must be a multiple of the row tile after the caller's
    bucket padding (buckets are powers of two >= 256, so any power-of-two
    tile <= N divides it)."""
    N, _ = codes.shape
    T, L1 = arrays.split_feature.shape
    tile = min(row_tile, N)
    while N % tile:
        tile //= 2
    grid = (N // tile,)

    def full(a, dtype=jnp.int32):
        return a.astype(dtype)

    tables = (
        full(arrays.num_leaves.reshape(1, T)),
        full(arrays.split_feature),
        full(arrays.threshold_bin),
        full(arrays.zero_bin),
        full(arrays.default_left),
        full(arrays.missing_type),
        full(arrays.left_child),
        full(arrays.right_child),
    )
    table_specs = [pl.BlockSpec(t.shape, lambda i: (0, 0)) for t in tables]
    kern = functools.partial(_kernel, n_steps=n_steps, zero_code=zero_code,
                             nan_code=nan_code)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=table_specs + [
            pl.BlockSpec((tile, codes.shape[1]), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tile, T), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, T), jnp.int32),
        interpret=interpret,
    )(*tables, codes)
