"""Best-split search over histograms.

TPU-native re-design of the reference split finding
(``FeatureHistogram::FindBestThresholdSequentially``,
src/treelearner/feature_histogram.hpp:855-1056, and the gain math
``GetSplitGains``/``CalculateSplittedLeafOutput``/``ThresholdL1``
feature_histogram.hpp:734-782).

The reference scans each feature's bins twice sequentially (forward scan =
missing defaults right; reverse scan = missing defaults left).  Here both
directions are expressed as cumulative sums over the bin axis and evaluated
for **all features, all bins, both directions at once** — a handful of
vectorized ops + one argmax, no sequential loop.  This runs per-leaf and is
vmapped over the tree frontier.

Differences from the reference:
* No most-freq-bin offset arithmetic — histograms store every bin densely
  (see ops/histogram.py), so the reference's ``FixHistogram``
  (src/io/dataset.cpp:1410) has no equivalent here.
* Counts are exact fp32 sums instead of the reference's
  ``RoundInt(sum_hess * cnt_factor)`` estimate (feature_histogram.hpp:885);
  min_data_in_leaf gating is therefore exact.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO

NEG_INF = -jnp.inf

# Near-tie tolerance of the split argmax (relative to the gain scale).
# Distributed histograms are f32 reductions whose summation ORDER differs
# between the serial sum, lax.psum and lax.psum_scatter; candidate gains
# therefore carry reduction-order noise of a few f32 ulps of the LEAF GAIN
# terms they are differences of (the shift/parent-gain magnitude — the
# final gain itself can be arbitrarily small through cancellation).
# Candidates within ``TIE_RTOL * (|shift| + |best|)`` of the best are
# treated as TIED and resolved by the deterministic preference order
# (reference scan-order within a feature, lowest feature id across
# features), which makes the chosen split invariant to reduction order and
# device count — the fix for the psum-summation-order near-tie threshold
# flips tests/test_parallel.py[data] exposed.  The band is ~30 f32 ulps:
# far below any gain gap the reference itself could distinguish, so the
# golden-parity fixtures are unaffected.
TIE_RTOL = 4e-6


def tie_tol(best_gain, scale):
    """Absolute gain tolerance under which two split candidates count as
    tied.  ``scale`` is the leaf-gain magnitude the candidate gains were
    differenced against (the parent-gain shift); ``best_gain`` may be
    -inf (no candidate), which contributes nothing."""
    b = jnp.where(jnp.isfinite(best_gain), jnp.abs(best_gain), 0.0)
    return TIE_RTOL * (jnp.abs(scale) + b)


def go_left_rule(bins, thr, dl, mt, nan_bin, zero_bin):
    """The committed numerical split's go-left decision on raw bin ids —
    bin compare plus the NaN/zero missing-direction rules (reference
    ``NumericalBin::data + missing-type dispatch``, dense_bin.hpp:85-140).

    All inputs broadcast (``bins`` is int32 bin ids, the rest per-split
    scalars or column vectors; ``dl`` bool, ``mt``/``nan_bin``/
    ``zero_bin`` int32).  Pure integer/bool ops — exact everywhere, so
    the staged (S, N) partition pass (models/grower_wave.py
    ``go_left_s``), the deferred valid-routing drain (``route_pending``)
    and the fused megakernel's in-VMEM routing stage
    (ops/wave_fused.py ``route_tile``) all evaluate the SAME code
    object: the decision cannot drift between the paths.  Categorical
    bitset membership stays with the callers that support it (the fused
    gate excludes categorical datasets)."""
    na = ((mt == MISSING_NAN) & (bins == nan_bin)) | (
        (mt == MISSING_ZERO) & (bins == zero_bin))
    return jnp.where(na, dl, bins <= thr)


class SplitParams(NamedTuple):
    """Static-ish regularization parameters (traced scalars are fine too)."""

    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    # categorical split parameters (reference config.h / feature_histogram.hpp)
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: float = 100.0
    # path smoothing (reference CalculateSplittedLeafOutput USE_SMOOTHING,
    # feature_histogram.hpp:756-760) and extremely-randomized trees
    path_smooth: float = 0.0
    extra_trees: bool = False
    extra_seed: int = 0       # offsets the extra_trees threshold stream
                              # (reference config.h extra_seed)
    # cost-effective gradient boosting (reference
    # cost_effective_gradient_boosting.hpp:22 DetlaGain)
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0


class SplitResult(NamedTuple):
    gain: jax.Array          # relative gain (already minus parent gain and
                             # min_gain_to_split); <= 0 means "don't split"
    feature: jax.Array       # int32
    threshold_bin: jax.Array  # int32 — rows with bin <= threshold_bin go left
    default_left: jax.Array  # bool — missing-value direction
    left_sum: jax.Array      # (3,) [grad, hess, count]
    right_sum: jax.Array     # (3,)
    is_cat: jax.Array        # bool — categorical (bitset) split
    cat_bitset: jax.Array    # (W,) uint32 — bin-space membership bitset
                             # (W = ceil(num_bins/32)); bins in the set go left


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    """reference: ThresholdL1, feature_histogram.hpp:734."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_gain(g: jax.Array, h: jax.Array, p: SplitParams) -> jax.Array:
    """reference: GetLeafGain, feature_histogram.hpp:823-839.

    With ``max_delta_step > 0`` (USE_MAX_OUTPUT) the reference evaluates the
    gain AT the clamped output via GetLeafGainGivenOutput instead of the
    closed form — the closed form would overstate the gain of leaves whose
    unconstrained optimum exceeds the clamp (feature_histogram.hpp:833-838).
    The smoothing counterpart lives in the callers (smooth_output needs the
    leaf count, which this signature doesn't carry)."""
    if isinstance(p.max_delta_step, (int, float)) and p.max_delta_step <= 0:
        t = threshold_l1(g, p.lambda_l1)
        return (t * t) / (h + p.lambda_l2)
    return leaf_gain_given_output(g, h, leaf_output(g, h, p), p)


def leaf_output(g: jax.Array, h: jax.Array, p: SplitParams) -> jax.Array:
    """reference: CalculateSplittedLeafOutput, feature_histogram.hpp:740-778."""
    out = -threshold_l1(g, p.lambda_l1) / (h + p.lambda_l2)
    if isinstance(p.max_delta_step, (int, float)) and p.max_delta_step <= 0:
        return out
    return jnp.where(
        jnp.asarray(p.max_delta_step) > 0,
        jnp.clip(out, -p.max_delta_step, p.max_delta_step),
        out,
    )


class FeatureMeta(NamedTuple):
    """Per-feature binning metadata consumed by the split finder; built once
    per dataset from the BinMappers (host) and shipped to device."""

    num_bins: jax.Array       # (F,) int32
    missing_type: jax.Array   # (F,) int32
    nan_bin: jax.Array        # (F,) int32 (-1 if none)
    zero_bin: jax.Array       # (F,) int32
    is_categorical: jax.Array  # (F,) bool
    usable: jax.Array         # (F,) bool — not trivial
    monotone_type: jax.Array  # (F,) int32 — -1 / 0 / +1 constraint direction
    contri: Optional[jax.Array] = None  # (F,) f32 feature_contri gain
                              # multipliers (reference FeatureMetainfo::penalty,
                              # feature_histogram.hpp:32,94,1139) or None


def make_feature_meta(dataset, monotone_constraints=None,
                      feature_contri=None) -> FeatureMeta:
    F = len(dataset.num_bins)
    mono = np.zeros(F, np.int32)
    if monotone_constraints:
        mc = np.asarray(list(monotone_constraints), np.int32)
        mono[: min(F, len(mc))] = mc[:F]
    contri = None
    if feature_contri:
        contri = np.ones(F, np.float32)
        fc = np.asarray(list(feature_contri), np.float32)
        contri[: min(F, len(fc))] = fc[:F]
        contri = jnp.asarray(contri)
    return FeatureMeta(
        num_bins=jnp.asarray(dataset.num_bins, jnp.int32),
        missing_type=jnp.asarray(dataset.missing_types, jnp.int32),
        nan_bin=jnp.asarray(dataset.nan_bins, jnp.int32),
        zero_bin=jnp.asarray(dataset.zero_bins, jnp.int32),
        is_categorical=jnp.asarray(dataset.is_categorical),
        usable=jnp.asarray(~dataset.is_trivial),
        monotone_type=jnp.asarray(mono),
        contri=contri,
    )


NO_CONSTRAINT = (-3.0e38, 3.0e38)   # f32-max-ish; reference uses double max


def leaf_gain_given_output(g, h, out, p: SplitParams):
    """reference: GetLeafGainGivenOutput, feature_histogram.hpp — the gain
    of a leaf forced to emit ``out`` (equals leaf_gain at the unconstrained
    optimum)."""
    t = threshold_l1(g, p.lambda_l1)
    return -(2.0 * t * out + (h + p.lambda_l2) * out * out)


def smooth_output(raw_out, count, parent_output, p: SplitParams):
    """Path smoothing (reference feature_histogram.hpp:756-760):
    ``out*(n/a)/(n/a+1) + parent/(n/a+1)`` with a = path_smooth."""
    w = count / p.path_smooth
    return raw_out * w / (w + 1.0) + parent_output / (w + 1.0)


def child_leaf_output(sums, constr, parent_out, p: SplitParams,
                      use_mc: bool = False):
    """One frontier child's (possibly smoothed / clamped) leaf output from
    its (g, h, c) sums — the wave grower's per-round ``clamp_out`` math,
    factored here so the grower bookkeeping and the persistent wave-loop
    kernel (ops/wave_fused.make_fused_wave_loop) run the SAME op sequence;
    the loop's bit-parity contract rides on sharing this code object."""
    out = leaf_output(sums[0], sums[1], p)
    if p.path_smooth > 0:
        out = smooth_output(out, sums[2], parent_out, p)
    if not use_mc:
        return out
    return jnp.clip(out, constr[0], constr[1])


def monotone_penalty_factor(depth, penalization):
    """reference: ComputeMonotoneSplitGainPenalty,
    monotone_constraints.hpp:66-76."""
    eps = 1e-10
    d = depth.astype(jnp.float32) if hasattr(depth, "astype") else float(depth)
    small = 1.0 - penalization / (2.0 ** d) + eps
    large = 1.0 - 2.0 ** (penalization - 1.0 - d) + eps
    out = jnp.where(penalization <= 1.0, small, large)
    return jnp.where(penalization >= d + 1.0, eps, out)


def _pack_bitset(member: jax.Array, num_bins: int) -> jax.Array:
    """(B,) bool membership -> (ceil(B/32),) uint32 bitset words."""
    W = -(-num_bins // 32)
    pad = W * 32 - num_bins
    m = jnp.pad(member.astype(jnp.uint32), (0, pad)).reshape(W, 32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return (m << shifts).sum(axis=1).astype(jnp.uint32)


def bitset_contains(bitset: jax.Array, bins: jax.Array) -> jax.Array:
    """Vectorized FindInBitset (reference include/LightGBM/utils/common.h):
    bitset (..., W) uint32, bins (...,) int — True where bit is set."""
    b = bins.astype(jnp.int32)
    word = jnp.take_along_axis(
        bitset, (b[..., None] >> 5).astype(jnp.int32), axis=-1)[..., 0]
    return ((word >> (b.astype(jnp.uint32) & 31)) & 1) == 1


def _cat_split_gain(lg, lh, rg, rh, lc, rc, p, constraint, parent_output,
                    use_mc, use_smooth):
    """GetSplitGains<USE_MC, USE_SMOOTHING> for categorical candidates
    (reference feature_histogram.hpp:350-355,450-456): leaf outputs smoothed
    toward the parent and clamped to the leaf's [min, max] bound; no monotone
    direction check — categorical features cannot carry monotone constraints
    (dataset_loader.cpp:569 fatals on that combination)."""
    if not use_mc and not use_smooth:
        return leaf_gain(lg, lh, p) + leaf_gain(rg, rh, p)
    out_l = leaf_output(lg, lh, p)
    out_r = leaf_output(rg, rh, p)
    if use_smooth:
        out_l = smooth_output(out_l, lc, parent_output, p)
        out_r = smooth_output(out_r, rc, parent_output, p)
    if use_mc:
        out_l = jnp.clip(out_l, constraint[0], constraint[1])
        out_r = jnp.clip(out_r, constraint[0], constraint[1])
    return (leaf_gain_given_output(lg, lh, out_l, p)
            + leaf_gain_given_output(rg, rh, out_r, p))


def _best_categorical(hist, parent_sum, meta, feature_mask, params,
                      shift=0.0, constraint=None, parent_output=0.0,
                      rand_key=None, cegb_penalty=None):
    """Best categorical split across all features of one leaf.

    reference: FindBestThresholdCategoricalInner,
    src/treelearner/feature_histogram.hpp:278-460 — one-vs-rest for features
    with few categories (max_cat_to_onehot), otherwise a two-direction scan
    over bins sorted by grad/(hess+cat_smooth) with cat_l2 regularization and
    min_data_per_group batching.  Returned gains are RELATIVE (minus
    ``shift`` = parent gain + min_gain_to_split) with the per-feature
    ``meta.contri`` penalty applied, matching ``output->gain`` after
    FindBestThreshold (feature_histogram.hpp:94).

    Deviation from the reference: the trailing "other/unseen/NaN" bin of a
    categorical feature is never placed in the left (in-set) side, so the
    bin-space decision used in training is always exactly expressible as a
    raw-category bitset in the v3 model format (unseen categories at
    prediction time go right, like the reference's FindInBitset miss).
    """
    F, B, _ = hist.shape
    eps = 1e-15
    use_mc = constraint is not None
    use_smooth = params.path_smooth > 0
    if constraint is None:
        constraint = jnp.asarray(NO_CONSTRAINT, jnp.float32)
    g, h, c = hist[..., 0], hist[..., 1], hist[..., 2]
    total_g, total_h, total_c = parent_sum[0], parent_sum[1], parent_sum[2]
    t_idx = lax.broadcasted_iota(jnp.int32, (F, B), 1)
    nb = meta.num_bins[:, None]
    fmask = (feature_mask & meta.usable & meta.is_categorical)[:, None]
    # exclude the trailing other/unseen bin from left-set membership
    bin_ok = (t_idx < nb - 1) & fmask
    use_onehot = (nb <= params.max_cat_to_onehot)
    use_rand = params.extra_trees and rand_key is not None
    if use_rand:
        ku = jax.random.uniform(jax.random.fold_in(rand_key, 7), (2, F))

    # ---- one-vs-rest (reference :316-369) --------------------------------
    oth_g, oth_h, oth_c = total_g - g, total_h - h, total_c - c
    ok1 = (
        bin_ok & use_onehot
        & (c >= params.min_data_in_leaf)
        & (h >= params.min_sum_hessian_in_leaf)
        & (oth_c >= params.min_data_in_leaf)
        & (oth_h - eps >= params.min_sum_hessian_in_leaf)
    )
    if use_rand:
        # USE_RAND (reference :316-318,344-348): only one random bin per
        # feature is evaluated
        rb1 = (ku[0] * jnp.maximum(meta.num_bins - 1, 1)
               ).astype(jnp.int32)[:, None]
        ok1 = ok1 & (t_idx == rb1)
    gain1 = _cat_split_gain(g, h + eps, oth_g, oth_h - eps, c, oth_c,
                            params, constraint, parent_output,
                            use_mc, use_smooth) - shift
    if meta.contri is not None:
        gain1 = gain1 * meta.contri[:, None]
    if cegb_penalty is not None:
        gain1 = gain1 - cegb_penalty[:, None]
    gain1 = jnp.where(ok1, gain1, NEG_INF)

    # ---- sorted two-direction scan (reference :371-470) ------------------
    l2cat = params._replace(lambda_l2=params.lambda_l2 + params.cat_l2)
    valid = bin_ok & (~use_onehot) & (c >= params.cat_smooth)
    ratio = jnp.where(valid, g / (h + params.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=1)                 # (F, B) valid first
    used_bin = valid.sum(axis=1)                       # (F,)
    sg = jnp.take_along_axis(g, order, axis=1)
    sh = jnp.take_along_axis(h, order, axis=1)
    sc = jnp.take_along_axis(c, order, axis=1)
    # backward direction: positions used_bin-1, used_bin-2, ...
    bwd_idx = jnp.clip(used_bin[:, None] - 1 - t_idx, 0, B - 1)
    sg2 = jnp.stack([sg, jnp.take_along_axis(sg, bwd_idx, axis=1)])  # (2,F,B)
    sh2 = jnp.stack([sh, jnp.take_along_axis(sh, bwd_idx, axis=1)])
    sc2 = jnp.stack([sc, jnp.take_along_axis(sc, bwd_idx, axis=1)])
    clg = jnp.cumsum(sg2, axis=2)
    clh = jnp.cumsum(sh2, axis=2) + eps
    clc = jnp.cumsum(sc2, axis=2)
    crg, crh, crc = total_g - clg, total_h - clh, total_c - clc

    max_num_cat = jnp.minimum(params.max_cat_threshold, (used_bin + 1) // 2)
    pos_ok = (
        (t_idx[None] < max_num_cat[None, :, None])
        & (t_idx[None] < used_bin[None, :, None])
        & (clc >= params.min_data_in_leaf)
        & (clh >= params.min_sum_hessian_in_leaf)
        & (crc >= params.min_data_in_leaf)
        & (crc >= params.min_data_per_group)
        & (crh >= params.min_sum_hessian_in_leaf)
    )
    if use_rand:
        # USE_RAND (reference :400-404,448-451): one random prefix position,
        # shared by both scan directions; NextInt(0, max_threshold) is
        # half-open, so positions are drawn from [0, max_threshold)
        max_thr = jnp.maximum(jnp.minimum(max_num_cat, used_bin) - 1, 0)
        rp = (ku[1] * jnp.maximum(max_thr, 1)).astype(jnp.int32)
        pos_ok = pos_ok & (t_idx[None] == rp[None, :, None])

    # min_data_per_group batching: evaluate a prefix only when >= mdpg rows
    # accumulated since the previous evaluated prefix (reference
    # cnt_cur_group) — the single sequential piece, scanned over positions.
    n_steps = min(B, int(params.max_cat_threshold))

    def grp_step(grp, i):
        grp = grp + sc2[:, :, i]
        can = pos_ok[:, :, i] & (grp >= params.min_data_per_group)
        return jnp.where(can, 0.0, grp), can

    _, can_eval = lax.scan(grp_step, jnp.zeros((2, F)), jnp.arange(n_steps))
    can_eval = jnp.moveaxis(can_eval, 0, 2)            # (2, F, n_steps)
    can_eval = jnp.pad(can_eval, ((0, 0), (0, 0), (0, B - n_steps)))

    gain2 = _cat_split_gain(clg, clh, crg, crh, clc, crc, l2cat,
                            constraint, parent_output,
                            use_mc, use_smooth) - shift
    if meta.contri is not None:
        gain2 = gain2 * meta.contri[None, :, None]
    if cegb_penalty is not None:
        gain2 = gain2 - cegb_penalty[None, :, None]
    gain2 = jnp.where(can_eval, gain2, NEG_INF)        # (2, F, B)

    # ---- pick the best categorical candidate -----------------------------
    flat = jnp.concatenate([gain1.reshape(-1), gain2.reshape(-1)])
    best = jnp.argmax(flat)
    best_gain = flat[best]
    from_onehot = best < F * B
    idx2 = jnp.maximum(best - F * B, 0)
    direction = (idx2 // (F * B)).astype(jnp.int32)    # 0 fwd, 1 bwd
    feat = jnp.where(from_onehot, (best // B) % F, (idx2 // B) % F).astype(jnp.int32)
    pos = jnp.where(from_onehot, best % B, idx2 % B).astype(jnp.int32)

    left1 = hist[feat, pos] + jnp.array([0.0, eps, 0.0])
    left2 = jnp.stack([clg[direction, feat, pos],
                       clh[direction, feat, pos],
                       clc[direction, feat, pos]])
    left = jnp.where(from_onehot, left1, left2)

    # membership: one-hot -> the single bin; sorted -> prefix of the order
    pos_iota = t_idx[0]                                # (B,)
    ub = used_bin[feat]
    member_pos = jnp.where(direction == 0,
                           pos_iota <= pos,
                           (pos_iota >= ub - 1 - pos) & (pos_iota < ub))
    member_sorted = jnp.zeros(B, bool).at[order[feat]].set(member_pos)
    member_bins = jnp.where(from_onehot, pos_iota == pos, member_sorted)
    bitset = _pack_bitset(member_bins, B)

    return best_gain, feat, left, bitset


def _no_cat_result(num_bins: int):
    W = -(-num_bins // 32)
    return jnp.zeros(W, jnp.uint32)


def find_best_split(
    hist: jax.Array,          # (F, B, 3) — [sum_grad, sum_hess, count]
    parent_sum: jax.Array,    # (3,)
    meta: FeatureMeta,
    feature_mask: jax.Array,  # (F,) bool — col-sampled usable features
    params: SplitParams,
    constraint: Optional[jax.Array] = None,  # (2,) [min, max] leaf output bound
    depth=0,                  # leaf depth (monotone_penalty)
    monotone_penalty: float = 0.0,
    parent_output=0.0,        # this leaf's current output (path smoothing)
    rand_key: Optional[jax.Array] = None,    # extra_trees threshold sampling
    cegb_penalty: Optional[jax.Array] = None,  # (F,) CEGB gain penalty
    hist_scale: Optional[jax.Array] = None,  # (3,) dequant multipliers when
                              # ``hist`` carries QUANTIZED integer counts
) -> SplitResult:
    with jax.named_scope("lgbm.split"):
        return _find_best_split(hist, parent_sum, meta, feature_mask, params,
                                constraint, depth, monotone_penalty,
                                parent_output, rand_key, cegb_penalty,
                                hist_scale)


def scan_left_sums(hist, meta, hist_scale=None):
    """Phase 1 of the fused split scan: ONE cumulative-sum pass over the
    bin axis plus the missing-mass adjustments, both scan directions
    stacked into a single ``(2, F, B, 3)`` tensor (direction 0 =
    missing/default right, direction 1 = missing joins the left side).

    Dequantize-aware (stochastic-rounded int8 histograms,
    ops/quantize.py): ``hist`` holds exact integer counts and
    ``hist_scale`` the per-channel dequant multipliers.  The cumsum runs
    in the INTEGER domain — exact, no f32 summation-order noise — and
    ONE broadcast multiply dequantizes the prefix sums; the same scale
    lands on the nan/zero missing-mass rows below.  The histogram is
    consumed straight from HBM in quantized form: no separate
    dequantization pass ever writes a real-valued copy back.

    Returns ``(left2, hist)`` where ``hist`` is the (dequantized) input
    for the point reads the categorical search and the missing-direction
    bookkeeping still need.  Module-level so tools/phase_attrib.py can
    time exactly this sub-phase of the scan the grower runs."""
    F, B, _ = hist.shape
    cum = jnp.cumsum(hist, axis=1)                    # (F, B, 3) inclusive
    if hist_scale is not None:
        cum = cum * hist_scale[None, None, :]
        hist = hist * hist_scale[None, None, :]       # point reads below
    t_idx = lax.broadcasted_iota(jnp.int32, (F, B), 1)

    nan_contrib = jnp.take_along_axis(
        hist,
        jnp.maximum(meta.nan_bin, 0)[:, None, None].repeat(3, axis=2),
        axis=1,
    )[:, 0, :]                                        # (F, 3)
    is_nan_f = (meta.missing_type == MISSING_NAN)[:, None]     # (F, 1)
    is_zero_f = (meta.missing_type == MISSING_ZERO)[:, None]   # (F, 1)

    # MISSING_ZERO: the reference's two scans SKIP the default (zero) bin
    # while accumulating (FindBestThresholdSequentially SKIP_DEFAULT_BIN,
    # feature_histogram.hpp:879-882,968-971), so the zero-bin mass rides
    # with the missing direction — left in the reverse scan, right in the
    # forward scan — INDEPENDENT of where the threshold falls relative to
    # the zero bin.
    zero_contrib = jnp.take_along_axis(
        hist, meta.zero_bin[:, None, None].repeat(3, axis=2),
        axis=1)[:, 0, :]                              # (F, 3)
    zb = meta.zero_bin[:, None]                       # (F, 1)

    # direction 0: missing/default right (forward scan)
    left_a = cum - jnp.where(
        (is_zero_f & (t_idx >= zb))[..., None], zero_contrib[:, None, :], 0.0)
    # direction 1: missing joins the left side (reverse scan equivalent)
    left_b = cum + jnp.where(
        is_nan_f[..., None], nan_contrib[:, None, :],
        jnp.where((is_zero_f & (t_idx < zb))[..., None],
                  zero_contrib[:, None, :], 0.0))
    return jnp.stack([left_a, left_b]), hist          # (2, F, B, 3)


def gain_shift(parent_sum, parent_output, params):
    """The gain baseline every candidate is differenced against: parent
    gain (at the smoothed current output when path smoothing is on) plus
    ``min_gain_to_split``.  One function so the staged scan
    (:func:`scan_direction_gains`) and the fused wave-round kernel's
    outside-the-kernel tie band (ops/wave_fused.py) cannot drift."""
    total_g, total_h = parent_sum[0], parent_sum[1]
    if params.path_smooth > 0:
        # reference: with smoothing the gain shift is the leaf's gain AT
        # its current (already-smoothed) output value
        parent_gain = leaf_gain_given_output(total_g, total_h,
                                             parent_output, params)
    else:
        parent_gain = leaf_gain(total_g, total_h, params)
    return parent_gain + params.min_gain_to_split


def scan_direction_gains(left2, parent_sum, meta, feature_mask, params,
                         constraint=None, depth=0, monotone_penalty=0.0,
                         parent_output=0.0, rand_key=None,
                         cegb_penalty=None, use_mc=None):
    """Phase 2 of the fused split scan: gains of every (direction,
    feature, bin) candidate in ONE stacked evaluation over the
    ``(2, F, B, 3)`` left sums from :func:`scan_left_sums` — the gain
    math (leaf_gain / smoothing / monotone clamps) is traced once on the
    doubled tensor instead of once per direction, so the whole
    cumsum → gain chain lowers as a single fused pass.

    ``use_mc`` overrides the monotone-constraint probe for callers whose
    ``meta`` arrays are traced values (the fused wave-round kernel reads
    its per-feature-block meta slices from kernel refs, where the
    ``np.asarray`` probe below cannot run); ``None`` derives it from the
    concrete meta as before.

    Returns ``(gains (2, F, B), shift)`` with gains RELATIVE (shift =
    parent gain + min_gain_to_split already subtracted) and every
    penalty applied.  Module-level for tools/phase_attrib.py."""
    _, F, B, _ = left2.shape
    total_g, total_h, total_c = parent_sum[0], parent_sum[1], parent_sum[2]
    if use_mc is None:
        use_mc = bool(np.asarray(meta.monotone_type).any())
    use_smooth = params.path_smooth > 0
    if constraint is None:
        constraint = jnp.asarray(NO_CONSTRAINT, jnp.float32)
    t_idx = lax.broadcasted_iota(jnp.int32, (F, B), 1)
    nb = meta.num_bins[:, None]                       # (F, 1)
    is_nan_f = (meta.missing_type == MISSING_NAN)[:, None]     # (F, 1)
    is_zero_f = (meta.missing_type == MISSING_ZERO)[:, None]   # (F, 1)
    has_miss_dir = is_nan_f | is_zero_f

    def eval_direction(left):
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = total_g - lg, total_h - lh, total_c - lc
        ok = (
            (lc >= params.min_data_in_leaf)
            & (rc >= params.min_data_in_leaf)
            & (lh >= params.min_sum_hessian_in_leaf)
            & (rh >= params.min_sum_hessian_in_leaf)
        )
        if not use_mc and not use_smooth:
            gain = leaf_gain(lg, lh, params) + leaf_gain(rg, rh, params)
            return jnp.where(ok, gain, NEG_INF)
        # constrained/smoothed mode (reference: GetSplitGains with USE_MC /
        # USE_SMOOTHING, feature_histogram.hpp:782-830): leaf outputs are
        # smoothed toward the parent's output and clamped to the leaf's
        # [min, max] bound; the gain is evaluated at those outputs, and a
        # split violating the feature's monotone direction is rejected.
        out_l = leaf_output(lg, lh, params)
        out_r = leaf_output(rg, rh, params)
        if use_smooth:
            out_l = smooth_output(out_l, lc, parent_output, params)
            out_r = smooth_output(out_r, rc, parent_output, params)
        if use_mc:
            out_l = jnp.clip(out_l, constraint[0], constraint[1])
            out_r = jnp.clip(out_r, constraint[0], constraint[1])
        gain = (leaf_gain_given_output(lg, lh, out_l, params)
                + leaf_gain_given_output(rg, rh, out_r, params))
        if use_mc:
            mono = meta.monotone_type[:, None]         # (F, 1)
            violates = ((mono > 0) & (out_l > out_r)) | (
                (mono < 0) & (out_l < out_r))
            ok = ok & (~violates)
        return jnp.where(ok, gain, NEG_INF)

    numerical_ok = feature_mask[:, None] & meta.usable[:, None] & (
        ~meta.is_categorical[:, None])
    base_valid = (t_idx <= nb - 2) & numerical_ok
    if params.extra_trees and rand_key is not None:
        # extremely-randomized trees (reference USE_RAND: one random
        # threshold per feature per node, feature_histogram.hpp:919-930)
        u = jax.random.uniform(rand_key, (F,))
        rand_bin = (u * jnp.maximum(meta.num_bins - 1, 1)).astype(jnp.int32)
        base_valid = base_valid & (t_idx == rand_bin[:, None])
    # both directions masked and evaluated in one shot: direction 1 only
    # exists for features with a missing direction
    valid2 = jnp.stack([base_valid, base_valid & has_miss_dir])
    gains2 = jnp.where(valid2, eval_direction(left2), NEG_INF)

    shift = gain_shift(parent_sum, parent_output, params)

    # Work in RELATIVE gains from here on — the reference's output->gain is
    # best_gain - min_gain_shift, and every penalty below operates on that
    # relative value (ComputeBestSplitForFeature,
    # serial_tree_learner.cpp:701-736):
    #   1. feature_contri multiply (inside FindBestThreshold,
    #      feature_histogram.hpp:94)
    #   2. CEGB DetlaGain subtract (serial_tree_learner.cpp:723-727)
    #   3. monotone depth-penalty multiply (:728-732)
    gains = gains2 - shift                            # (2, F, B)
    finite = jnp.isfinite(gains)
    if meta.contri is not None:
        gains = jnp.where(finite, gains * meta.contri[None, :, None], gains)
    if cegb_penalty is not None:
        gains = jnp.where(finite, gains - cegb_penalty[None, :, None], gains)
    if use_mc and monotone_penalty > 0:
        factor = monotone_penalty_factor(jnp.asarray(depth), monotone_penalty)
        mono_f = (meta.monotone_type != 0)[None, :, None]
        gains = jnp.where(finite & mono_f, gains * factor, gains)
    return gains, shift


def scan_pick_feature(gains, shift, meta):
    """Per-feature stage of the tie-band preference argmax: each
    feature's best candidate gain over its ``2B`` (direction, bin) slots
    plus the preferred in-band candidate index.  Returns
    ``(fbest (F,), sel_f (F,))`` with ``sel_f`` encoding
    ``direction * B + threshold``.

    Split out of :func:`scan_pick` so the fused wave-round kernel
    (ops/wave_fused.py) can run EXACTLY this reduction per feature block
    in VMEM and emit only the O(F) residue — the cross-feature band
    needs the global best, so that half stays outside the kernel — while
    the staged path composes the same code object."""
    _, F, B = gains.shape
    t_idx = lax.broadcasted_iota(jnp.int32, (F, B), 1)
    rev_like_a = ((meta.missing_type == MISSING_NONE)
                  | (meta.num_bins <= 2))[:, None]        # (F, 1)
    pref_a = jnp.where(rev_like_a, 2 * B + t_idx, B - 1 - t_idx)
    pref_b = jnp.broadcast_to(2 * B + t_idx, (F, B))
    gains_f = jnp.concatenate([gains[0], gains[1]], axis=1)   # (F, 2B)
    pref_f = jnp.concatenate([pref_a, pref_b], axis=1)        # (F, 2B)
    fbest = gains_f.max(axis=1)                               # (F,)
    # near-tie band (tie_tol above): every candidate within the band of
    # its feature's best competes on the deterministic preference order
    # alone, so reduction-order ulp noise cannot flip the pick
    tol_f = tie_tol(fbest, shift)                             # (F,)
    sel_f = jnp.argmax(
        jnp.where(gains_f >= (fbest - tol_f)[:, None], pref_f, -1),
        axis=1)                                               # (F,)
    return fbest, sel_f


def scan_pick(gains, shift, meta):
    """Phase 3 of the fused split scan: the tie-band preference argmax.

    Tie-breaking (matters when gains plateau, e.g. under max_delta_step
    clamping).  The reference evaluates the REVERSE scan first and the
    forward scan replaces only on strictly greater gain
    (FuncForNumricalL3, feature_histogram.hpp:157-215), and each scan
    keeps the FIRST candidate seen (`current_gain > best_gain`,
    :928,1002): reverse = highest threshold, forward = lowest.  For
    missing-none (or 2-bin) features only the reverse scan runs, so our
    direction-0 candidates inherit its highest-threshold preference.
    Cross-feature ties pick the smaller feature (SplitInfo::operator>,
    split_info.hpp:147-152) — argmax first-occurrence order below.

    Returns ``(best_gain, feature, threshold, direction)``.  Module-level
    for tools/phase_attrib.py."""
    _, F, B = gains.shape
    fbest, sel_f = scan_pick_feature(gains, shift, meta)
    gains_f = jnp.concatenate([gains[0], gains[1]], axis=1)   # (F, 2B)
    gbest = jnp.max(fbest)
    feature = jnp.argmax(fbest >= gbest - tie_tol(gbest, shift)) \
        .astype(jnp.int32)                   # first in band = min feature
    sel = sel_f[feature]
    best_gain = gains_f[feature, sel]
    direction = (sel // B).astype(jnp.int32)
    threshold = (sel % B).astype(jnp.int32)
    return best_gain, feature, threshold, direction


def _find_best_split(
    hist, parent_sum, meta, feature_mask, params, constraint=None, depth=0,
    monotone_penalty=0.0, parent_output=0.0, rand_key=None, cegb_penalty=None,
    hist_scale=None,
) -> SplitResult:
    # One fused scan pass (round-7 split-phase burn-down): cumsum +
    # missing-mass adjust (scan_left_sums, dequantize fold included) →
    # stacked both-direction gain evaluation (scan_direction_gains) →
    # tie-band preference argmax (scan_pick).  The three stages are
    # module-level so the phase-attribution harness times the exact code
    # objects this search runs; candidate values are bit-identical to the
    # historical per-direction evaluation (same formulas, elementwise).
    F, B, _ = hist.shape
    use_mc = bool(np.asarray(meta.monotone_type).any())
    if constraint is None:
        constraint = jnp.asarray(NO_CONSTRAINT, jnp.float32)

    left2, hist = scan_left_sums(hist, meta, hist_scale)
    gains, shift = scan_direction_gains(
        left2, parent_sum, meta, feature_mask, params, constraint, depth,
        monotone_penalty, parent_output, rand_key, cegb_penalty)
    best_gain, feature, threshold, direction = scan_pick(gains, shift, meta)

    left = left2[direction, feature, threshold]

    # categorical candidates (compiled in only when the dataset has any —
    # meta arrays are trace-time constants via the grower closure)
    has_cat = bool(np.asarray(meta.is_categorical).any())
    W = -(-B // 32)
    if has_cat:
        cgain, cfeat, cleft, cbitset = _best_categorical(
            hist, parent_sum, meta, feature_mask, params,
            shift=shift, constraint=constraint if use_mc else None,
            parent_output=parent_output, rand_key=rand_key,
            cegb_penalty=cegb_penalty)
        use_cat = cgain > best_gain
        best_gain = jnp.maximum(best_gain, cgain)
        feature = jnp.where(use_cat, cfeat, feature)
        threshold = jnp.where(use_cat, 0, threshold)
        left = jnp.where(use_cat, cleft, left)
        is_cat = use_cat
        cat_bitset = jnp.where(use_cat, cbitset, jnp.zeros(W, jnp.uint32))
    else:
        is_cat = jnp.asarray(False)
        cat_bitset = jnp.zeros(W, jnp.uint32)

    right = parent_sum - left

    # default direction for missing values at prediction time: the side the
    # missing mass (NaN bin / zero bin) was accumulated on
    mtype = meta.missing_type[feature]
    default_left = jnp.where(
        (mtype == MISSING_NAN) | (mtype == MISSING_ZERO),
        direction == 1, False)
    default_left = default_left & (~is_cat)

    # best_gain is already relative (shift subtracted before the argmax)
    rel_gain = jnp.where(jnp.isfinite(best_gain), best_gain, NEG_INF)

    return SplitResult(
        gain=rel_gain.astype(jnp.float32),
        feature=feature,
        threshold_bin=threshold,
        default_left=default_left,
        left_sum=left.astype(jnp.float32),
        right_sum=right.astype(jnp.float32),
        is_cat=is_cat,
        cat_bitset=cat_bitset,
    )


def per_feature_best_gain(
    hist: jax.Array,          # (F, B, 3)
    parent_sum: jax.Array,    # (3,)
    meta: FeatureMeta,
    feature_mask: jax.Array,  # (F,) bool
    params: SplitParams,
    parent_output=0.0,        # leaf's current output (path smoothing shift)
) -> jax.Array:               # (F,) best split gain per feature (-inf if none)
    """Per-feature best numerical gain — the PV-Tree voting score
    (reference: VotingParallelTreeLearner computes local best splits per
    feature before voting, voting_parallel_tree_learner.cpp:300-310)."""
    F, B, _ = hist.shape
    total_g, total_h, total_c = parent_sum[0], parent_sum[1], parent_sum[2]
    cum = jnp.cumsum(hist, axis=1)
    t_idx = lax.broadcasted_iota(jnp.int32, (F, B), 1)
    nb = meta.num_bins[:, None]
    is_nan_f = (meta.missing_type == MISSING_NAN)[:, None]
    is_zero_f = (meta.missing_type == MISSING_ZERO)[:, None]
    nan_contrib = jnp.take_along_axis(
        hist, jnp.maximum(meta.nan_bin, 0)[:, None, None].repeat(3, axis=2),
        axis=1)[:, 0, :]
    zero_contrib = jnp.take_along_axis(
        hist, meta.zero_bin[:, None, None].repeat(3, axis=2),
        axis=1)[:, 0, :]
    zb = meta.zero_bin[:, None]

    def gains_for(left):
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = total_g - lg, total_h - lh, total_c - lc
        ok = ((lc >= params.min_data_in_leaf)
              & (rc >= params.min_data_in_leaf)
              & (lh >= params.min_sum_hessian_in_leaf)
              & (rh >= params.min_sum_hessian_in_leaf))
        gain = leaf_gain(lg, lh, params) + leaf_gain(rg, rh, params)
        return jnp.where(ok, gain, NEG_INF)

    valid = (t_idx <= nb - 2) & feature_mask[:, None] & meta.usable[:, None] \
        & (~meta.is_categorical[:, None])
    # missing-direction accounting mirrors find_best_split (zero-as-missing
    # mass rides the scan direction, SKIP_DEFAULT_BIN semantics)
    left_a = cum - jnp.where(
        (is_zero_f & (t_idx >= zb))[..., None], zero_contrib[:, None, :], 0.0)
    left_b = cum + jnp.where(
        is_nan_f[..., None], nan_contrib[:, None, :],
        jnp.where((is_zero_f & (t_idx < zb))[..., None],
                  zero_contrib[:, None, :], 0.0))
    ga = jnp.where(valid, gains_for(left_a), NEG_INF)
    gb = jnp.where(valid & (is_nan_f | is_zero_f),
                   gains_for(left_b), NEG_INF)
    best = jnp.maximum(ga.max(axis=1), gb.max(axis=1))
    # votes rank RELATIVE gains with the feature_contri penalty applied,
    # like the full search (the constant shift is rank-neutral without
    # contri, but with per-feature multipliers it changes the ordering);
    # with path smoothing the shift is the smoothed parent gain, matching
    # find_best_split's baseline so votes rank consistently with the
    # search they gate
    if params.path_smooth > 0:
        parent_gain = leaf_gain_given_output(total_g, total_h,
                                             parent_output, params)
    else:
        parent_gain = leaf_gain(total_g, total_h, params)
    shift = parent_gain + params.min_gain_to_split
    best = jnp.where(jnp.isfinite(best), best - shift, best)
    if meta.contri is not None:
        best = jnp.where(jnp.isfinite(best), best * meta.contri, best)
    return best


# vmapped over a batch of leaves: hist (K, F, B, 3), parent (K, 3), mask (K, F),
# constraint (K, 2), parent_output (K,); depth/penalty/key shared
find_best_split_batch = jax.vmap(
    find_best_split, in_axes=(0, 0, None, 0, None, 0, None, None, 0, None))
