"""Best-split search over histograms.

TPU-native re-design of the reference split finding
(``FeatureHistogram::FindBestThresholdSequentially``,
src/treelearner/feature_histogram.hpp:855-1056, and the gain math
``GetSplitGains``/``CalculateSplittedLeafOutput``/``ThresholdL1``
feature_histogram.hpp:734-782).

The reference scans each feature's bins twice sequentially (forward scan =
missing defaults right; reverse scan = missing defaults left).  Here both
directions are expressed as cumulative sums over the bin axis and evaluated
for **all features, all bins, both directions at once** — a handful of
vectorized ops + one argmax, no sequential loop.  This runs per-leaf and is
vmapped over the tree frontier.

Differences from the reference:
* No most-freq-bin offset arithmetic — histograms store every bin densely
  (see ops/histogram.py), so the reference's ``FixHistogram``
  (src/io/dataset.cpp:1410) has no equivalent here.
* Counts are exact fp32 sums instead of the reference's
  ``RoundInt(sum_hess * cnt_factor)`` estimate (feature_histogram.hpp:885);
  min_data_in_leaf gating is therefore exact.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..io.binning import MISSING_NAN, MISSING_ZERO

NEG_INF = -jnp.inf


class SplitParams(NamedTuple):
    """Static-ish regularization parameters (traced scalars are fine too)."""

    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0


class SplitResult(NamedTuple):
    gain: jax.Array          # relative gain (already minus parent gain and
                             # min_gain_to_split); <= 0 means "don't split"
    feature: jax.Array       # int32
    threshold_bin: jax.Array  # int32 — rows with bin <= threshold_bin go left
    default_left: jax.Array  # bool — missing-value direction
    left_sum: jax.Array      # (3,) [grad, hess, count]
    right_sum: jax.Array     # (3,)


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    """reference: ThresholdL1, feature_histogram.hpp:734."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_gain(g: jax.Array, h: jax.Array, p: SplitParams) -> jax.Array:
    """reference: GetLeafGain (no max_delta_step / path smoothing branch),
    feature_histogram.hpp:~760."""
    t = threshold_l1(g, p.lambda_l1)
    return (t * t) / (h + p.lambda_l2)


def leaf_output(g: jax.Array, h: jax.Array, p: SplitParams) -> jax.Array:
    """reference: CalculateSplittedLeafOutput, feature_histogram.hpp:740-778."""
    out = -threshold_l1(g, p.lambda_l1) / (h + p.lambda_l2)
    if isinstance(p.max_delta_step, (int, float)) and p.max_delta_step <= 0:
        return out
    return jnp.where(
        jnp.asarray(p.max_delta_step) > 0,
        jnp.clip(out, -p.max_delta_step, p.max_delta_step),
        out,
    )


class FeatureMeta(NamedTuple):
    """Per-feature binning metadata consumed by the split finder; built once
    per dataset from the BinMappers (host) and shipped to device."""

    num_bins: jax.Array       # (F,) int32
    missing_type: jax.Array   # (F,) int32
    nan_bin: jax.Array        # (F,) int32 (-1 if none)
    zero_bin: jax.Array       # (F,) int32
    is_categorical: jax.Array  # (F,) bool
    usable: jax.Array         # (F,) bool — not trivial


def make_feature_meta(dataset) -> FeatureMeta:
    import numpy as np

    # TODO(categorical): categorical features are excluded from splitting
    # until the bitset categorical split (reference
    # FindBestThresholdCategoricalInner, feature_histogram.hpp:278-460) is
    # implemented — splitting them as ordinal rank-bins would make raw
    # prediction silently diverge from training.
    return FeatureMeta(
        num_bins=jnp.asarray(dataset.num_bins, jnp.int32),
        missing_type=jnp.asarray(dataset.missing_types, jnp.int32),
        nan_bin=jnp.asarray(dataset.nan_bins, jnp.int32),
        zero_bin=jnp.asarray(dataset.zero_bins, jnp.int32),
        is_categorical=jnp.asarray(dataset.is_categorical),
        usable=jnp.asarray(~dataset.is_trivial & ~dataset.is_categorical),
    )


def find_best_split(
    hist: jax.Array,          # (F, B, 3) — [sum_grad, sum_hess, count]
    parent_sum: jax.Array,    # (3,)
    meta: FeatureMeta,
    feature_mask: jax.Array,  # (F,) bool — col-sampled usable features
    params: SplitParams,
) -> SplitResult:
    F, B, _ = hist.shape
    total_g, total_h, total_c = parent_sum[0], parent_sum[1], parent_sum[2]

    cum = jnp.cumsum(hist, axis=1)                    # (F, B, 3) inclusive
    t_idx = lax.broadcasted_iota(jnp.int32, (F, B), 1)
    nb = meta.num_bins[:, None]                       # (F, 1)

    nan_contrib = jnp.take_along_axis(
        hist,
        jnp.maximum(meta.nan_bin, 0)[:, None, None].repeat(3, axis=2),
        axis=1,
    )[:, 0, :]                                        # (F, 3)
    has_nan_dir = (meta.missing_type == MISSING_NAN)[:, None]  # (F, 1)

    # direction 0: missing/default right (forward scan)
    left_a = cum                                       # (F, B, 3)
    # direction 1: missing joins the left side (reverse scan equivalent)
    left_b = cum + nan_contrib[:, None, :]

    def eval_direction(left):
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = total_g - lg, total_h - lh, total_c - lc
        ok = (
            (lc >= params.min_data_in_leaf)
            & (rc >= params.min_data_in_leaf)
            & (lh >= params.min_sum_hessian_in_leaf)
            & (rh >= params.min_sum_hessian_in_leaf)
        )
        gain = leaf_gain(lg, lh, params) + leaf_gain(rg, rh, params)
        return jnp.where(ok, gain, NEG_INF)

    base_valid = (t_idx <= nb - 2) & feature_mask[:, None] & meta.usable[:, None]
    gain_a = jnp.where(base_valid, eval_direction(left_a), NEG_INF)
    gain_b = jnp.where(
        base_valid & has_nan_dir, eval_direction(left_b), NEG_INF
    )

    gains = jnp.stack([gain_a, gain_b])               # (2, F, B)
    flat = gains.reshape(-1)
    best = jnp.argmax(flat)
    best_gain = flat[best]

    direction = (best // (F * B)).astype(jnp.int32)
    feature = ((best // B) % F).astype(jnp.int32)
    threshold = (best % B).astype(jnp.int32)

    left = jnp.where(direction == 0, left_a[feature, threshold],
                     left_b[feature, threshold])
    right = parent_sum - left

    # default direction for missing values at prediction time
    mtype = meta.missing_type[feature]
    default_left = jnp.where(
        mtype == MISSING_NAN,
        direction == 1,
        jnp.where(mtype == MISSING_ZERO, meta.zero_bin[feature] <= threshold, False),
    )

    parent_gain = leaf_gain(total_g, total_h, params)
    rel_gain = best_gain - parent_gain - params.min_gain_to_split
    rel_gain = jnp.where(jnp.isfinite(best_gain), rel_gain, NEG_INF)

    return SplitResult(
        gain=rel_gain.astype(jnp.float32),
        feature=feature,
        threshold_bin=threshold,
        default_left=default_left,
        left_sum=left.astype(jnp.float32),
        right_sum=right.astype(jnp.float32),
    )


# vmapped over a batch of leaves: hist (K, F, B, 3), parent (K, 3), mask (K, F)
find_best_split_batch = jax.vmap(find_best_split, in_axes=(0, 0, None, 0, None))
