"""Pallas TPU histogram kernel.

TPU-native replacement for the reference's OpenCL histogram kernels
(reference: ``src/treelearner/ocl/histogram{16,64,256}.cl`` — per-workgroup
local-memory sub-histograms with hand-rolled atomic float adds and a
cross-workgroup reduction, 2,299 LoC of OpenCL).

TPUs have no atomics; the design maps the OpenCL structure onto the MXU:

* a grid step owns a (rows × feature-block) tile and builds the bin one-hot
  for its whole feature block in VMEM, laid out ``(rows, bins*features)``
  via a tile-repeat of the bin ids (``pltpu.repeat``) compared against a
  ``lane // FBLK`` iota — nothing intermediate ever touches HBM, which is
  what made the pure-XLA one-hot path bandwidth-bound,
* the histogram update is ONE MXU matmul per tile:
  ``(3·leaves, rows) @ (rows, bins*features)``, with the per-leaf-masked
  gradient rows built by an iota//3-vs-leaf compare (cheap VPU work),
* the per-workgroup local histogram of the OpenCL kernels becomes a VMEM
  f32 accumulator block revisited across the row-tile grid dimension (the
  analog of ``within_kernel_reduction256x4``, histogram256.cl:139-310,
  without the atomic counter dance),
* precision modes replace the OpenCL ``USE_DP_FLOAT`` switch:
    - ``int8``  — per-tile-quantized gradients on the int8 MXU path (2×
      bf16 throughput; counts are exact via a power-of-two scale). The
      TPU analog of LightGBM's quantized-histogram training.
    - ``int8sr``— PRE-quantized gradients (ops/quantize.sr_quantize_g3:
      stochastic rounding, deterministic counter-based PRNG) on the same
      int8 MXU path with hierarchical widening: int8 multiplicands →
      int32 MXU accumulators → exact integer f32 across row tiles.  The
      kernel does NO scale math at all — neither the per-tile amax
      reduction of ``int8`` nor the per-chunk dequant multiply — and
      emits the RAW integer histogram; the caller holds the scales and
      dequantization is folded into the consumer (the split scan /
      smaller-child subtraction), so the histogram write stream carries
      no extra pass.  Integer accumulation in f32 is exact to 2^24
      (±127 per row ⇒ exact beyond 130k rows per (leaf, bin) cell —
      far past any real bin occupancy at bench shapes).
    - ``bf16``  — single bf16 pass (the GPU learner's single-precision
      default, gpu_tree_learner.h:79).
    - ``bf16x2``— hi/lo-split bf16, ~fp32 accuracy at 2 MXU passes.
    - ``f32``   — exact; used by tests/CPU.

HBM traffic per pass ≈ bins (N·F bytes) + g3 + leaf_id — nothing else.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MAX_LANES = 2048          # lanes per one-hot block: FBLK * num_bins
_COUNT_SCALE = 64.0       # power-of-two count quantizer => exact counts


def kernel_width(num_bins: int) -> int:
    """Static kernel-width rung for a bin count — the TPU analog of the
    reference's histogram16/64/256 OpenCL kernel ladder
    (src/treelearner/ocl/histogram{16,64,256}.cl): every caller
    specializes its tiling on the rung, not the raw bin count, so two
    configs on the same rung compile the same kernel.  The <=16 rung is
    the 4-bit packed leg's home: only there can a bin id live in a
    nibble (``pack4bit``)."""
    if num_bins <= 16:
        return 16
    if num_bins <= 64:
        return 64
    if num_bins <= 256:
        return 256
    raise ValueError("uint8 kernel family holds num_bins <= 256; route "
                     "int16-binned data to the onehot/scatter path")


def _row_tile_for(m_pad: int, num_lanes: int, num_bins: int) -> int:
    """Row-tile size keeping the VMEM working set (chunked one-hot + repeat
    buffer + lg rows + out accumulator) within Mosaic's ~16MB scoped-vmem
    budget.  The estimate is deliberately conservative: per-chunk f32
    temporaries (repeat buffer, compare, select, cast) can coexist, and
    narrow feature blocks pay lane-padding amplification (observed OOM at
    B=256 with 3 features and T=1024)."""
    out_bytes = m_pad * num_lanes * 4
    per_row = 14 * min(num_lanes, 512) + 16 * m_pad
    t0 = 1024 if kernel_width(num_bins) <= 64 else 512
    for t in (1024, 512, 256, 128):
        if t <= t0 and out_bytes + t * per_row <= 8 * 2**20:
            return t
    return 128


def _kernel(iota_ref, bins_ref, g3_ref, leaf_ref, out_ref, *, lpad, num_bins,
            fblk, precision, interpret, packed=False):
    """Grid: (feature_blocks, row_tiles); out revisited across row tiles.

    iota_ref: (1, FBLK*B) bf16         — precomputed ``lane // FBLK`` pattern
                                         (bin ids are < 256 => exact in bf16;
                                         v5e has no int8 vector compare)
    bins_ref: (T, FBLK) uint8          — row-major bin tile; with ``packed``
                                         each byte holds TWO 4-bit bins
                                         (lo nibble = feature 2p, hi = 2p+1 —
                                         reference DenseBin<.., IS_4BIT=true>
                                         src/io/dense_bin.hpp:52) and the
                                         effective feature block is 2*FBLK
                                         wide, ordered [lo nibbles | hi]
    g3_ref:   (3, T) f32               — grad / hess / count (pre-transposed)
    leaf_ref: (1, T) int32             — leaf id per row
    out_ref:  (1, 3*Lpad, FBLK*B) f32  — rows are (leaf-major, channel-minor)
    """
    rt = pl.program_id(1)
    B = num_bins
    T = bins_ref.shape[0]
    m_pad = out_ref.shape[1]
    lanes = B * fblk

    @pl.when(rt == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    def rep(x, n, axis):
        if interpret:
            reps = [1, 1]
            reps[axis] = n
            return jnp.tile(x, reps)
        return pltpu.repeat(x, n, axis)

    # --- per-leaf-masked gradient rows (3*Lpad, T), built once -------------
    leaf = leaf_ref[...]                                     # (1, T)
    row_leaf = lax.broadcasted_iota(jnp.int32, (m_pad, T), 0) // 3
    loh = row_leaf == leaf                                   # (3*Lpad, T) bool
    g3 = g3_ref[...]                                         # (3, T) f32

    # VPU constraints on this target: vector compare/select only in i32/f32;
    # narrow dtypes appear only via a final astype feeding the MXU.
    if precision == "int8sr":
        # rows arrive PRE-quantized to exact integers in [-127, 127]
        # (ops/quantize.sr_quantize_g3); the leaf mask runs in f32 and the
        # int8 cast is the final op feeding the MXU — no scale math here
        lg_parts = [jnp.where(loh, rep(g3, lpad, 0), 0.0).astype(jnp.int8)]
        scale_rep = None
    elif precision == "int8":
        amax = jnp.max(jnp.abs(g3[:2]), axis=1, keepdims=True)       # (2, 1)
        inv = jnp.where(amax > 0, 127.0 / amax, 0.0)
        scale = jnp.where(amax > 0, amax / 127.0, 0.0)
        inv3 = jnp.concatenate(
            [inv, jnp.full((1, 1), _COUNT_SCALE, jnp.float32)], axis=0)
        scale3 = jnp.concatenate(
            [scale, jnp.full((1, 1), 1.0 / _COUNT_SCALE, jnp.float32)], axis=0)
        q3 = jnp.round(g3 * inv3)                                    # (3, T)
        lg_parts = [jnp.where(loh, rep(q3, lpad, 0), 0.0).astype(jnp.int8)]
        scale_rep = rep(scale3, lpad, 0)                             # (M, 1)
    elif precision in ("bf16", "bf16x2"):
        lg = jnp.where(loh, rep(g3, lpad, 0), 0.0)            # (3*Lpad, T)
        hi = lg.astype(jnp.bfloat16)
        lg_parts = [hi]
        if precision == "bf16x2":
            lg_parts.append((lg - hi.astype(jnp.float32)).astype(jnp.bfloat16))
    else:  # f32 — exact (HIGHEST forces true-f32 MXU passes)
        lg_parts = [jnp.where(loh, rep(g3, lpad, 0), 0.0)]

    # --- bin one-hot, built in column chunks to bound VMEM -----------------
    # column b*FBLK + f is (feature f, bin b); the repeat pattern of the bin
    # ids over one chunk of bins is chunk-invariant, so it is hoisted.
    cb = max(1, min(B, 512 // fblk))         # bins per chunk
    n_chunks = -(-B // cb)
    if packed:
        # unpack two 4-bit bins per byte in VMEM: HBM traffic for the
        # binned matrix halves (the hist pass's dominant stream)
        bi = bins_ref[...].astype(jnp.int32)
        bins_f = jnp.concatenate([bi & 15, bi >> 4], axis=1) \
            .astype(jnp.float32)
    else:
        bins_f = bins_ref[...].astype(jnp.int32).astype(jnp.float32)

    for c in range(n_chunks):
        cb_c = min(cb, B - c * cb)
        sl = slice(c * cb * fblk, (c * cb + cb_c) * fblk)
        bw = rep(bins_f, cb_c, 1)                            # (T, cb_c*FBLK)
        oh_cmp = bw == iota_ref[0:1, sl]
        # bool -> numeric cast IS the one-hot (exactly 1.0/0.0): a direct
        # convert, not a select pass — the one-hot build is the
        # slot-count-independent floor of the whole pass, so every VPU op
        # here is measurable in the roofline fraction
        if precision in ("int8", "int8sr"):
            oh = oh_cmp.astype(jnp.int8)
            acc = lax.dot_general(lg_parts[0], oh, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            upd = acc.astype(jnp.float32)
            if scale_rep is not None:       # int8sr stays in integer units
                upd = upd * scale_rep
            out_ref[0, :, sl] += upd
        elif precision in ("bf16", "bf16x2"):
            oh = oh_cmp.astype(jnp.bfloat16)
            if len(lg_parts) > 1:
                # bf16x2: ONE stacked (2·M, T) @ (T, lanes) pass sharing the
                # built one-hot block across the hi and lo accumulations,
                # instead of two matmuls that each re-stream it — the
                # one-hot build + stream is the slot-count-independent
                # floor of the pass.  Splitting the output and adding
                # hi + lo afterwards is bit-identical to the two-matmul
                # form: each output row's fp32 dot is unchanged and the
                # final add keeps the same operand order.
                stacked = lax.dot_general(
                    jnp.concatenate(lg_parts, axis=0), oh,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                upd = stacked[:m_pad] + stacked[m_pad:]
            else:
                upd = lax.dot_general(lg_parts[0], oh,
                                      (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            out_ref[0, :, sl] += upd
        else:
            oh = oh_cmp.astype(jnp.float32)
            out_ref[0, :, sl] += lax.dot_general(
                lg_parts[0], oh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST)


def pack4bit(binned: np.ndarray) -> np.ndarray:
    """(F, N) uint8 bins < 16 -> (ceil(F/2), N) packed bytes, two features
    per byte (lo nibble = feature 2p, hi = 2p+1) — the analog of the
    reference's 4-bit dense bins (DenseBin<VAL_T, IS_4BIT=true>,
    src/io/dense_bin.hpp:52): halves the binned matrix's HBM footprint and
    the hist pass's dominant memory stream at max_bin <= 15."""
    binned = np.asarray(binned)
    F, N = binned.shape
    if F % 2:
        binned = np.concatenate(
            [binned, np.zeros((1, N), binned.dtype)], axis=0)
    return (binned[0::2] | (binned[1::2] << 4)).astype(np.uint8)


def unpack4bit(packed, num_features: int):
    """(ceil(F/2), N) packed bytes -> (F, N) uint8 bins — ``pack4bit``'s
    inverse in natural feature order (works on numpy and jnp arrays, so
    the streaming cache can ship packed bytes over PCIe and unpack ON
    DEVICE).  The phantom hi-nibble feature of an odd-F tail is sliced
    away."""
    xp = jnp if isinstance(packed, jax.Array) else np
    lo = packed & 15
    hi = packed >> 4
    un = xp.stack([lo, hi], axis=1).reshape(2 * packed.shape[0],
                                            packed.shape[1])
    return un[:num_features].astype(xp.uint8)


def packed_bins_of_feat(binned, feat):
    """(ceil(F/2), N) packed bytes -> (N,) bins of ORIGINAL feature ``feat``
    (traced scalar).  The single source of truth for the nibble layout
    (lo nibble = feature 2p, hi = 2p+1) outside the kernel."""
    byte = binned[feat >> 1].astype(jnp.int32)
    return (byte >> (4 * (feat & 1))) & 15


def packed_bins_of_rows(binned, f_row):
    """Per-row feature variant: ``f_row`` (N,) -> (N,) original bins."""
    byte = jnp.take_along_axis(
        binned, (f_row >> 1)[None, :], axis=0)[0].astype(jnp.int32)
    return (byte >> (4 * (f_row & 1))) & 15


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_bins", "precision", "row_tile",
                     "interpret", "packed", "num_features"),
)
def hist_leaves_pallas(
    binned: jax.Array,      # (F, N) uint8; packed: (ceil(F/2), N)
    g3: jax.Array,          # (N, 3) f32
    leaf_id: jax.Array,     # (N,) int32
    num_leaves: int,
    num_bins: int,
    precision: str = "int8",
    row_tile: int = 0,
    interpret: bool = False,
    packed: bool = False,
    num_features: int = 0,  # REAL feature count when packed (else derived)
) -> jax.Array:             # (L, F, B, 3) f32
    L, B = num_leaves, num_bins
    if binned.dtype not in (jnp.uint8, np.uint8):
        raise ValueError(
            "hist_leaves_pallas requires uint8 bins (num_bins <= 256); "
            "route int16-binned data to the onehot/scatter path")
    if packed:
        if B > 16:
            raise ValueError("packed (4-bit) bins require num_bins <= 16")
        Fp, N = binned.shape
        F = num_features or 2 * Fp
    else:
        F, N = binned.shape

    if packed:
        # fblk counts UNPACKED features and must be even (each byte column
        # contributes its lo and hi nibble feature)
        fblk = max(2, min(2 * Fp, MAX_LANES // B) & ~1)
        fpb = fblk // 2                      # packed byte columns per block
        nfb = -(-Fp // fpb)
        f_pad = nfb * fblk
    else:
        fblk = max(1, min(F, MAX_LANES // B))
        nfb = -(-F // fblk)
        f_pad = nfb * fblk
    lpad = -(-L // 8) * 8
    m_pad = 3 * lpad
    T = row_tile if row_tile > 0 else _row_tile_for(m_pad, fblk * B, B)
    nrt = -(-N // T)
    n_pad = nrt * T

    # row-major bins; padded features get bin 255 (matches no b < 256 when
    # B < 256; for B == 256 padded features land in bin 255 of a feature
    # that is sliced away below; packed pad bytes are 0 -> phantom features
    # collect bin 0 and are dropped by the permutation below). padded rows
    # carry zero g3 => no effect.
    tile_cols = fpb if packed else fblk      # stored byte columns per block
    stored_pad = nfb * tile_cols
    binned_rm = jnp.pad(
        binned,
        ((0, stored_pad - binned.shape[0]), (0, n_pad - N)),
        constant_values=0 if packed else 255).T     # (n_pad, stored_pad)
    g3t = jnp.pad(g3.astype(jnp.float32), ((0, n_pad - N), (0, 0))).T  # (3, n_pad)
    leaf_p = jnp.pad(leaf_id.astype(jnp.int32), (0, n_pad - N),
                     constant_values=lpad)[None, :]      # (1, n_pad)

    iota_bins = (jnp.arange(B * fblk, dtype=jnp.int32)
                 // fblk).astype(jnp.float32)[None, :]      # (1, B*fblk)

    kernel = functools.partial(
        _kernel, lpad=lpad, num_bins=B, fblk=fblk, precision=precision,
        interpret=interpret, packed=packed,
    )

    def one_block(bins_block):
        # Mosaic requires the bins block's lane dim to equal the array dim
        # (or be 128-divisible), so each feature block is its own call; the
        # row-tile grid dimension does the accumulation.
        return pl.pallas_call(
            kernel,
            grid=(1, nrt),
            in_specs=[
                pl.BlockSpec((1, fblk * B), lambda fb, rt: (0, 0)),
                pl.BlockSpec((T, tile_cols), lambda fb, rt: (rt, 0)),
                pl.BlockSpec((3, T), lambda fb, rt: (0, rt)),
                pl.BlockSpec((1, T), lambda fb, rt: (0, rt)),
            ],
            out_specs=pl.BlockSpec((1, m_pad, fblk * B),
                                   lambda fb, rt: (0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, m_pad, fblk * B), jnp.float32),
            interpret=interpret,
        )(iota_bins, bins_block, g3t, leaf_p)

    blocks = [one_block(binned_rm[:, fb * tile_cols:(fb + 1) * tile_cols])
              for fb in range(nfb)]
    out = jnp.concatenate(blocks, axis=0) if nfb > 1 else blocks[0]

    # (nfb, 3*Lpad, B*fblk) -> (L, F, B, 3)
    h = out.reshape(nfb, lpad, 3, B, fblk)
    h = h.transpose(1, 0, 4, 3, 2).reshape(lpad, f_pad, B, 3)
    if packed:
        # per block the unpacked feature order is [lo nibbles | hi nibbles]
        # = [2p0, 2p0+2, ... | 2p0+1, 2p0+3, ...]; invert it
        perm = np.empty(f_pad, np.int64)
        pos = 0
        for fb in range(nfb):
            ps = np.arange(fb * fpb, (fb + 1) * fpb)
            perm[pos:pos + fblk] = np.concatenate([2 * ps, 2 * ps + 1])
            pos += fblk
        inv = np.argsort(perm)
        h = h[:, inv]
    return h[:L, :F]
