"""Pallas TPU histogram kernel.

TPU-native replacement for the reference's OpenCL histogram kernels
(reference: ``src/treelearner/ocl/histogram{16,64,256}.cl`` — per-workgroup
local-memory sub-histograms with hand-rolled atomic float adds and a
cross-workgroup reduction, 2,299 LoC of OpenCL).

TPUs have no atomics; the design maps the OpenCL structure onto the MXU:

* a grid step owns a row tile and builds the bin one-hot for ALL features of
  its feature block at once, laid out ``(rows, features*bins)`` — the bins
  are first broadcast across each feature's bin-lane span with a tiny
  constant expansion matmul (`bins_wide[r, f*B+b] = bins[r, f]`), then
  compared against a per-lane ``iota % B`` pattern.  Everything stays in
  VMEM; nothing intermediate touches HBM (the jnp fallback's bottleneck),
* per (channel, hi/lo-part) the histogram update is ONE large MXU matmul
  ``(leaves, rows) @ (rows, features*bins)``,
* the per-workgroup local histogram becomes a VMEM f32 accumulator block
  revisited across the row-tile grid dimension (Pallas output revisiting =
  the ``within_kernel_reduction`` of histogram256.cl:139-310, without the
  atomic counter dance),
* fp32 precision comes from the bf16 hi/lo split (two MXU passes) instead
  of the OpenCL kernels' compile-time ``USE_DP_FLOAT`` switch.

HBM traffic per pass ≈ bins (N·F bytes) + g3 + leaf_id — nothing else.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

FEATURE_BLOCK = 32


def _row_tile_for(num_leaves_p: int) -> int:
    # keep the VMEM working set (one-hot + bins_wide + lg parts + out
    # accumulator) under the ~16MB budget as the leaf count grows
    if num_leaves_p <= 72:
        return 1024
    if num_leaves_p <= 136:
        return 512
    return 256


def _hist_kernel(bins_ref, g3_ref, leaf_ref, out_ref, *, num_leaves_p,
                 num_bins, fblock, precision):
    """Grid: (feature_blocks, row_tiles).

    bins_ref: (RT, FBLK) uint8      — row-major bin tile
    g3_ref:   (RT, 3) f32           — grad / hess / count
    leaf_ref: (RT, 1) int32         — leaf id per row (padded rows -> Lp-1)
    out_ref:  (1, 3, Lp, FBLK*B) f32 — accumulated across the row-tile dim
    """
    rt = pl.program_id(1)
    Lp = num_leaves_p
    B = num_bins
    FB = fblock * B
    RT = g3_ref.shape[0]
    mm_dtype = jnp.float32 if precision == "f32" else jnp.bfloat16

    @pl.when(rt == 0)
    def _():
        out_ref[...] = jnp.zeros(out_ref.shape, out_ref.dtype)

    # --- one-hot over (rows, features*bins) ------------------------------
    # expansion matmul: bins_wide[r, f*B + b] = bins[r, f]
    col_feat = lax.broadcasted_iota(jnp.int32, (fblock, FB), 1) // B
    row_feat = lax.broadcasted_iota(jnp.int32, (fblock, FB), 0)
    expand = (col_feat == row_feat).astype(jnp.bfloat16)        # (FBLK, FB)
    bins_bf16 = bins_ref[...].astype(jnp.int32).astype(jnp.bfloat16)
    bins_wide = jnp.dot(bins_bf16, expand,
                        preferred_element_type=jnp.float32)     # (RT, FB)
    iota_mod = (
        lax.broadcasted_iota(jnp.int32, (1, FB), 1) % B
    ).astype(jnp.float32)                                       # (1, FB)
    oh = (bins_wide == iota_mod).astype(mm_dtype)               # (RT, FB)

    # --- per-leaf-masked gradient rows -----------------------------------
    leaf = leaf_ref[:, 0]
    leaf_oh = (
        leaf[None, :] == lax.broadcasted_iota(jnp.int32, (Lp, RT), 0)
    ).astype(jnp.float32)                                       # (Lp, RT)

    for ch in range(3):
        lg = leaf_oh * g3_ref[:, ch][None, :]                   # (Lp, RT)
        if precision == "bf16":
            parts = [lg.astype(jnp.bfloat16)]
        elif precision == "f32":
            parts = [lg]
        else:  # bf16x2: exact-ish fp32 via hi/lo split
            hi = lg.astype(jnp.bfloat16)
            lo = (lg - hi.astype(jnp.float32)).astype(jnp.bfloat16)
            parts = [hi, lo]
        acc = out_ref[0, ch]
        for p in parts:
            acc = acc + jnp.dot(p, oh, preferred_element_type=jnp.float32)
        out_ref[0, ch] = acc


@functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "num_bins", "precision", "row_tile",
                     "interpret"),
)
def hist_leaves_pallas(
    binned: jax.Array,      # (F, N) uint8/int16
    g3: jax.Array,          # (N, 3) f32
    leaf_id: jax.Array,     # (N,) int32
    num_leaves: int,
    num_bins: int,
    precision: str = "bf16x2",
    row_tile: int = 0,
    interpret: bool = False,
) -> jax.Array:             # (L, F, B, 3) f32
    F, N = binned.shape
    L, B = num_leaves, num_bins
    Lp = L + 1                       # padded rows route to slot L
    RT = row_tile if row_tile > 0 else _row_tile_for(Lp)
    NRT = -(-N // RT)
    NFB = -(-F // FEATURE_BLOCK)
    F_pad = NFB * FEATURE_BLOCK
    N_pad = NRT * RT

    binsT = jnp.pad(binned.astype(jnp.uint8),
                    ((0, F_pad - F), (0, N_pad - N))).T      # (N_pad, F_pad)
    g3_p = jnp.pad(g3.astype(jnp.float32), ((0, N_pad - N), (0, 0)))
    leaf_p = jnp.pad(leaf_id.astype(jnp.int32), (0, N_pad - N),
                     constant_values=L)[:, None]

    kernel = functools.partial(
        _hist_kernel, num_leaves_p=Lp, num_bins=B, fblock=FEATURE_BLOCK,
        precision=precision,
    )
    out = pl.pallas_call(
        kernel,
        grid=(NFB, NRT),
        in_specs=[
            pl.BlockSpec((RT, FEATURE_BLOCK), lambda fb, rt: (rt, fb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((RT, 3), lambda fb, rt: (rt, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((RT, 1), lambda fb, rt: (rt, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 3, Lp, FEATURE_BLOCK * B), lambda fb, rt: (fb, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((NFB, 3, Lp, FEATURE_BLOCK * B),
                                       jnp.float32),
        interpret=interpret,
    )(binsT, g3_p, leaf_p)

    # (NFB, 3, Lp, FBLK*B) -> (L, F, B, 3)
    h = out.reshape(NFB, 3, Lp, FEATURE_BLOCK, B)
    h = h.transpose(2, 0, 3, 4, 1).reshape(Lp, F_pad, B, 3)
    return h[:L, :F]
