"""Fused wave-round megakernel: histogram + split scan in ONE Pallas pass.

The staged wave round (the r05 phase table) is a pure-bandwidth
round-trip: ``hist_pallas`` writes the ``(slots, F, B, 3)`` histogram
stack to HBM, ``models/grower_wave.subtract_child_hists`` reads it back
to build the 2K-child stack, and ``ops/split.py``'s scan streams that
stack in again — three traversals of a tensor that is consumed exactly
once.  This kernel keeps the round's histograms in VMEM end to end:

* the row-tile grid REUSES ``hist_pallas._kernel`` verbatim (the one-hot
  MXU formulation with its bf16 / bf16x2 / int8 / int8sr precision
  modes) to accumulate each wave slot's histogram into a VMEM scratch
  accumulator,
* on the LAST row tile the same kernel invocation runs the split scan on
  the VMEM-resident stack: the smaller-child-subtraction path reads the
  parent histograms as a kernel input and subtracts in VMEM before
  scanning (the int8sr dequantize multiply folded in), then the staged
  scan's own stages — ``scan_left_sums`` (stacked two-direction cumsum +
  missing-mass adjust), ``scan_direction_gains`` (gain/penalty chain)
  and ``scan_pick_feature`` (tie-band preference argmax, per-feature
  half) — are composed AS THE SAME CODE OBJECTS on the VMEM values, so
  interpret-mode results are bit-identical to the staged path by
  construction, not by re-derivation,
* the round's PARTITION rides the same pass (ISSUE 15, the single-pass
  wave round): the feature-block-0 kernel invocation receives each
  row's DECISION BIN (the committed split feature's bin for the row's
  current leaf — one O(N) gather, the only extra touch of the binned
  matrix) plus the packed per-slot split metadata, evaluates the
  go-left decisions in VMEM with the staged partition's own
  ``ops/split.go_left_rule`` (bin compare + the NaN/zero
  missing-direction rules, op-for-op), writes the updated row→slot
  label into its own output block and accumulates the child histograms
  from it IN THE SAME SWEEP — the staged path's separate (S, N)
  decision pass over the binned rows (``phase_partition_ms``) and its
  HBM-resident mask intermediates disappear, and the kernel emits the
  new per-row leaf ids as a second O(N) output.  Valid-set routing
  rides the same decision stage (``fused_route_rows`` — a routing-only
  grid over the valid binned matrix, same ``route_tile`` code object),
  replacing the staged gather chain (``phase_valid_route_ms``),
* only an O(F) per-(child, feature) residue (best gain, in-band pick,
  left sums at the pick — ``RES_COLS`` floats per feature) leaves the
  kernel; the grid iterates feature blocks and the cross-feature half of
  ``scan_pick`` runs on the concatenated residue outside the kernel.
  The tie band needs the GLOBAL best gain, so a running in-VMEM
  reduction across feature blocks could mis-pick inside overlapping
  near-tie bands; reducing to the O(F) residue in VMEM and finishing the
  O(F) argmax outside keeps bit-exactness while still shrinking the
  kernel's HBM output from O(F·B) histograms to O(F) floats,
* the packed per-slot SplitInfo (``PACK_COLS`` floats per child) is all
  the round emits in pool-free mode; the subtraction-composed mode also
  emits the K smaller-child histograms (the per-leaf state the NEXT
  round's subtraction needs) — the ``(2K, F, B, 3)`` scan stack itself
  never materializes off-chip in either mode.

Fallback taxonomy (every gate logs once at build time,
parallel/trainer.py):

* categorical features — the sorted two-direction categorical scan
  (``_best_categorical``) argsorts per feature, which has no Mosaic
  lowering; such datasets run the staged path,
* ``extra_trees`` — per-node threshold sampling draws ``jax.random``
  inside the scan,
* EFB bundles / 4-bit packed bins / int16 bins — the scan runs in
  original-feature uint8 bin space only,
* row-sharded learners (``tree_learner=data``/``voting``) — the
  cross-shard histogram reduce needs the explicit histogram on the wire;
  the feature-parallel learner DOES run the kernel per feature slice and
  elects through the existing ``_sync_best_split``,
* feature-parallel partition (partition-specific) — the in-kernel
  routing stage needs the committed split feature's GLOBAL column, but
  each shard's kernel sees only its own feature slice; the
  feature-parallel learner therefore keeps the staged (S, N) partition
  and per-slice election while still fusing histogram + scan,
* EFB / 4-bit packed decisions (partition-specific) — the go-left stage
  compares raw uint8 bins; bundle-column and nibble decode happen in
  ``bins_of_fn`` outside any kernel (these configs are already excluded
  by the histogram gates above, so the partition gate never fires
  alone),
* Mosaic lowering failure on a device backend — auto-fallback with a
  warning, the ``predict_pallas`` precedent; the CPU backend always runs
  the kernel in interpret mode (the bit-parity lane the tests pin).
  The lowering probe compiles the ROUTED round (partition folded in)
  plus the valid-set router, so a backend that can fuse histograms but
  not the routing stage still falls back cleanly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..io.binning import MISSING_NAN, MISSING_ZERO
from .hist_pallas import MAX_LANES, _kernel as _hist_tile, _row_tile_for
from .split import (
    NEG_INF,
    FeatureMeta,
    SplitResult,
    gain_shift,
    go_left_rule,
    scan_direction_gains,
    scan_left_sums,
    scan_pick_feature,
    tie_tol,
)

RES_COLS = 6    # fbest, gain_at_sel, sel (direction*B+thr), left g/h/c
PACK_COLS = 10  # gain, feature, threshold, default_left, left(3), right(3)
RMETA_COLS = 8  # leaf, new-leaf, thr, default_left, mtype, nan_bin,
                # zero_bin, smaller-is-left — the packed per-slot split
                # metadata the routing stage consumes (int32)


def route_tile(dbin, oleaf, rmeta, *, nslots, sub, want_label=True):
    """The fused decision stage on one row tile — pure jnp on VALUES, so
    the megakernel (train rows), the routing-only valid-set kernel and
    any host-side replay all run the SAME code object.

    ``dbin`` (1, T) int32 — each row's DECISION bin: the bin of its
    current leaf's committed split feature (rows of non-splitting
    leaves carry an arbitrary bin; their ``mine`` mask is False).
    ``oleaf`` (1, T) int32 — current leaf ids (pad rows carry -1).
    ``rmeta`` (S, RMETA_COLS) int32 — per-slot split metadata; dead
    slots carry leaf id ``num_leaves`` (matches no row).

    Returns ``(new_leaf (1, T), label (1, T) or None)``: the updated
    row→leaf routing and (``want_label``) the row→histogram-slot label
    (smaller-child slot in subtraction mode, ``2s + right`` pool-free;
    ``nslots`` = dead).  Mirrors the staged ``go_left_s`` partition
    op-for-op — every update term is int32, so deferring/fusing is
    bit-identical to the staged pass by construction."""
    S = rmeta.shape[0]
    leafs = rmeta[:, 0:1]
    nls = rmeta[:, 1:2]
    thr = rmeta[:, 2:3]
    dl = rmeta[:, 3:4] != 0
    mt = rmeta[:, 4:5]
    nanb = rmeta[:, 5:6]
    zb = rmeta[:, 6:7]
    sml = rmeta[:, 7:8] != 0
    mine = oleaf == leafs                                    # (S, T)
    g = go_left_rule(dbin, thr, dl, mt, nanb, zb)            # (S, T)
    new_leaf = oleaf + jnp.sum(
        jnp.where(mine & (~g), nls - oleaf, 0), axis=0, keepdims=True)
    if not want_label:
        return new_leaf, None
    siota = lax.broadcasted_iota(jnp.int32, (S, 1), 0)
    if sub:
        hit = mine & (g == sml)
        slot = jnp.broadcast_to(siota, mine.shape)
    else:
        hit = mine
        slot = 2 * siota + (~g).astype(jnp.int32)
    label = jnp.sum(jnp.where(hit, slot - nslots, 0),
                    axis=0, keepdims=True) + nslots
    return new_leaf, label


def pack_route_meta(feats, thrs, dls, leafs, nls, meta, sml=None):
    """(S, RMETA_COLS) int32 routing metadata from rank/slot-order split
    arrays + the feature meta — one place, so the megakernel's train
    stage and the valid-set router cannot pack differently."""
    feats = feats.astype(jnp.int32)
    z = jnp.zeros_like(feats)
    return jnp.stack([
        leafs.astype(jnp.int32),
        nls.astype(jnp.int32),
        thrs.astype(jnp.int32),
        dls.astype(jnp.int32),
        meta.missing_type[feats].astype(jnp.int32),
        meta.nan_bin[feats].astype(jnp.int32),
        meta.zero_bin[feats].astype(jnp.int32),
        (sml.astype(jnp.int32) if sml is not None else z),
    ], axis=1)


def decision_bins(binned, lids, feats, leafs, num_leaves):
    """Each row's decision bin — ``binned[f(leaf(row)), row]`` via a
    leaf→feature table and ONE per-element gather (O(N) bytes), the
    only touch of the binned matrix the routing stage adds.  Rows of
    non-splitting leaves read feature 0; their slot mask is False."""
    tab = jnp.zeros(num_leaves + 1, jnp.int32) \
        .at[leafs].set(feats.astype(jnp.int32), mode="drop")
    f_of = tab[lids]                                        # (N,)
    return jnp.take_along_axis(binned, f_of[None, :], axis=0)[0] \
        .astype(jnp.int32)


def _fused_kernel(*refs, nrt, lpad, num_bins, fblk, precision, interpret,
                  params, use_mc, monotone_penalty, has_contri, sub,
                  apply_scale, child_scale, nslots, nchildren,
                  route_blk=False):
    """Grid ``(1, row_tiles)``: every tile accumulates its rows via the
    REUSED ``hist_pallas._kernel``; the last tile runs the split scan on
    the VMEM accumulator and writes the per-feature residue (plus, in
    subtraction mode, the raw smaller-child histograms).

    ``route_blk`` (feature block 0 of a routed round): the tile FIRST
    evaluates the committed splits' go-left decisions (``route_tile`` on
    the decision-bin/old-leaf tiles + the packed slot metadata), writes
    the row→slot label into its own output block — which the remaining
    feature blocks consume as their ``leaf`` input — and the new per-row
    leaf ids, then accumulates this block's histogram FROM the label it
    just produced: partition and histogram share one sweep of the rows.
    """
    names = ["iota", "bins", "g3"]
    names += (["dbin", "oleaf", "rmeta"] if route_blk else ["leaf"])
    names += ["nb", "mt", "nanb", "zb", "usbl", "mono"]
    if has_contri:
        names.append("contri")
    names += ["mask", "csums", "constr", "depth", "pout"]
    if child_scale:
        names.append("cscale")
    if sub and apply_scale:
        names.append("sscale")
    if sub:
        names += ["sml", "parent"]
    names.append("res")
    if sub:
        names.append("hsmall")
    if route_blk:
        names += ["lab", "nleaf"]
    names.append("acc")
    r = dict(zip(names, refs))

    if route_blk:
        new_leaf, label = route_tile(
            r["dbin"][...], r["oleaf"][...], r["rmeta"][...],
            nslots=nslots, sub=sub)
        r["lab"][...] = label
        r["nleaf"][...] = new_leaf
        leaf_ref = r["lab"]
    else:
        leaf_ref = r["leaf"]

    _hist_tile(r["iota"], r["bins"], r["g3"], leaf_ref, r["acc"],
               lpad=lpad, num_bins=num_bins, fblk=fblk,
               precision=precision, interpret=interpret)

    rt = pl.program_id(1)
    B = num_bins

    @pl.when(rt == nrt - 1)
    def _scan():
        # accumulator rows are (slot-major, channel-minor), lanes are
        # (bin-major, feature-minor) — the same unscramble
        # hist_leaves_pallas applies outside, here on VMEM values
        acc = r["acc"][0]                               # (3*lpad, B*fblk)
        h = acc.reshape(lpad, 3, B, fblk).transpose(0, 3, 2, 1)
        meta_blk = FeatureMeta(
            num_bins=r["nb"][...][0],
            missing_type=r["mt"][...][0],
            nan_bin=r["nanb"][...][0],
            zero_bin=r["zb"][...][0],
            is_categorical=jnp.zeros(fblk, bool),
            usable=r["usbl"][...][0] != 0,
            monotone_type=r["mono"][...][0],
            contri=(r["contri"][...][0] if has_contri else None),
        )
        if sub:
            # smaller-child + parent subtraction IN VMEM — the exact op
            # order of subtract_child_hists (dequant multiply first, then
            # the smaller/larger select), so values are bit-identical
            hsm = h[:nslots]                            # (S, fblk, B, 3)
            r["hsmall"][...] = hsm                      # raw (int on quant)
            if apply_scale:
                hsm = hsm * r["sscale"][...][:, None, None, :]
            sml = (r["sml"][...][:, 0] != 0)[:, None, None, None]
            parent = r["parent"][...]
            h_left = jnp.where(sml, hsm, parent - hsm)
            h_right = parent - h_left
            ch = jnp.stack([h_left, h_right], axis=1).reshape(
                (2 * nslots,) + h_left.shape[1:])       # (2S, fblk, B, 3)
        else:
            ch = h[:nchildren]

        mask = r["mask"][...] != 0                      # (C, fblk)
        csums = r["csums"][...]
        constr = r["constr"][...]
        depth = r["depth"][...][:, 0]
        pout = r["pout"][...][:, 0]
        cscale = (r["cscale"][...] if child_scale
                  else jnp.zeros((nchildren, 3), jnp.float32))

        def child_scan(hc, mask_c, csum_c, constr_c, depth_c, pout_c,
                       hsc_c):
            # the staged scan's OWN stages on the VMEM stack
            left2, _ = scan_left_sums(
                hc, meta_blk, hsc_c if child_scale else None)
            gains, shift = scan_direction_gains(
                left2, csum_c, meta_blk, mask_c, params, constr_c,
                depth_c, monotone_penalty, pout_c, None, None,
                use_mc=use_mc)
            fbest, sel = scan_pick_feature(gains, shift, meta_blk)
            gains_f = jnp.concatenate([gains[0], gains[1]], axis=1)
            gsel = jnp.take_along_axis(gains_f, sel[:, None],
                                       axis=1)[:, 0]
            lsel = left2[sel // B, jnp.arange(fblk), sel % B]  # (fblk, 3)
            return jnp.concatenate(
                [fbest[:, None], gsel[:, None],
                 sel.astype(jnp.float32)[:, None], lsel], axis=1)

        r["res"][...] = jax.vmap(child_scan)(
            ch, mask, csums, constr, depth, pout, cscale)


def fused_wave_scan(binned, g3, label, *, nslots, nchildren, num_bins,
                    precision, interpret, meta, params, use_mc,
                    monotone_penalty, mask, csums, constr, depth, pout,
                    cscale=None, sscale=None, sml=None, parent=None,
                    apply_scale=False, row_tile=0, route=None):
    """One fused wave round over all feature blocks.

    ``nslots`` counts the ACCUMULATED slots (smaller children in
    subtraction mode, all 2S children pool-free); slot ``nslots`` is the
    sacrificial dead-row slot, as in ``hist_wave``.  ``parent`` non-None
    selects the subtraction-composed mode.  ``route`` non-None (dict
    ``dbin (N,) / oleaf (N,) / rmeta (S, RMETA_COLS)``) folds the
    partition in: ``label`` is ignored (pass None) — feature block 0
    evaluates the go-left decisions in VMEM, emits the label the other
    blocks consume and the updated per-row leaf ids.  Returns
    ``(residue (C, F, RES_COLS), hsmall (nslots, F, B, 3) or None,
    new_leaf (N,) or None)``.
    """
    sub = parent is not None
    C = nchildren
    F = mask.shape[1]
    B = num_bins
    N = binned.shape[1]
    fblk = max(1, min(F, MAX_LANES // B))
    nfb = -(-F // fblk)
    f_pad = nfb * fblk
    L = nslots + 1
    lpad = -(-L // 8) * 8
    m_pad = 3 * lpad
    T = row_tile if row_tile > 0 else _row_tile_for(m_pad, fblk * B, B)
    nrt = -(-N // T)
    n_pad = nrt * T

    # padding identical to hist_leaves_pallas: padded features collect
    # bin 255 (no bin when B < 256; masked unusable below when B == 256),
    # padded rows carry zero g3 and an out-of-range slot id
    binned_rm = jnp.pad(binned, ((0, f_pad - F), (0, n_pad - N)),
                        constant_values=255).T          # (n_pad, f_pad)
    g3t = jnp.pad(g3.astype(jnp.float32), ((0, n_pad - N), (0, 0))).T
    if route is not None:
        # pad rows: leaf -1 matches no slot -> the routing stage labels
        # them with the dead slot (zero g3 anyway) and passes the -1
        # leaf through (sliced off below)
        dbin_p = jnp.pad(route["dbin"].astype(jnp.int32),
                         (0, n_pad - N))[None, :]
        oleaf_p = jnp.pad(route["oleaf"].astype(jnp.int32),
                          (0, n_pad - N), constant_values=-1)[None, :]
        rmeta = route["rmeta"].astype(jnp.int32)
        leaf_p = None
    else:
        leaf_p = jnp.pad(label.astype(jnp.int32), (0, n_pad - N),
                         constant_values=lpad)[None, :]
    iota_bins = (jnp.arange(B * fblk, dtype=jnp.int32)
                 // fblk).astype(jnp.float32)[None, :]

    def padf(a, cv, dtype=jnp.int32):
        return jnp.pad(a.astype(dtype), (0, f_pad - F),
                       constant_values=cv)[None, :]

    nb_p = padf(meta.num_bins, 1)
    mt_p = padf(meta.missing_type, 0)
    nanb_p = padf(meta.nan_bin, -1)
    zb_p = padf(meta.zero_bin, 0)
    us_p = padf(meta.usable, 0)
    mono_p = padf(meta.monotone_type, 0)
    has_contri = meta.contri is not None
    contri_p = padf(meta.contri, 1.0, jnp.float32) if has_contri else None
    mask_p = jnp.pad(mask.astype(jnp.int8), ((0, 0), (0, f_pad - F)))
    parent_p = (jnp.pad(parent.astype(jnp.float32),
                        ((0, 0), (0, f_pad - F), (0, 0), (0, 0)))
                if sub else None)
    csums2 = csums.astype(jnp.float32)
    constr2 = constr.astype(jnp.float32)
    depth2 = depth.astype(jnp.int32)[:, None]
    pout2 = pout.astype(jnp.float32)[:, None]
    sml2 = sml.astype(jnp.int32)[:, None] if sub else None
    child_scale = cscale is not None

    kern = functools.partial(
        _fused_kernel, nrt=nrt, lpad=lpad, num_bins=B, fblk=fblk,
        precision=precision, interpret=interpret, params=params,
        use_mc=use_mc, monotone_penalty=monotone_penalty,
        has_contri=has_contri, sub=sub, apply_scale=apply_scale,
        child_scale=child_scale, nslots=nslots, nchildren=C)

    def full_spec(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, lambda fb, rt, _n=nd: (0,) * _n)

    res_blocks, hs_blocks = [], []
    new_leaf = None
    for fb in range(nfb):
        route_blk = route is not None and fb == 0
        sl = slice(fb * fblk, (fb + 1) * fblk)
        ins = [iota_bins, binned_rm[:, sl], g3t]
        specs = [
            pl.BlockSpec((1, fblk * B), lambda fb_, rt: (0, 0)),
            pl.BlockSpec((T, fblk), lambda fb_, rt: (rt, 0)),
            pl.BlockSpec((3, T), lambda fb_, rt: (0, rt)),
        ]
        if route_blk:
            # block 0 routes: decision bins + old leaf ids per row tile,
            # packed slot metadata resident; the label it emits becomes
            # the remaining blocks' ``leaf`` input below
            ins += [dbin_p, oleaf_p, rmeta]
            specs += [pl.BlockSpec((1, T), lambda fb_, rt: (0, rt)),
                      pl.BlockSpec((1, T), lambda fb_, rt: (0, rt)),
                      full_spec(rmeta.shape)]
        else:
            ins.append(leaf_p)
            specs.append(pl.BlockSpec((1, T), lambda fb_, rt: (0, rt)))
        ins += [nb_p[:, sl], mt_p[:, sl], nanb_p[:, sl], zb_p[:, sl],
                us_p[:, sl], mono_p[:, sl]]
        specs += [full_spec((1, fblk))] * 6
        if has_contri:
            ins.append(contri_p[:, sl])
            specs.append(full_spec((1, fblk)))
        ins.append(mask_p[:, sl])
        specs.append(full_spec((C, fblk)))
        for a in (csums2, constr2, depth2, pout2):
            ins.append(a)
            specs.append(full_spec(a.shape))
        if child_scale:
            ins.append(cscale.astype(jnp.float32))
            specs.append(full_spec((C, 3)))
        if sub and apply_scale:
            ins.append(sscale.astype(jnp.float32))
            specs.append(full_spec((nslots, 3)))
        if sub:
            ins += [sml2, parent_p[:, sl]]
            specs += [full_spec((nslots, 1)),
                      full_spec((nslots, fblk, B, 3))]
        out_shape = [jax.ShapeDtypeStruct((C, fblk, RES_COLS),
                                          jnp.float32)]
        out_specs = [full_spec((C, fblk, RES_COLS))]
        if sub:
            out_shape.append(
                jax.ShapeDtypeStruct((nslots, fblk, B, 3), jnp.float32))
            out_specs.append(full_spec((nslots, fblk, B, 3)))
        if route_blk:
            out_shape += [jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
                          jax.ShapeDtypeStruct((1, n_pad), jnp.int32)]
            out_specs += [pl.BlockSpec((1, T), lambda fb_, rt: (0, rt)),
                          pl.BlockSpec((1, T), lambda fb_, rt: (0, rt))]
        out = pl.pallas_call(
            functools.partial(kern, route_blk=route_blk),
            grid=(1, nrt),
            in_specs=specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((1, m_pad, fblk * B), jnp.float32)],
            interpret=interpret,
        )(*ins)
        res_blocks.append(out[0])
        if sub:
            hs_blocks.append(out[1])
        if route_blk:
            leaf_p = out[2 if sub else 1]         # the emitted label
            new_leaf = out[3 if sub else 2][0, :N]
    residue = (jnp.concatenate(res_blocks, axis=1)
               if nfb > 1 else res_blocks[0])[:, :F]
    hsmall = None
    if sub:
        hsmall = (jnp.concatenate(hs_blocks, axis=1)
                  if nfb > 1 else hs_blocks[0])[:, :F]
    return residue, hsmall, new_leaf


def _route_only_kernel(dbin_ref, oleaf_ref, rmeta_ref, out_ref):
    """One routing-only tile: the fused decision stage (``route_tile``)
    with no histogram behind it — the valid-set lane."""
    new_leaf, _ = route_tile(dbin_ref[...], oleaf_ref[...],
                             rmeta_ref[...], nslots=0, sub=False,
                             want_label=False)
    out_ref[...] = new_leaf


def fused_route_rows(binned, lids, *, feats, thrs, dls, leafs, nls,
                     num_leaves, meta, interpret, row_tile=1024):
    """Route one row set through a round's committed splits with the
    SAME kernel decision stage the megakernel runs on the train rows —
    the valid-set lane of the single-pass round (ISSUE 15).

    Replaces the staged gather chain (per-split bin gather + (S, N)
    masks in HBM): one O(N) decision-bin gather feeds a routing-only
    Pallas grid whose tiles evaluate ``route_tile`` in VMEM and emit
    only the updated leaf ids.  Every update term is int32, so the
    result is bit-identical to the staged ``go_left_s``/
    ``route_pending`` routing (pinned in tests/test_wave_fused.py).
    """
    N = lids.shape[0]
    if N == 0:
        return lids
    dbin = decision_bins(binned, lids, feats, leafs, num_leaves)
    rmeta = pack_route_meta(feats, thrs, dls, leafs, nls, meta)
    T = min(row_tile, max(128, -(-N // 128) * 128))
    nrt = -(-N // T)
    n_pad = nrt * T
    dbin_p = jnp.pad(dbin, (0, n_pad - N))[None, :]
    oleaf_p = jnp.pad(lids.astype(jnp.int32), (0, n_pad - N),
                      constant_values=-1)[None, :]
    out = pl.pallas_call(
        _route_only_kernel,
        grid=(nrt,),
        in_specs=[
            pl.BlockSpec((1, T), lambda rt: (0, rt)),
            pl.BlockSpec((1, T), lambda rt: (0, rt)),
            pl.BlockSpec(rmeta.shape, lambda rt: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, T), lambda rt: (0, rt)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        interpret=interpret,
    )(dbin_p, oleaf_p, rmeta)
    return out[0, :N]


def _pick_pack(residue_c, shift_c, parent_sum_c, meta, num_bins):
    """Cross-feature half of ``scan_pick`` on one child's O(F) residue,
    plus the non-categorical tail of ``_find_best_split`` (right sums,
    missing default direction) — the packed per-slot SplitInfo the round
    emits.  Formula-for-formula the staged code, evaluated on identical
    inputs, so the pick is bit-identical."""
    fbest = residue_c[:, 0]
    gsel = residue_c[:, 1]
    sel = residue_c[:, 2].astype(jnp.int32)
    gbest = jnp.max(fbest)
    feature = jnp.argmax(fbest >= gbest - tie_tol(gbest, shift_c)) \
        .astype(jnp.int32)                   # first in band = min feature
    best_gain = gsel[feature]
    sc = sel[feature]
    direction = (sc // num_bins).astype(jnp.int32)
    threshold = (sc % num_bins).astype(jnp.int32)
    left = residue_c[feature, 3:6]
    right = parent_sum_c - left
    mtype = meta.missing_type[feature]
    default_left = jnp.where(
        (mtype == MISSING_NAN) | (mtype == MISSING_ZERO),
        direction == 1, False)
    rel_gain = jnp.where(jnp.isfinite(best_gain), best_gain, NEG_INF)
    return jnp.concatenate([
        jnp.stack([rel_gain.astype(jnp.float32),
                   feature.astype(jnp.float32),
                   threshold.astype(jnp.float32),
                   default_left.astype(jnp.float32)]),
        left.astype(jnp.float32), right.astype(jnp.float32)])


def pack_children(res: SplitResult) -> jnp.ndarray:
    """Batched SplitResult -> the (C, PACK_COLS) wire rows (no bitset —
    the fused path never produces categorical splits)."""
    return jnp.concatenate([
        res.gain[:, None],
        res.feature.astype(jnp.float32)[:, None],
        res.threshold_bin.astype(jnp.float32)[:, None],
        res.default_left.astype(jnp.float32)[:, None],
        res.left_sum, res.right_sum], axis=1)


def unpack_children(packed: jnp.ndarray, num_bins: int) -> SplitResult:
    """(C, PACK_COLS) rows -> batched SplitResult (is_cat False, zero
    bitset — the fused gate excludes categorical datasets)."""
    W = -(-num_bins // 32)
    C = packed.shape[0]
    return SplitResult(
        gain=packed[:, 0],
        feature=packed[:, 1].astype(jnp.int32),
        threshold_bin=packed[:, 2].astype(jnp.int32),
        default_left=packed[:, 3] != 0,
        left_sum=packed[:, 4:7],
        right_sum=packed[:, 7:10],
        is_cat=jnp.zeros(C, bool),
        cat_bitset=jnp.zeros((C, W), jnp.uint32),
    )


def make_fused_round(*, meta, params, num_bins, precision, deep_precision,
                     monotone_penalty=0.0, interpret=False,
                     axis_name=None):
    """Build the grower-facing ``fused_round_fn``.

    ``fused_round(binned, g3, label, S, *, deep, quant_key, scaled,
    mask, csums, constr, depth, pout, sml, parent, meta_override,
    feature_rebase, route) -> (packed (2S, PACK_COLS), hsmall or None,
    slot_scales (nslots, 3))`` — plus ``new_leaf (N,)`` when routed.

    * ``route`` non-None (dict ``leaf_id (N,) / feats / thrs / dls /
      leafs / nls (S,) / num_leaves``) folds the round's PARTITION into
      the kernel (ISSUE 15): ``label`` must be None — the kernel
      evaluates the committed splits' go-left decisions in VMEM
      (``route_tile`` + the staged partition's own
      ``split.go_left_rule``) while sweeping the rows for the
      histograms, and the call returns the updated per-row leaf ids as
      a fourth output.  The decision-bin gather (``decision_bins``,
      O(N) bytes) is the routing stage's only extra touch of the binned
      matrix — the round reads the binned rows ONCE.  The builder marks
      the returned callable ``supports_route=True`` and hangs the
      valid-set router on it as ``route_rows`` (same decision stage
      over a valid binned matrix); the feature-parallel trainer wrapper
      deliberately has neither (its shard sees only a feature slice —
      the partition-specific fallback of the module taxonomy).

    * ``deep`` — sustained-bucket round: the kernel accumulates at
      ``deep_precision`` (the staged deep-dtype policy, so precision per
      bucket cannot drift between the paths).
    * ``quant_key`` non-None — an int8sr-eligible bucket
      (models/grower_wave.py quant gate: the sustained bucket and the
      16-slot ramp of a K>16 wave; root and <=4-slot ramps never reach
      here): the gradients are stochastic-round quantized with the SAME
      ``sr_quantize_g3`` call the staged pass makes, and the dequantize
      multiply folds into the in-VMEM subtraction (or the scan's integer
      cumsum pool-free) exactly where the staged path folds it.
    * ``scaled`` — quant buckets exist this grow (the staged path then
      applies identity scales on non-quant rounds too; mirrored for bit
      parity).
    * ``meta_override``/``feature_rebase`` — the feature-parallel
      learner passes its (traced) per-shard meta slice and block offset;
      packed feature ids come back shard-local and are rebased by the
      caller after the SplitInfo election.
    """
    from .quantize import sr_quantize_g3

    use_mc = bool(np.asarray(meta.monotone_type).any())

    def fused_round(binned, g3, label, S, *, deep=False, quant_key=None,
                    scaled=False, mask=None, csums=None, constr=None,
                    depth=None, pout=None, sml=None, parent=None,
                    meta_override=None, route=None):
        sub = parent is not None
        C = 2 * S
        nslots = S if sub else C
        m = meta_override if meta_override is not None else meta
        if quant_key is not None:
            # routed rounds have no precomputed label; sr_quantize_g3's
            # global-scale implementation ignores it (per-pass scales),
            # so the rounding stream — and int8sr bit-reproducibility —
            # is identical to the staged pass either way
            q3, scales = sr_quantize_g3(
                g3, route["leaf_id"] if route is not None else label,
                nslots, quant_key, axis_name=axis_name)
            g3u, prec = q3, "int8sr"
        else:
            scales = jnp.ones((nslots, 3), jnp.float32)
            g3u = g3
            prec = deep_precision if deep else precision
        route_in = None
        if route is not None:
            route_in = dict(
                dbin=decision_bins(binned, route["leaf_id"],
                                   route["feats"], route["leafs"],
                                   route["num_leaves"]),
                oleaf=route["leaf_id"],
                rmeta=pack_route_meta(route["feats"], route["thrs"],
                                      route["dls"], route["leafs"],
                                      route["nls"], m, sml=sml))
        with jax.named_scope("lgbm.fused_round"):
            residue, hsmall, new_leaf = fused_wave_scan(
                binned, g3u, label, nslots=nslots, nchildren=C,
                num_bins=num_bins, precision=prec, interpret=interpret,
                meta=m, params=params, use_mc=use_mc,
                monotone_penalty=monotone_penalty, mask=mask,
                csums=csums, constr=constr, depth=depth, pout=pout,
                cscale=(scales if (scaled and not sub) else None),
                sscale=(scales if (scaled and sub) else None),
                sml=sml, parent=parent, apply_scale=(scaled and sub),
                route=route_in)
            shift = jax.vmap(
                lambda ps, po: gain_shift(ps, po, params))(csums, pout)
            packed = jax.vmap(
                lambda rc, sh, ps: _pick_pack(rc, sh, ps, m, num_bins)
            )(residue, shift, csums)
        if route is not None:
            return packed, hsmall, scales, new_leaf
        return packed, hsmall, scales

    fused_round.supports_route = True
    fused_round.route_rows = functools.partial(
        fused_route_rows, meta=meta, interpret=interpret)
    return fused_round


def fused_ineligible_reason(*, meta, params, bin_dtype, num_bins,
                            packed=False, bundled=False) -> str:
    """Static eligibility gate — returns the fallback reason (one line of
    the module-docstring taxonomy) or ``""`` when the fused kernel can
    run.  Learner/grower routing gates live in parallel/trainer.py."""
    if bundled:
        return ("EFB bundle-space histograms expand to original features "
                "before the scan")
    if packed:
        return "4-bit packed bins decode outside the fused kernel"
    if np.dtype(bin_dtype).itemsize > 1:
        return "int16 bins exceed the uint8 one-hot kernel family"
    if num_bins > 256:
        return "num_bins > 256 exceeds the uint8 kernel family"
    if bool(np.asarray(meta.is_categorical).any()):
        return ("categorical sorted-scan (per-feature argsort) has no "
                "kernel lowering")
    if params.extra_trees:
        return "extra_trees draws per-node randomness inside the scan"
    return ""


_BACKEND_LOWERS: dict = {}


def backend_lowers_fused() -> bool:
    """One cached trial compile of a tiny fused round on the current
    backend — the Mosaic-lowering auto-fallback probe (the
    ``predict_pallas`` precedent: opt-in kernel, warn + staged fallback
    when the local backend cannot lower it).  CPU always passes: the
    kernel runs in interpret mode there (the bit-parity lane)."""
    backend = jax.default_backend()
    if backend in _BACKEND_LOWERS:
        return _BACKEND_LOWERS[backend]
    if backend == "cpu":
        _BACKEND_LOWERS[backend] = True
        return True
    from ..utils.log import log_warning

    try:
        F, B, N, S = 4, 8, 64, 2
        meta = FeatureMeta(
            num_bins=jnp.full(F, B, jnp.int32),
            missing_type=jnp.zeros(F, jnp.int32),
            nan_bin=jnp.full(F, -1, jnp.int32),
            zero_bin=jnp.zeros(F, jnp.int32),
            is_categorical=jnp.zeros(F, bool),
            usable=jnp.ones(F, bool),
            monotone_type=jnp.zeros(F, jnp.int32),
        )
        from .split import SplitParams

        fn = make_fused_round(meta=meta, params=SplitParams(),
                              num_bins=B, precision="bf16x2",
                              deep_precision="bf16")
        rng = np.random.RandomState(0)
        binned_t = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.uint8))
        g3_t = jnp.asarray(rng.randn(N, 3).astype(np.float32))
        lids_t = jnp.asarray(rng.randint(0, 2 * S, N).astype(np.int32))
        kw = dict(mask=jnp.ones((2 * S, F), bool),
                  csums=jnp.abs(jnp.asarray(
                      rng.randn(2 * S, 3).astype(np.float32))),
                  constr=jnp.tile(jnp.asarray([-3e38, 3e38], jnp.float32),
                                  (2 * S, 1)),
                  depth=jnp.ones(2 * S, jnp.int32),
                  pout=jnp.zeros(2 * S, jnp.float32))
        # probe the ROUTED round (ISSUE 15: partition folded in) — the
        # superset the serial trainer dispatches — plus the valid-set
        # router; a backend that lowers histograms but not the routing
        # stage must fall back whole, never half
        rkw = dict(feats=jnp.arange(S, dtype=jnp.int32),
                   thrs=jnp.full(S, B // 2, jnp.int32),
                   dls=jnp.zeros(S, bool),
                   leafs=jnp.arange(S, dtype=jnp.int32),
                   nls=jnp.arange(S, dtype=jnp.int32) + S,
                   num_leaves=2 * S)
        jax.jit(lambda b, g, l: fn(
            b, g, None, S, **kw, route=dict(leaf_id=l, **rkw))
        ).lower(binned_t, g3_t, lids_t).compile()
        jax.jit(lambda b, l: fn.route_rows(b, l, **rkw)) \
            .lower(binned_t, lids_t).compile()
        _BACKEND_LOWERS[backend] = True
    except Exception as e:  # noqa: BLE001 — any lowering failure falls back
        log_warning(
            f"hist_method=fused: Mosaic could not lower the fused "
            f"wave-round kernel on backend {backend!r} "
            f"({type(e).__name__}); falling back to the staged "
            "histogram+split path")
        _BACKEND_LOWERS[backend] = False
    return _BACKEND_LOWERS[backend]
