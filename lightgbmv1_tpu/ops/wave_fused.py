"""Fused wave-round megakernel: histogram + split scan in ONE Pallas pass.

The staged wave round (the r05 phase table) is a pure-bandwidth
round-trip: ``hist_pallas`` writes the ``(slots, F, B, 3)`` histogram
stack to HBM, ``models/grower_wave.subtract_child_hists`` reads it back
to build the 2K-child stack, and ``ops/split.py``'s scan streams that
stack in again — three traversals of a tensor that is consumed exactly
once.  This kernel keeps the round's histograms in VMEM end to end:

* the row-tile grid REUSES ``hist_pallas._kernel`` verbatim (the one-hot
  MXU formulation with its bf16 / bf16x2 / int8 / int8sr precision
  modes) to accumulate each wave slot's histogram into a VMEM scratch
  accumulator,
* on the LAST row tile the same kernel invocation runs the split scan on
  the VMEM-resident stack: the smaller-child-subtraction path reads the
  parent histograms as a kernel input and subtracts in VMEM before
  scanning (the int8sr dequantize multiply folded in), then the staged
  scan's own stages — ``scan_left_sums`` (stacked two-direction cumsum +
  missing-mass adjust), ``scan_direction_gains`` (gain/penalty chain)
  and ``scan_pick_feature`` (tie-band preference argmax, per-feature
  half) — are composed AS THE SAME CODE OBJECTS on the VMEM values, so
  interpret-mode results are bit-identical to the staged path by
  construction, not by re-derivation,
* the round's PARTITION rides the same pass (ISSUE 15, the single-pass
  wave round): the feature-block-0 kernel invocation receives each
  row's DECISION BIN (the committed split feature's bin for the row's
  current leaf — one O(N) gather, the only extra touch of the binned
  matrix) plus the packed per-slot split metadata, evaluates the
  go-left decisions in VMEM with the staged partition's own
  ``ops/split.go_left_rule`` (bin compare + the NaN/zero
  missing-direction rules, op-for-op), writes the updated row→slot
  label into its own output block and accumulates the child histograms
  from it IN THE SAME SWEEP — the staged path's separate (S, N)
  decision pass over the binned rows (``phase_partition_ms``) and its
  HBM-resident mask intermediates disappear, and the kernel emits the
  new per-row leaf ids as a second O(N) output.  Valid-set routing
  rides the same decision stage (``fused_route_rows`` — a routing-only
  grid over the valid binned matrix, same ``route_tile`` code object),
  replacing the staged gather chain (``phase_valid_route_ms``),
* only an O(F) per-(child, feature) residue (best gain, in-band pick,
  left sums at the pick — ``RES_COLS`` floats per feature) leaves the
  kernel; the grid iterates feature blocks and the cross-feature half of
  ``scan_pick`` runs on the concatenated residue outside the kernel.
  The tie band needs the GLOBAL best gain, so a running in-VMEM
  reduction across feature blocks could mis-pick inside overlapping
  near-tie bands; reducing to the O(F) residue in VMEM and finishing the
  O(F) argmax outside keeps bit-exactness while still shrinking the
  kernel's HBM output from O(F·B) histograms to O(F) floats,
* the packed per-slot SplitInfo (``PACK_COLS`` floats per child) is all
  the round emits in pool-free mode; the subtraction-composed mode also
  emits the K smaller-child histograms (the per-leaf state the NEXT
  round's subtraction needs) — the ``(2K, F, B, 3)`` scan stack itself
  never materializes off-chip in either mode.

Fallback taxonomy (every gate logs once at build time,
parallel/trainer.py):

* categorical features — the sorted two-direction categorical scan
  (``_best_categorical``) argsorts per feature, which has no Mosaic
  lowering; such datasets run the staged path,
* ``extra_trees`` — per-node threshold sampling draws ``jax.random``
  inside the scan,
* EFB bundles / int16 bins — the scan runs in original-feature uint8
  bin space only.  4-bit PACKED bins are NOT a fallback leg any more
  (ISSUE 18): on the ``num_bins <= 16`` rung of the kernel-width
  ladder (``hist_pallas.kernel_width``) the fused round and the
  persistent wave loop consume the ``(ceil(F/2), N)`` packed matrix
  directly — nibbles unpack in VMEM (the reused ``_hist_tile`` packed
  path), the accumulator is restored to natural feature order before
  the scan, and the routing stage decodes decision bins from the
  packed bytes — so the round's dominant HBM read halves; packed bins
  at ``num_bins > 16`` cannot exist (a nibble holds 16 values) and are
  refused honestly,
* row-sharded learners (``tree_learner=data``/``voting``) — the
  cross-shard histogram reduce needs the explicit histogram on the wire;
  the feature-parallel learner DOES run the kernel per feature slice and
  elects through the existing ``_sync_best_split``,
* feature-parallel partition (partition-specific) — the in-kernel
  routing stage needs the committed split feature's GLOBAL column, but
  each shard's kernel sees only its own feature slice; the
  feature-parallel learner therefore keeps the staged (S, N) partition
  and per-slice election while still fusing histogram + scan,
* EFB decisions (partition-specific) — the go-left stage compares raw
  uint8 bins; bundle-column decode happens in ``bins_of_fn`` outside
  any kernel (EFB is already excluded by the histogram gate above, so
  the partition gate never fires alone).  Packed nibble decode, by
  contrast, IS in-kernel now: ``decision_bins`` gathers the packed
  byte by ``feature >> 1`` and selects the nibble by feature parity
  (the ``packed_bins_of_rows`` layout contract),
* Mosaic lowering failure on a device backend — auto-fallback with a
  warning, the ``predict_pallas`` precedent; the CPU backend always runs
  the kernel in interpret mode (the bit-parity lane the tests pin).
  The lowering probe compiles the ROUTED round (partition folded in)
  plus the valid-set router, so a backend that can fuse histograms but
  not the routing stage still falls back cleanly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..io.binning import MISSING_NAN, MISSING_ZERO
from .hist_pallas import (MAX_LANES, _kernel as _hist_tile, _row_tile_for,
                          packed_bins_of_rows)
from .split import (
    NEG_INF,
    NO_CONSTRAINT,
    FeatureMeta,
    SplitResult,
    child_leaf_output,
    gain_shift,
    go_left_rule,
    scan_direction_gains,
    scan_left_sums,
    scan_pick_feature,
    tie_tol,
)

RES_COLS = 6    # fbest, gain_at_sel, sel (direction*B+thr), left g/h/c
PACK_COLS = 10  # gain, feature, threshold, default_left, left(3), right(3)
RMETA_COLS = 8  # leaf, new-leaf, thr, default_left, mtype, nan_bin,
                # zero_bin, smaller-is-left — the packed per-slot split
                # metadata the routing stage consumes (int32)



def route_tile(dbin, oleaf, rmeta, *, nslots, sub, want_label=True):
    """The fused decision stage on one row tile — pure jnp on VALUES, so
    the megakernel (train rows), the routing-only valid-set kernel and
    any host-side replay all run the SAME code object.

    ``dbin`` (1, T) int32 — each row's DECISION bin: the bin of its
    current leaf's committed split feature (rows of non-splitting
    leaves carry an arbitrary bin; their ``mine`` mask is False).
    ``oleaf`` (1, T) int32 — current leaf ids (pad rows carry -1).
    ``rmeta`` (S, RMETA_COLS) int32 — per-slot split metadata; dead
    slots carry leaf id ``num_leaves`` (matches no row).

    Returns ``(new_leaf (1, T), label (1, T) or None)``: the updated
    row→leaf routing and (``want_label``) the row→histogram-slot label
    (smaller-child slot in subtraction mode, ``2s + right`` pool-free;
    ``nslots`` = dead).  Mirrors the staged ``go_left_s`` partition
    op-for-op — every update term is int32, so deferring/fusing is
    bit-identical to the staged pass by construction."""
    S = rmeta.shape[0]
    leafs = rmeta[:, 0:1]
    nls = rmeta[:, 1:2]
    thr = rmeta[:, 2:3]
    dl = rmeta[:, 3:4] != 0
    mt = rmeta[:, 4:5]
    nanb = rmeta[:, 5:6]
    zb = rmeta[:, 6:7]
    sml = rmeta[:, 7:8] != 0
    mine = oleaf == leafs                                    # (S, T)
    g = go_left_rule(dbin, thr, dl, mt, nanb, zb)            # (S, T)
    new_leaf = oleaf + jnp.sum(
        jnp.where(mine & (~g), nls - oleaf, 0), axis=0, keepdims=True)
    if not want_label:
        return new_leaf, None
    siota = lax.broadcasted_iota(jnp.int32, (S, 1), 0)
    if sub:
        hit = mine & (g == sml)
        slot = jnp.broadcast_to(siota, mine.shape)
    else:
        hit = mine
        slot = 2 * siota + (~g).astype(jnp.int32)
    label = jnp.sum(jnp.where(hit, slot - nslots, 0),
                    axis=0, keepdims=True) + nslots
    return new_leaf, label


def pack_route_meta(feats, thrs, dls, leafs, nls, meta, sml=None):
    """(S, RMETA_COLS) int32 routing metadata from rank/slot-order split
    arrays + the feature meta — one place, so the megakernel's train
    stage and the valid-set router cannot pack differently."""
    feats = feats.astype(jnp.int32)
    z = jnp.zeros_like(feats)
    return jnp.stack([
        leafs.astype(jnp.int32),
        nls.astype(jnp.int32),
        thrs.astype(jnp.int32),
        dls.astype(jnp.int32),
        meta.missing_type[feats].astype(jnp.int32),
        meta.nan_bin[feats].astype(jnp.int32),
        meta.zero_bin[feats].astype(jnp.int32),
        (sml.astype(jnp.int32) if sml is not None else z),
    ], axis=1)


def decision_bins(binned, lids, feats, leafs, num_leaves, packed=False):
    """Each row's decision bin — ``binned[f(leaf(row)), row]`` via a
    leaf→feature table and ONE per-element gather (O(N) bytes), the
    only touch of the binned matrix the routing stage adds.  Rows of
    non-splitting leaves read feature 0; their slot mask is False.
    ``packed``: ``binned`` is the 4-bit matrix — the gather indexes the
    packed byte (``feature >> 1``, HALF the bytes touched) and selects
    the nibble by feature parity (``packed_bins_of_rows``, the layout's
    single source of truth)."""
    tab = jnp.zeros(num_leaves + 1, jnp.int32) \
        .at[leafs].set(feats.astype(jnp.int32), mode="drop")
    f_of = tab[lids]                                        # (N,)
    if packed:
        return packed_bins_of_rows(binned, f_of)
    return jnp.take_along_axis(binned, f_of[None, :], axis=0)[0] \
        .astype(jnp.int32)


def child_scan_residue(hc, mask_c, csum_c, constr_c, depth_c, pout_c,
                       hsc_c, *, meta_blk, params, use_mc,
                       monotone_penalty, child_scale, num_bins, fblk):
    """One child's in-VMEM split scan -> its (fblk, RES_COLS) residue:
    the staged scan's OWN stages (``scan_left_sums`` ->
    ``scan_direction_gains`` -> ``scan_pick_feature``) composed on VMEM
    values.  Module-level so the single-round megakernel and the
    persistent wave-loop kernel (``make_fused_wave_loop``) run the SAME
    code object — the loop's bit-parity contract rides on that, exactly
    as the grower's ``clamp_out`` rides on ``split.child_leaf_output``."""
    left2, _ = scan_left_sums(hc, meta_blk, hsc_c if child_scale else None)
    gains, shift = scan_direction_gains(
        left2, csum_c, meta_blk, mask_c, params, constr_c, depth_c,
        monotone_penalty, pout_c, None, None, use_mc=use_mc)
    fbest, sel = scan_pick_feature(gains, shift, meta_blk)
    gains_f = jnp.concatenate([gains[0], gains[1]], axis=1)
    gsel = jnp.take_along_axis(gains_f, sel[:, None], axis=1)[:, 0]
    lsel = left2[sel // num_bins, jnp.arange(fblk), sel % num_bins]
    return jnp.concatenate(
        [fbest[:, None], gsel[:, None],
         sel.astype(jnp.float32)[:, None], lsel], axis=1)


def _fused_kernel(*refs, nrt, lpad, num_bins, fblk, precision, interpret,
                  params, use_mc, monotone_penalty, has_contri, sub,
                  apply_scale, child_scale, nslots, nchildren,
                  route_blk=False, fpb=0):
    """Grid ``(1, row_tiles)``: every tile accumulates its rows via the
    REUSED ``hist_pallas._kernel``; the last tile runs the split scan on
    the VMEM accumulator and writes the per-feature residue (plus, in
    subtraction mode, the raw smaller-child histograms).

    ``route_blk`` (feature block 0 of a routed round): the tile FIRST
    evaluates the committed splits' go-left decisions (``route_tile`` on
    the decision-bin/old-leaf tiles + the packed slot metadata), writes
    the row→slot label into its own output block — which the remaining
    feature blocks consume as their ``leaf`` input — and the new per-row
    leaf ids, then accumulates this block's histogram FROM the label it
    just produced: partition and histogram share one sweep of the rows.

    ``fpb > 0`` (4-bit packed bins, ISSUE 18): the bins tile holds
    ``fpb`` packed byte columns whose nibbles ``_hist_tile`` unpacks in
    VMEM to the ``fblk == 2*fpb`` unpacked feature block — its lane
    order is [lo nibbles | hi nibbles], so before the scan the
    accumulator's feature axis is re-interleaved back to NATURAL order
    (lo/hi alternating).  Everything downstream — subtraction, residue
    scan, the order-sensitive tie-band pick — then sees exactly the
    unpacked kernel's values in the unpacked kernel's order.
    """
    names = ["iota", "bins", "g3"]
    names += (["dbin", "oleaf", "rmeta"] if route_blk else ["leaf"])
    names += ["nb", "mt", "nanb", "zb", "usbl", "mono"]
    if has_contri:
        names.append("contri")
    names += ["mask", "csums", "constr", "depth", "pout"]
    if child_scale:
        names.append("cscale")
    if sub and apply_scale:
        names.append("sscale")
    if sub:
        names += ["sml", "parent"]
    names.append("res")
    if sub:
        names.append("hsmall")
    if route_blk:
        names += ["lab", "nleaf"]
    names.append("acc")
    r = dict(zip(names, refs))

    if route_blk:
        new_leaf, label = route_tile(
            r["dbin"][...], r["oleaf"][...], r["rmeta"][...],
            nslots=nslots, sub=sub)
        r["lab"][...] = label
        r["nleaf"][...] = new_leaf
        leaf_ref = r["lab"]
    else:
        leaf_ref = r["leaf"]

    _hist_tile(r["iota"], r["bins"], r["g3"], leaf_ref, r["acc"],
               lpad=lpad, num_bins=num_bins, fblk=fblk,
               precision=precision, interpret=interpret, packed=fpb > 0)

    rt = pl.program_id(1)
    B = num_bins

    @pl.when(rt == nrt - 1)
    def _scan():
        # accumulator rows are (slot-major, channel-minor), lanes are
        # (bin-major, feature-minor) — the same unscramble
        # hist_leaves_pallas applies outside, here on VMEM values
        acc = r["acc"][0]                               # (3*lpad, B*fblk)
        h = acc.reshape(lpad, 3, B, fblk).transpose(0, 3, 2, 1)
        if fpb:
            # packed accumulator order is [lo nibbles | hi nibbles]; the
            # tie-band pick is feature-ORDER-sensitive (first in band =
            # min feature), so restore natural order BEFORE any scan
            h = jnp.stack([h[:, :fpb], h[:, fpb:]], axis=2) \
                .reshape(lpad, fblk, B, 3)
        meta_blk = FeatureMeta(
            num_bins=r["nb"][...][0],
            missing_type=r["mt"][...][0],
            nan_bin=r["nanb"][...][0],
            zero_bin=r["zb"][...][0],
            is_categorical=jnp.zeros(fblk, bool),
            usable=r["usbl"][...][0] != 0,
            monotone_type=r["mono"][...][0],
            contri=(r["contri"][...][0] if has_contri else None),
        )
        if sub:
            # smaller-child + parent subtraction IN VMEM — the exact op
            # order of subtract_child_hists (dequant multiply first, then
            # the smaller/larger select), so values are bit-identical
            hsm = h[:nslots]                            # (S, fblk, B, 3)
            r["hsmall"][...] = hsm                      # raw (int on quant)
            if apply_scale:
                # power-of-two scales (ops/quantize.py) make this exact,
                # so the parent subtraction rounds the same with or
                # without fma contraction — matches the host grower's
                # subtract_child_hists bit-for-bit in any fusion context
                hsm = hsm * r["sscale"][...][:, None, None, :]
            sml = (r["sml"][...][:, 0] != 0)[:, None, None, None]
            parent = r["parent"][...]
            h_left = jnp.where(sml, hsm, parent - hsm)
            h_right = parent - h_left
            ch = jnp.stack([h_left, h_right], axis=1).reshape(
                (2 * nslots,) + h_left.shape[1:])       # (2S, fblk, B, 3)
        else:
            ch = h[:nchildren]


        mask = r["mask"][...] != 0                      # (C, fblk)
        csums = r["csums"][...]
        constr = r["constr"][...]
        depth = r["depth"][...][:, 0]
        pout = r["pout"][...][:, 0]
        cscale = (r["cscale"][...] if child_scale
                  else jnp.zeros((nchildren, 3), jnp.float32))

        child_scan = functools.partial(
            child_scan_residue, meta_blk=meta_blk, params=params,
            use_mc=use_mc, monotone_penalty=monotone_penalty,
            child_scale=child_scale, num_bins=B, fblk=fblk)
        r["res"][...] = jax.vmap(child_scan)(
            ch, mask, csums, constr, depth, pout, cscale)


def fused_wave_scan(binned, g3, label, *, nslots, nchildren, num_bins,
                    precision, interpret, meta, params, use_mc,
                    monotone_penalty, mask, csums, constr, depth, pout,
                    cscale=None, sscale=None, sml=None, parent=None,
                    apply_scale=False, row_tile=0, route=None,
                    packed=False):
    """One fused wave round over all feature blocks.

    ``nslots`` counts the ACCUMULATED slots (smaller children in
    subtraction mode, all 2S children pool-free); slot ``nslots`` is the
    sacrificial dead-row slot, as in ``hist_wave``.  ``parent`` non-None
    selects the subtraction-composed mode.  ``route`` non-None (dict
    ``dbin (N,) / oleaf (N,) / rmeta (S, RMETA_COLS)``) folds the
    partition in: ``label`` is ignored (pass None) — feature block 0
    evaluates the go-left decisions in VMEM, emits the label the other
    blocks consume and the updated per-row leaf ids.  ``packed``:
    ``binned`` is the ``(ceil(F/2), N)`` 4-bit matrix (num_bins <= 16)
    — each block streams its PACKED byte columns (half the HBM binned
    read) and unpacks nibbles in VMEM; a block's ``fblk`` unpacked
    features are the CONTIGUOUS natural range ``[fb*fblk, (fb+1)*fblk)``
    (lo nibble = feature 2p, hi = 2p+1), so the per-feature meta/mask/
    parent slices below are identical to the unpacked layout.  Returns
    ``(residue (C, F, RES_COLS), hsmall (nslots, F, B, 3) or None,
    new_leaf (N,) or None)``.
    """
    sub = parent is not None
    C = nchildren
    F = mask.shape[1]
    B = num_bins
    N = binned.shape[1]
    if packed:
        # fblk counts UNPACKED features and must be even (each byte
        # column contributes its lo and hi nibble feature); the phantom
        # hi-nibble feature of an odd-F tail pads to unusable below
        Fp = binned.shape[0]
        fblk = max(2, min(2 * Fp, MAX_LANES // B) & ~1)
        fpb = fblk // 2                  # packed byte columns per block
        nfb = -(-Fp // fpb)
    else:
        fpb = 0
        fblk = max(1, min(F, MAX_LANES // B))
        nfb = -(-F // fblk)
    f_pad = nfb * fblk
    L = nslots + 1
    lpad = -(-L // 8) * 8
    m_pad = 3 * lpad
    # the row tile is priced on the UNPACKED lane count either way: the
    # same T means the same row partition, so every (leaf, bin, feature)
    # accumulator cell sums the same per-tile dots in the same order —
    # the packed round's f32 histograms are bit-identical to unpacked
    T = row_tile if row_tile > 0 else _row_tile_for(
        m_pad, max(1, min(F, MAX_LANES // B)) * B, B)
    nrt = -(-N // T)
    n_pad = nrt * T

    # padding identical to hist_leaves_pallas: padded features collect
    # bin 255 (no bin when B < 256; masked unusable below when B == 256;
    # packed pad bytes are 0 -> phantom features collect bin 0 and are
    # masked unusable below), padded rows carry zero g3 and an
    # out-of-range slot id
    tile_cols = fpb if packed else fblk   # stored byte columns per block
    binned_rm = jnp.pad(
        binned,
        ((0, nfb * tile_cols - binned.shape[0]), (0, n_pad - N)),
        constant_values=0 if packed else 255).T   # (n_pad, nfb*tile_cols)
    g3t = jnp.pad(g3.astype(jnp.float32), ((0, n_pad - N), (0, 0))).T
    if route is not None:
        # pad rows: leaf -1 matches no slot -> the routing stage labels
        # them with the dead slot (zero g3 anyway) and passes the -1
        # leaf through (sliced off below)
        dbin_p = jnp.pad(route["dbin"].astype(jnp.int32),
                         (0, n_pad - N))[None, :]
        oleaf_p = jnp.pad(route["oleaf"].astype(jnp.int32),
                          (0, n_pad - N), constant_values=-1)[None, :]
        rmeta = route["rmeta"].astype(jnp.int32)
        leaf_p = None
    else:
        leaf_p = jnp.pad(label.astype(jnp.int32), (0, n_pad - N),
                         constant_values=lpad)[None, :]
    iota_bins = (jnp.arange(B * fblk, dtype=jnp.int32)
                 // fblk).astype(jnp.float32)[None, :]

    def padf(a, cv, dtype=jnp.int32):
        return jnp.pad(a.astype(dtype), (0, f_pad - F),
                       constant_values=cv)[None, :]

    nb_p = padf(meta.num_bins, 1)
    mt_p = padf(meta.missing_type, 0)
    nanb_p = padf(meta.nan_bin, -1)
    zb_p = padf(meta.zero_bin, 0)
    us_p = padf(meta.usable, 0)
    mono_p = padf(meta.monotone_type, 0)
    has_contri = meta.contri is not None
    contri_p = padf(meta.contri, 1.0, jnp.float32) if has_contri else None
    mask_p = jnp.pad(mask.astype(jnp.int8), ((0, 0), (0, f_pad - F)))
    parent_p = (jnp.pad(parent.astype(jnp.float32),
                        ((0, 0), (0, f_pad - F), (0, 0), (0, 0)))
                if sub else None)
    csums2 = csums.astype(jnp.float32)
    constr2 = constr.astype(jnp.float32)
    depth2 = depth.astype(jnp.int32)[:, None]
    pout2 = pout.astype(jnp.float32)[:, None]
    sml2 = sml.astype(jnp.int32)[:, None] if sub else None
    child_scale = cscale is not None

    kern = functools.partial(
        _fused_kernel, nrt=nrt, lpad=lpad, num_bins=B, fblk=fblk,
        precision=precision, interpret=interpret, params=params,
        use_mc=use_mc, monotone_penalty=monotone_penalty,
        has_contri=has_contri, sub=sub, apply_scale=apply_scale,
        child_scale=child_scale, nslots=nslots, nchildren=C, fpb=fpb)

    def full_spec(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, lambda fb, rt, _n=nd: (0,) * _n)

    res_blocks, hs_blocks = [], []
    new_leaf = None
    for fb in range(nfb):
        route_blk = route is not None and fb == 0
        sl = slice(fb * fblk, (fb + 1) * fblk)
        bsl = slice(fb * tile_cols, (fb + 1) * tile_cols)
        ins = [iota_bins, binned_rm[:, bsl], g3t]
        specs = [
            pl.BlockSpec((1, fblk * B), lambda fb_, rt: (0, 0)),
            pl.BlockSpec((T, tile_cols), lambda fb_, rt: (rt, 0)),
            pl.BlockSpec((3, T), lambda fb_, rt: (0, rt)),
        ]
        if route_blk:
            # block 0 routes: decision bins + old leaf ids per row tile,
            # packed slot metadata resident; the label it emits becomes
            # the remaining blocks' ``leaf`` input below
            ins += [dbin_p, oleaf_p, rmeta]
            specs += [pl.BlockSpec((1, T), lambda fb_, rt: (0, rt)),
                      pl.BlockSpec((1, T), lambda fb_, rt: (0, rt)),
                      full_spec(rmeta.shape)]
        else:
            ins.append(leaf_p)
            specs.append(pl.BlockSpec((1, T), lambda fb_, rt: (0, rt)))
        ins += [nb_p[:, sl], mt_p[:, sl], nanb_p[:, sl], zb_p[:, sl],
                us_p[:, sl], mono_p[:, sl]]
        specs += [full_spec((1, fblk))] * 6
        if has_contri:
            ins.append(contri_p[:, sl])
            specs.append(full_spec((1, fblk)))
        ins.append(mask_p[:, sl])
        specs.append(full_spec((C, fblk)))
        for a in (csums2, constr2, depth2, pout2):
            ins.append(a)
            specs.append(full_spec(a.shape))
        if child_scale:
            ins.append(cscale.astype(jnp.float32))
            specs.append(full_spec((C, 3)))
        if sub and apply_scale:
            ins.append(sscale.astype(jnp.float32))
            specs.append(full_spec((nslots, 3)))
        if sub:
            ins += [sml2, parent_p[:, sl]]
            specs += [full_spec((nslots, 1)),
                      full_spec((nslots, fblk, B, 3))]
        out_shape = [jax.ShapeDtypeStruct((C, fblk, RES_COLS),
                                          jnp.float32)]
        out_specs = [full_spec((C, fblk, RES_COLS))]
        if sub:
            out_shape.append(
                jax.ShapeDtypeStruct((nslots, fblk, B, 3), jnp.float32))
            out_specs.append(full_spec((nslots, fblk, B, 3)))
        if route_blk:
            out_shape += [jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
                          jax.ShapeDtypeStruct((1, n_pad), jnp.int32)]
            out_specs += [pl.BlockSpec((1, T), lambda fb_, rt: (0, rt)),
                          pl.BlockSpec((1, T), lambda fb_, rt: (0, rt))]
        out = pl.pallas_call(
            functools.partial(kern, route_blk=route_blk),
            grid=(1, nrt),
            in_specs=specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((1, m_pad, fblk * B), jnp.float32)],
            interpret=interpret,
        )(*ins)
        res_blocks.append(out[0])
        if sub:
            hs_blocks.append(out[1])
        if route_blk:
            leaf_p = out[2 if sub else 1]         # the emitted label
            new_leaf = out[3 if sub else 2][0, :N]
    residue = (jnp.concatenate(res_blocks, axis=1)
               if nfb > 1 else res_blocks[0])[:, :F]
    hsmall = None
    if sub:
        hsmall = (jnp.concatenate(hs_blocks, axis=1)
                  if nfb > 1 else hs_blocks[0])[:, :F]
    return residue, hsmall, new_leaf


def _route_only_kernel(dbin_ref, oleaf_ref, rmeta_ref, out_ref):
    """One routing-only tile: the fused decision stage (``route_tile``)
    with no histogram behind it — the valid-set lane."""
    new_leaf, _ = route_tile(dbin_ref[...], oleaf_ref[...],
                             rmeta_ref[...], nslots=0, sub=False,
                             want_label=False)
    out_ref[...] = new_leaf


def fused_route_rows(binned, lids, *, feats, thrs, dls, leafs, nls,
                     num_leaves, meta, interpret, row_tile=1024,
                     packed=False):
    """Route one row set through a round's committed splits with the
    SAME kernel decision stage the megakernel runs on the train rows —
    the valid-set lane of the single-pass round (ISSUE 15).

    Replaces the staged gather chain (per-split bin gather + (S, N)
    masks in HBM): one O(N) decision-bin gather feeds a routing-only
    Pallas grid whose tiles evaluate ``route_tile`` in VMEM and emit
    only the updated leaf ids.  Every update term is int32, so the
    result is bit-identical to the staged ``go_left_s``/
    ``route_pending`` routing (pinned in tests/test_wave_fused.py).
    ``packed``: ``binned`` is the 4-bit matrix — the decision-bin
    gather decodes nibbles (``decision_bins``), same int32 values.
    """
    N = lids.shape[0]
    if N == 0:
        return lids
    dbin = decision_bins(binned, lids, feats, leafs, num_leaves,
                         packed=packed)
    rmeta = pack_route_meta(feats, thrs, dls, leafs, nls, meta)
    T = min(row_tile, max(128, -(-N // 128) * 128))
    nrt = -(-N // T)
    n_pad = nrt * T
    dbin_p = jnp.pad(dbin, (0, n_pad - N))[None, :]
    oleaf_p = jnp.pad(lids.astype(jnp.int32), (0, n_pad - N),
                      constant_values=-1)[None, :]
    out = pl.pallas_call(
        _route_only_kernel,
        grid=(nrt,),
        in_specs=[
            pl.BlockSpec((1, T), lambda rt: (0, rt)),
            pl.BlockSpec((1, T), lambda rt: (0, rt)),
            pl.BlockSpec(rmeta.shape, lambda rt: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, T), lambda rt: (0, rt)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        interpret=interpret,
    )(dbin_p, oleaf_p, rmeta)
    return out[0, :N]


def _pick_pack(residue_c, shift_c, parent_sum_c, meta, num_bins):
    """Cross-feature half of ``scan_pick`` on one child's O(F) residue,
    plus the non-categorical tail of ``_find_best_split`` (right sums,
    missing default direction) — the packed per-slot SplitInfo the round
    emits.  Formula-for-formula the staged code, evaluated on identical
    inputs, so the pick is bit-identical."""
    fbest = residue_c[:, 0]
    gsel = residue_c[:, 1]
    sel = residue_c[:, 2].astype(jnp.int32)
    gbest = jnp.max(fbest)
    feature = jnp.argmax(fbest >= gbest - tie_tol(gbest, shift_c)) \
        .astype(jnp.int32)                   # first in band = min feature
    best_gain = gsel[feature]
    sc = sel[feature]
    direction = (sc // num_bins).astype(jnp.int32)
    threshold = (sc % num_bins).astype(jnp.int32)
    left = residue_c[feature, 3:6]
    right = parent_sum_c - left
    mtype = meta.missing_type[feature]
    default_left = jnp.where(
        (mtype == MISSING_NAN) | (mtype == MISSING_ZERO),
        direction == 1, False)
    rel_gain = jnp.where(jnp.isfinite(best_gain), best_gain, NEG_INF)
    return jnp.concatenate([
        jnp.stack([rel_gain.astype(jnp.float32),
                   feature.astype(jnp.float32),
                   threshold.astype(jnp.float32),
                   default_left.astype(jnp.float32)]),
        left.astype(jnp.float32), right.astype(jnp.float32)])


def pack_children(res: SplitResult) -> jnp.ndarray:
    """Batched SplitResult -> the (C, PACK_COLS) wire rows (no bitset —
    the fused path never produces categorical splits)."""
    return jnp.concatenate([
        res.gain[:, None],
        res.feature.astype(jnp.float32)[:, None],
        res.threshold_bin.astype(jnp.float32)[:, None],
        res.default_left.astype(jnp.float32)[:, None],
        res.left_sum, res.right_sum], axis=1)


def unpack_children(packed: jnp.ndarray, num_bins: int) -> SplitResult:
    """(C, PACK_COLS) rows -> batched SplitResult (is_cat False, zero
    bitset — the fused gate excludes categorical datasets)."""
    W = -(-num_bins // 32)
    C = packed.shape[0]
    return SplitResult(
        gain=packed[:, 0],
        feature=packed[:, 1].astype(jnp.int32),
        threshold_bin=packed[:, 2].astype(jnp.int32),
        default_left=packed[:, 3] != 0,
        left_sum=packed[:, 4:7],
        right_sum=packed[:, 7:10],
        is_cat=jnp.zeros(C, bool),
        cat_bitset=jnp.zeros((C, W), jnp.uint32),
    )


def make_fused_round(*, meta, params, num_bins, precision, deep_precision,
                     monotone_penalty=0.0, interpret=False,
                     axis_name=None, packed=False):
    """Build the grower-facing ``fused_round_fn``.

    ``fused_round(binned, g3, label, S, *, deep, quant_key, scaled,
    mask, csums, constr, depth, pout, sml, parent, meta_override,
    feature_rebase, route) -> (packed (2S, PACK_COLS), hsmall or None,
    slot_scales (nslots, 3))`` — plus ``new_leaf (N,)`` when routed.

    * ``route`` non-None (dict ``leaf_id (N,) / feats / thrs / dls /
      leafs / nls (S,) / num_leaves``) folds the round's PARTITION into
      the kernel (ISSUE 15): ``label`` must be None — the kernel
      evaluates the committed splits' go-left decisions in VMEM
      (``route_tile`` + the staged partition's own
      ``split.go_left_rule``) while sweeping the rows for the
      histograms, and the call returns the updated per-row leaf ids as
      a fourth output.  The decision-bin gather (``decision_bins``,
      O(N) bytes) is the routing stage's only extra touch of the binned
      matrix — the round reads the binned rows ONCE.  The builder marks
      the returned callable ``supports_route=True`` and hangs the
      valid-set router on it as ``route_rows`` (same decision stage
      over a valid binned matrix); the feature-parallel trainer wrapper
      deliberately has neither (its shard sees only a feature slice —
      the partition-specific fallback of the module taxonomy).

    * ``deep`` — sustained-bucket round: the kernel accumulates at
      ``deep_precision`` (the staged deep-dtype policy, so precision per
      bucket cannot drift between the paths).
    * ``quant_key`` non-None — an int8sr-eligible bucket
      (models/grower_wave.py quant gate: the sustained bucket and the
      16-slot ramp of a K>16 wave; root and <=4-slot ramps never reach
      here): the gradients are stochastic-round quantized with the SAME
      ``sr_quantize_g3`` call the staged pass makes, and the dequantize
      multiply folds into the in-VMEM subtraction (or the scan's integer
      cumsum pool-free) exactly where the staged path folds it.
    * ``scaled`` — quant buckets exist this grow (the staged path then
      applies identity scales on non-quant rounds too; mirrored for bit
      parity).
    * ``meta_override``/``feature_rebase`` — the feature-parallel
      learner passes its (traced) per-shard meta slice and block offset;
      packed feature ids come back shard-local and are rebased by the
      caller after the SplitInfo election.
    * ``packed`` (builder-static, ISSUE 18) — the binned matrix is the
      4-bit ``(ceil(F/2), N)`` layout; the kernel unpacks nibbles in
      VMEM and the routing stage (train AND valid: ``route_rows`` binds
      it too) decodes decision bins from the packed bytes.
    """
    from .quantize import sr_quantize_g3

    use_mc = bool(np.asarray(meta.monotone_type).any())

    def fused_round(binned, g3, label, S, *, deep=False, quant_key=None,
                    scaled=False, mask=None, csums=None, constr=None,
                    depth=None, pout=None, sml=None, parent=None,
                    meta_override=None, route=None):
        sub = parent is not None
        C = 2 * S
        nslots = S if sub else C
        m = meta_override if meta_override is not None else meta
        if quant_key is not None:
            # routed rounds have no precomputed label; sr_quantize_g3's
            # global-scale implementation ignores it (per-pass scales),
            # so the rounding stream — and int8sr bit-reproducibility —
            # is identical to the staged pass either way
            q3, scales = sr_quantize_g3(
                g3, route["leaf_id"] if route is not None else label,
                nslots, quant_key, axis_name=axis_name)
            g3u, prec = q3, "int8sr"
        else:
            scales = jnp.ones((nslots, 3), jnp.float32)
            g3u = g3
            prec = deep_precision if deep else precision
        route_in = None
        if route is not None:
            route_in = dict(
                dbin=decision_bins(binned, route["leaf_id"],
                                   route["feats"], route["leafs"],
                                   route["num_leaves"], packed=packed),
                oleaf=route["leaf_id"],
                rmeta=pack_route_meta(route["feats"], route["thrs"],
                                      route["dls"], route["leafs"],
                                      route["nls"], m, sml=sml))
        with jax.named_scope("lgbm.fused_round"):
            residue, hsmall, new_leaf = fused_wave_scan(
                binned, g3u, label, nslots=nslots, nchildren=C,
                num_bins=num_bins, precision=prec, interpret=interpret,
                meta=m, params=params, use_mc=use_mc,
                monotone_penalty=monotone_penalty, mask=mask,
                csums=csums, constr=constr, depth=depth, pout=pout,
                cscale=(scales if (scaled and not sub) else None),
                sscale=(scales if (scaled and sub) else None),
                sml=sml, parent=parent, apply_scale=(scaled and sub),
                route=route_in, packed=packed)
            shift = jax.vmap(
                lambda ps, po: gain_shift(ps, po, params))(csums, pout)
            ptab = jax.vmap(
                lambda rc, sh, ps: _pick_pack(rc, sh, ps, m, num_bins)
            )(residue, shift, csums)
        if route is not None:
            return ptab, hsmall, scales, new_leaf
        return ptab, hsmall, scales

    fused_round.supports_route = True
    fused_round.packed = packed
    fused_round.route_rows = functools.partial(
        fused_route_rows, meta=meta, interpret=interpret, packed=packed)
    return fused_round


class _ValRef:
    """Minimal ref-shaped adapter over a VALUE so kernel helpers written
    against Pallas refs (``_hist_tile``'s g3/leaf inputs) can consume
    values the loop kernel computed in-register — the quantized gradient
    rows and the routing label — without a scratch round-trip."""

    def __init__(self, v):
        self._v = v

    @property
    def shape(self):
        return self._v.shape

    @property
    def dtype(self):
        return self._v.dtype

    def __getitem__(self, idx):
        return self._v[idx]


_LOOP_MAX_ROUNDS = 64
_LOOP_VMEM_BUDGET = 14 * 2 ** 20


def plan_wave_loop(*, rounds, N, F, num_bins, K, L, use_sub, slot_buckets,
                   quant_buckets=(), precision="f32", deep_precision="f32",
                   use_mc=False, packed=False,
                   vmem_budget=_LOOP_VMEM_BUDGET):
    """Static VMEM-budget planner for the persistent wave loop.

    Decides — entirely at trace/build time, from shapes and knobs — how
    many consecutive rounds ``R`` one launch may run and whether the
    loop is eligible at all; the returned dict is recorded verbatim in
    the BENCH record (``measure_fused_waveloop``) so a capture shows WHY
    a shape ran looped or fell back.  The resident-state footprint is
    R-independent (the packed SplitInfo tables stream out per round), so
    R is capped only by the sanity bound ``_LOOP_MAX_ROUNDS``; the
    budget decides looped-vs-single-round, and the slot-bucket LADDER
    constraint below decides whether the staged bucket dispatch can be
    mimicked bit-exactly inside one kernel:

    * the row tile must be IDENTICAL for every ladder bucket — the loop
      accumulates every round at the K-slot tile, and a bucket whose
      staged tile differs would change the f32 accumulation order;
    * int8sr rounds inside the loop require ``precision == "f32"``: the
      loop accumulates the exact-integer quantized rows through the f32
      MXU path, which matches the staged int8 path bit-for-bit BECAUSE
      both are exact (|q| <= 127, <= 1024 rows per tile => every per-tile
      partial sum < 2^24), but a bf16 base precision would not be;
    * a reachable deep bucket (K >= 32, multi-bucket ladder, no quant)
      requires ``deep_precision == precision`` — one static accumulate
      dtype for the whole loop.

    ``packed`` (ISSUE 18): the loop keeps the 4-bit PACKED matrix
    resident — the bins row tile is priced on packed bytes (HALF), and
    the kernel feature width is the even ``2*ceil(F/2)`` nibble span
    (the phantom odd-F feature rides masked-unusable).  The row tile
    itself is still derived from the UNPACKED lane count, so packed and
    unpacked loops share the accumulation partition (bit parity).
    """
    B = num_bins
    Fk = 2 * -(-F // 2) if packed else F    # kernel feature width
    Fb = -(-F // 2) if packed else F        # stored bins columns

    def lanes_pad(S):
        nsl = S if use_sub else 2 * S
        return 3 * (-(-(nsl + 1) // 8) * 8)

    m_pad = lanes_pad(K)
    T = _row_tile_for(m_pad, F * B, B)
    nrt = -(-max(N, 1) // T)
    n_pad = nrt * T
    acc_bytes = m_pad * Fk * B * 4
    # the one-hot working set _row_tile_for budgets for, per row tile,
    # plus the resident bins row tile (packed bytes when packed — the
    # layout's VMEM dividend)
    stream_bytes = T * (14 * min(Fk * B, 512) + 16 * m_pad) + T * Fb
    state_bytes = (L * 12 * 4 + n_pad * 4
                   + (L * Fk * B * 3 * 4 if use_sub else 0))
    total_bytes = acc_bytes + stream_bytes + state_bytes
    plan = dict(eligible=False, rounds=1, reason="",
                acc_bytes=int(acc_bytes), state_bytes=int(state_bytes),
                stream_bytes=int(stream_bytes),
                total_bytes=int(total_bytes), row_tile=int(T),
                ladder=tuple(int(s) for s in slot_buckets),
                vmem_budget=int(vmem_budget),
                packed=bool(packed),
                binned_bytes=int(Fb * max(N, 1)),
                binned_tile_bytes=int(T * Fb))
    if rounds <= 1:
        plan["reason"] = "wave_loop_rounds=1 (single-round dispatch)"
        return plan
    if Fk * B > MAX_LANES:
        plan["reason"] = ("F*num_bins > MAX_LANES: multi-feature-block "
                          "rounds keep the single-round kernel")
        return plan
    if use_mc:
        plan["reason"] = ("monotone constraints propagate per-round "
                          "bounds outside the kernel")
        return plan
    if quant_buckets and precision != "f32":
        plan["reason"] = ("int8sr-in-loop needs the exact-integer f32 "
                          "accumulate (hist_dtype=f32)")
        return plan
    if (not quant_buckets and K >= 32 and len(slot_buckets) > 1
            and deep_precision != precision):
        plan["reason"] = ("deep-precision drop would change the "
                          "accumulate dtype mid-loop")
        return plan
    tiles = {_row_tile_for(lanes_pad(S), F * B, B) for S in slot_buckets}
    if len(tiles) > 1:
        plan["reason"] = ("slot-bucket ladder changes the row tile "
                          "(accumulation order would differ)")
        return plan
    if total_bytes > vmem_budget:
        plan["reason"] = (
            f"resident state + accumulator ({total_bytes} B) exceeds the "
            f"VMEM budget ({vmem_budget} B)")
        return plan
    plan["eligible"] = True
    plan["rounds"] = int(min(rounds, _LOOP_MAX_ROUNDS))
    return plan


def _loop_kernel(*refs, R, nrt, T, lpad, num_bins, fblk, N, K, L,
                 precision, interpret, params, monotone_penalty,
                 has_contri, sub, scaled, ladder, quant_ladder, max_depth,
                 topk_fn, qmax, packed=False):
    """Grid ``(R, row_tiles)`` — R consecutive wave rounds in ONE launch,
    the frontier state resident in VMEM scratch between them:

    * ``ft_scr`` (L, 12) — the frontier table: per-leaf [gain, feature,
      threshold, default_left, left sums (3), right sums (3), output,
      depth], exactly the split-store columns the staged round boundary
      reads back from HBM;
    * ``pool_scr`` (L, F, B, 3) — the histogram pool (subtraction mode);
    * ``leaf_scr`` (1, n_pad) — row -> leaf routing labels;
    * ``nl_scr`` (1, 1) — the leaf count;
    * ``acc`` — the per-round histogram accumulator (re-zeroed by
      ``_hist_tile``'s own ``program_id(1) == 0`` guard each round).

    Every tile RECOMPUTES the round boundary (top-k over the frontier
    gains, slot compaction, routing metadata) from ``ft_scr`` — the
    table is frozen for the whole round (the commit below only runs on
    the last tile, after this recompute in program order), so all tiles
    derive identical values: O(K) math against an O(N/nrt) row sweep,
    and it saves a per-round metadata scratch plus an init-ordering
    hazard.  The boundary math is the staged round's own code objects
    (``_topk_by_rank``, ``route_tile``/``pack_route_meta``,
    ``child_scan_residue``, ``child_leaf_output``, ``_pick_pack``) on
    the same values, so the emitted per-round packed SplitInfo — all
    the host replay consumes — is bit-identical to R staged rounds.

    Staged-bucket mimicry: the staged ``round_pass`` dispatches a
    slot-bucket ladder (``lax.switch``) and decides int8sr per bucket;
    the loop always accumulates at the K-slot shape but computes the
    bucket the staged path WOULD have picked (``S_eff``) to reproduce
    its quant decision per round.  Real slot rows are invariant to the
    bucket width (each accumulator row's one-hot matmul and each
    child's scan are per-row independent), which the planner's uniform
    row-tile gate makes exact — dead-slot rows differ but are never
    read.  An exhausted frontier makes every remaining round a bit-exact
    no-op (all scatters drop, the leaf count stays put)."""
    quant = bool(quant_ladder)
    names = ["iota", "bins", "g3"]
    if quant:
        names.append("zq")
    names += ["oleaf0", "ft0", "nl0"]
    if quant:
        names += ["qkey", "qscale"]
    names += ["nb", "mt", "nanb", "zb", "usbl", "mono"]
    if has_contri:
        names.append("contri")
    names.append("mask")
    if sub:
        names.append("pool0")
    names += ["packed", "nleaf"]
    if sub:
        names.append("pool")
    names += ["acc", "ft_scr", "nl_scr", "leaf_scr"]
    if sub:
        names.append("pool_scr")
    r = dict(zip(names, refs))

    ri = pl.program_id(0)
    rt = pl.program_id(1)
    B = num_bins
    C = 2 * K
    nsl = K if sub else C

    @pl.when((ri == 0) & (rt == 0))
    def _init():
        r["ft_scr"][...] = r["ft0"][...]
        r["nl_scr"][...] = r["nl0"][...]
        if sub:
            r["pool_scr"][...] = r["pool0"][...]

    # ---- round boundary, recomputed per tile from the frozen table ----
    ft = r["ft_scr"][...]                               # (L, 12)
    nl = r["nl_scr"][0, 0]
    vals, leafs = topk_fn(ft[:, 0], K)
    kiota = jnp.arange(K, dtype=jnp.int32)
    budget = L - nl
    valid = (vals > 0) & (kiota < budget)
    n_split = jnp.sum(valid.astype(jnp.int32))
    order = jnp.cumsum(valid.astype(jnp.int32)) - 1
    nls = nl + order
    order_c = jnp.clip(order, 0, K - 1)
    rows = ft[leafs]                                    # (K, 12)
    feats = rows[:, 1].astype(jnp.int32)
    thrs = rows[:, 2].astype(jnp.int32)
    dls = rows[:, 3] != 0
    lsums = rows[:, 4:7]
    rsums = rows[:, 7:10]
    pout = rows[:, 10]
    d = rows[:, 11].astype(jnp.int32) + 1               # child depth
    sm_left = lsums[:, 2] <= rsums[:, 2]
    sidx = jnp.where(valid, order_c, K)

    def to_slot(v, fill):
        base = jnp.full((K,) + v.shape[1:], fill, v.dtype)
        return base.at[sidx].set(v, mode="drop")

    feats_s = to_slot(feats, 0)
    thrs_s = to_slot(thrs, 0)
    dls_s = to_slot(dls, False)
    leafs_s = to_slot(leafs, L)
    nls_s = to_slot(nls, 0)
    sml_s = to_slot(sm_left, False)

    # the slot bucket the STAGED round_pass would dispatch decides the
    # round's quant treatment (the lax.switch index, mirrored)
    s_idx = jnp.zeros((), jnp.int32)
    for S in ladder[:-1]:
        s_idx = s_idx + (n_split > S).astype(jnp.int32)
    # scalar-literal select (a constant ladder array would be a captured
    # const, which pallas_call rejects)
    S_eff = jnp.full((), ladder[0], jnp.int32)
    for i, S in enumerate(ladder[1:], 1):
        S_eff = jnp.where(s_idx >= i, jnp.int32(S), S_eff)
    quant_r = jnp.zeros((), bool)
    for S in quant_ladder:
        quant_r = quant_r | (S_eff == S)

    meta_blk = FeatureMeta(
        num_bins=r["nb"][...][0],
        missing_type=r["mt"][...][0],
        nan_bin=r["nanb"][...][0],
        zero_bin=r["zb"][...][0],
        is_categorical=jnp.zeros(fblk, bool),
        usable=r["usbl"][...][0] != 0,
        monotone_type=r["mono"][...][0],
        contri=(r["contri"][...][0] if has_contri else None),
    )

    # ---- routing: round 0 reads the input leaf ids, later rounds the
    # resident ones; every (round, tile) rewrites its slice + output ----
    oleaf = jnp.where(ri == 0, r["oleaf0"][...],
                      r["leaf_scr"][:, pl.ds(rt * T, T)])
    tab = jnp.zeros(L + 1, jnp.int32) \
        .at[leafs_s].set(feats_s, mode="drop")
    f_of = tab[oleaf[0]]
    bins_t = r["bins"][...].astype(jnp.int32)     # (T, fblk | fblk//2)
    if packed:
        # nibble-decode decision lane (packed_bins_of_rows' layout, in
        # VMEM): gather the packed byte, select by feature parity — the
        # select form avoids a variable-amount vector shift
        byte = jnp.take_along_axis(bins_t, (f_of >> 1)[:, None],
                                   axis=1)[:, 0]
        dbin = (jnp.where((f_of & 1) == 1, byte >> 4, byte)
                & 15)[None, :]
    else:
        dbin = jnp.take_along_axis(bins_t, f_of[:, None],
                                   axis=1)[:, 0][None, :]
    rmeta = pack_route_meta(feats_s, thrs_s, dls_s, leafs_s, nls_s,
                            meta_blk, sml=sml_s)
    new_leaf, label = route_tile(dbin, oleaf, rmeta, nslots=nsl, sub=sub)
    r["leaf_scr"][:, pl.ds(rt * T, T)] = new_leaf
    r["nleaf"][...] = new_leaf

    # ---- histogram accumulate (quant rounds: the staged int8sr stream,
    # drawn here per (iteration, round) key — exact integers through the
    # f32 path, see plan_wave_loop) ----
    g3v = r["g3"][...]                                  # (3, T)
    if quant:
        kdat = r["qkey"][...][0]                        # (2,) uint32
        rkey = jax.random.fold_in(kdat, 8_000_011 + nl)
        u = jax.random.uniform(rkey, (N, 2), dtype=jnp.float32)
        u_pad = jnp.zeros((nrt * T, 2), jnp.float32).at[:N].set(u)
        u_t = lax.dynamic_slice(u_pad, (rt * T, 0), (T, 2))
        zq = r["zq"][...]                               # (3, T)
        q = jnp.clip(jnp.floor(zq[:2] + u_t.T), -qmax, qmax)
        val3 = jnp.where(quant_r,
                         jnp.concatenate([q, zq[2:3]], axis=0), g3v)
    else:
        val3 = g3v
    _hist_tile(r["iota"], r["bins"], _ValRef(val3), _ValRef(label),
               r["acc"], lpad=lpad, num_bins=B, fblk=fblk,
               precision=precision, interpret=interpret, packed=packed)

    @pl.when(rt == nrt - 1)
    def _commit():
        acc = r["acc"][0]
        h = acc.reshape(lpad, 3, B, fblk).transpose(0, 3, 2, 1)
        if packed:
            # [lo nibbles | hi nibbles] -> natural feature order BEFORE
            # the order-sensitive tie-band pick (and the pool commit,
            # which the host replay reads in natural order)
            h = jnp.stack([h[:, :fblk // 2], h[:, fblk // 2:]], axis=2) \
                .reshape(lpad, fblk, B, 3)
        ones3 = jnp.ones((1, 3), jnp.float32)
        scale3 = (jnp.where(quant_r, r["qscale"][...], ones3)
                  if quant else ones3)                  # (1, 3)
        if sub:
            hsm = h[:K]
            # power-of-two scales (ops/quantize.py) make the dequant
            # product exact, so the parent subtraction below rounds
            # identically to the host grower's subtract_child_hists in
            # any fusion context (fma or separate mul+sub)
            hsm_sc = hsm * scale3[:, None, None, :] if scaled else hsm
            pr = jnp.zeros((K,) + h.shape[1:], jnp.float32) \
                .at[sidx].set(r["pool_scr"][...][leafs], mode="drop")
            smlb = sml_s[:, None, None, None]
            h_left = jnp.where(smlb, hsm_sc, pr - hsm_sc)
            h_right = pr - h_left
            ch = jnp.stack([h_left, h_right], axis=1).reshape(
                (C,) + h_left.shape[1:])
        else:
            ch = h[:C]


        csidx = (2 * sidx[:, None]
                 + jnp.arange(2, dtype=jnp.int32)[None, :]).reshape(C)

        def to_cslot(v, fill):
            base = jnp.full((C,) + v.shape[1:], fill, v.dtype)
            return base.at[csidx].set(v, mode="drop")

        cleafs = jnp.stack([leafs, nls], axis=1).reshape(C)
        csums = jnp.stack([lsums, rsums], axis=1).reshape(C, 3)
        def no_con(n):
            # built from scalar literals — a (2,) constant array would be
            # a captured const, which pallas_call rejects
            return jnp.stack(
                [jnp.full((n,), NO_CONSTRAINT[0], jnp.float32),
                 jnp.full((n,), NO_CONSTRAINT[1], jnp.float32)], axis=1)

        pconstr = no_con(K)
        clamp = jax.vmap(lambda s, c, p: child_leaf_output(
            s, c, p, params, use_mc=False))
        out_l = clamp(lsums, pconstr, pout)
        out_r = clamp(rsums, pconstr, pout)
        couts = jnp.stack([out_l, out_r], axis=1).reshape(C)
        dd = jnp.stack([d, d], axis=1).reshape(C)
        depth_ok = (max_depth <= 0) | (dd < max_depth)
        cconstr = no_con(C)
        mask_row = r["mask"][...][0] != 0
        cmask = jnp.broadcast_to(mask_row[None, :], (C, fblk))
        mask_c = to_cslot(cmask, False)
        csums_c = to_cslot(csums, 1.0)
        constr_c = to_cslot(cconstr, 0.0)
        depth_c = to_cslot(dd, 1)
        pout_c = to_cslot(couts, 0.0)

        child_scale = scaled and not sub
        cscale_c = (jnp.broadcast_to(scale3, (C, 3)) if child_scale
                    else jnp.zeros((C, 3), jnp.float32))
        scan_fn = functools.partial(
            child_scan_residue, meta_blk=meta_blk, params=params,
            use_mc=False, monotone_penalty=monotone_penalty,
            child_scale=child_scale, num_bins=B, fblk=fblk)
        residue = jax.vmap(scan_fn)(ch, mask_c, csums_c, constr_c,
                                    depth_c, pout_c, cscale_c)
        shift = jax.vmap(
            lambda ps, po: gain_shift(ps, po, params))(csums_c, pout_c)
        ptab = jax.vmap(
            lambda rc, sh, ps: _pick_pack(rc, sh, ps, meta_blk, B)
        )(residue, shift, csums_c)
        r["packed"][...] = ptab[None]

        # frontier + pool commit — slot->rank gather then scatter-by-
        # child-leaf, the staged store.write's index math
        ch_idx = jnp.stack([2 * order_c, 2 * order_c + 1],
                           axis=1).reshape(C)
        cvalid = jnp.stack([valid, valid], axis=1).reshape(C)
        cidx = jnp.where(cvalid, cleafs, L + 1)
        pk = ptab[ch_idx]
        cgain = jnp.where(depth_ok, pk[:, 0], -jnp.inf)
        crows = jnp.concatenate([
            cgain[:, None], pk[:, 1:4], pk[:, 4:10], couts[:, None],
            dd.astype(jnp.float32)[:, None]], axis=1)
        r["ft_scr"][...] = ft.at[cidx].set(crows, mode="drop")
        r["nl_scr"][0, 0] = nl + n_split
        if sub:
            pool_new = r["pool_scr"][...].at[cidx].set(
                ch[ch_idx], mode="drop")
            r["pool_scr"][...] = pool_new

            @pl.when(ri == R - 1)
            def _flush():
                r["pool"][...] = pool_new


def make_fused_wave_loop(*, meta, params, num_bins, precision,
                         deep_precision, rounds, monotone_penalty=0.0,
                         interpret=False, packed=False):
    """Build the grower-facing persistent wave-loop driver (ROADMAP
    item 1's endpoint: R consecutive wave rounds per launch, frontier
    state resident in VMEM — the R-1 intermediate kernel launches and
    their leaf-id / hist-pool / split-table HBM round-trips disappear).

    ``fused_loop(binned, g3, leaf_id, ft12, num_leaves, key, *, K,
    slot_buckets, quant_buckets, max_depth, base_mask, pool=None)
    -> (packed (R, 2K, PACK_COLS), new_leaf (N,), pool or None)``:

    * ``ft12`` (L, 12) f32 — the frontier table snapshot (store columns
      gain..depth, models/grower_wave assembles it store-agnostically);
    * ``pool`` non-None selects subtraction mode and seeds the resident
      histogram pool; the updated pool comes back as the third output;
    * the per-round packed SplitInfo tables are ALL the host replay
      needs — the grower re-runs the R rounds' bookkeeping (store
      writes, valid-set routing, done flag) from them, bit-identically.

    Eligibility is decided by ``fused_loop.plan`` (``plan_wave_loop``
    with this builder's statics bound); the trainer keys the dispatch
    and the BENCH record off the same plan.  ``rounds == 1`` never
    builds a loop — the trainer dispatches the PR 15 single-round
    kernel, the exact degeneration the tests pin."""
    from ..models.grower_wave import _topk_by_rank
    from .quantize import INT8_QMAX, sr_prequantize_g3

    has_contri = meta.contri is not None
    use_mc = bool(np.asarray(meta.monotone_type).any())
    B = num_bins

    def fused_loop(binned, g3, leaf_id, ft12, num_leaves, key, *, K,
                   slot_buckets, quant_buckets, max_depth, base_mask,
                   pool=None):
        sub = pool is not None
        if packed:
            # binned is the RESIDENT (ceil(F/2), N) packed matrix; the
            # kernel's feature width is the even nibble span — an odd-F
            # tail's phantom hi-nibble feature rides masked-unusable
            # through every round (pads below) and is sliced off the
            # returned pool
            Fb, N = binned.shape            # stored packed byte rows
            F0 = int(meta.num_bins.shape[0])
            F = 2 * Fb                      # kernel feature width
        else:
            F, N = binned.shape
            F0, Fb = F, F
        fpad = F - F0                       # 0 or 1 (phantom feature)
        L = ft12.shape[0]
        C = 2 * K
        nsl = K if sub else C
        lpad = -(-(nsl + 1) // 8) * 8
        m_pad = 3 * lpad
        # row tile from the UNPACKED lane count (plan_wave_loop's rule):
        # same T => same row partition => bit-identical f32 accumulation
        T = _row_tile_for(m_pad, F0 * B, B)
        nrt = -(-N // T)
        n_pad = nrt * T
        R = rounds
        quant = bool(quant_buckets)

        def full_spec(shape):
            nd = len(shape)
            return pl.BlockSpec(shape, lambda ri, rt, _n=nd: (0,) * _n)

        def row(a, dtype=jnp.int32, cv=0):
            a = a.astype(dtype)
            if fpad:
                a = jnp.pad(a, (0, fpad), constant_values=cv)
            return a[None, :]

        binned_rm = jnp.pad(binned, ((0, 0), (0, n_pad - N)),
                            constant_values=0 if packed else 255).T
        # (n_pad, Fb)
        g3t = jnp.pad(g3.astype(jnp.float32),
                      ((0, n_pad - N), (0, 0))).T       # (3, n_pad)
        oleaf_p = jnp.pad(leaf_id.astype(jnp.int32), (0, n_pad - N),
                          constant_values=-1)[None, :]
        iota_bins = (jnp.arange(B * F, dtype=jnp.int32)
                     // F).astype(jnp.float32)[None, :]

        ins = [iota_bins, binned_rm, g3t]
        specs = [
            pl.BlockSpec((1, F * B), lambda ri, rt: (0, 0)),
            pl.BlockSpec((T, Fb), lambda ri, rt: (rt, 0)),
            pl.BlockSpec((3, T), lambda ri, rt: (0, rt)),
        ]
        if quant:
            # key-independent half hoisted (sr_prequantize_g3); the loop
            # draws each round's uniforms in-kernel from the same
            # fold_in(key, 8_000_011 + num_leaves) stream the staged
            # rounds use — int8sr stays bit-reproducible through the loop
            zg, qc, scales = sr_prequantize_g3(g3, nsl)
            zq = jnp.pad(jnp.concatenate([zg, qc[:, None]], axis=1),
                         ((0, n_pad - N), (0, 0))).T    # (3, n_pad)
            ins.append(zq)
            specs.append(pl.BlockSpec((3, T), lambda ri, rt: (0, rt)))
        ins += [oleaf_p, ft12.astype(jnp.float32),
                jnp.asarray(num_leaves, jnp.int32).reshape(1, 1)]
        specs += [pl.BlockSpec((1, T), lambda ri, rt: (0, rt)),
                  full_spec((L, 12)), full_spec((1, 1))]
        if quant:
            kd = key
            if jnp.issubdtype(kd.dtype, jax.dtypes.prng_key):
                kd = jax.random.key_data(kd)
            ins += [kd.reshape(1, 2).astype(jnp.uint32), scales[0:1]]
            specs += [full_spec((1, 2)), full_spec((1, 3))]
        ins += [row(meta.num_bins, cv=1), row(meta.missing_type),
                row(meta.nan_bin, cv=-1), row(meta.zero_bin),
                row(meta.usable), row(meta.monotone_type)]
        specs += [full_spec((1, F))] * 6
        if has_contri:
            ins.append(row(meta.contri, jnp.float32, cv=1.0))
            specs.append(full_spec((1, F)))
        ins.append(row(base_mask, jnp.int8))
        specs.append(full_spec((1, F)))
        if sub:
            pool_in = pool.astype(jnp.float32)
            if fpad:
                pool_in = jnp.pad(pool_in,
                                  ((0, 0), (0, fpad), (0, 0), (0, 0)))
            ins.append(pool_in)
            specs.append(full_spec(pool_in.shape))

        out_shape = [
            jax.ShapeDtypeStruct((R, C, PACK_COLS), jnp.float32),
            jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        ]
        out_specs = [
            pl.BlockSpec((1, C, PACK_COLS), lambda ri, rt: (ri, 0, 0)),
            pl.BlockSpec((1, T), lambda ri, rt: (0, rt)),
        ]
        if sub:
            out_shape.append(
                jax.ShapeDtypeStruct(pool_in.shape, jnp.float32))
            out_specs.append(full_spec(pool_in.shape))

        scratch = [
            pltpu.VMEM((1, m_pad, F * B), jnp.float32),   # acc
            pltpu.VMEM((L, 12), jnp.float32),             # ft_scr
            pltpu.VMEM((1, 1), jnp.int32),                # nl_scr
            pltpu.VMEM((1, n_pad), jnp.int32),            # leaf_scr
        ]
        if sub:
            scratch.append(pltpu.VMEM(pool_in.shape, jnp.float32))

        kern = functools.partial(
            _loop_kernel, R=R, nrt=nrt, T=T, lpad=lpad, num_bins=B,
            fblk=F, N=N, K=K, L=L, precision=precision,
            interpret=interpret, params=params,
            monotone_penalty=monotone_penalty, has_contri=has_contri,
            sub=sub, scaled=quant, ladder=tuple(slot_buckets),
            quant_ladder=tuple(quant_buckets), max_depth=max_depth,
            topk_fn=_topk_by_rank, qmax=INT8_QMAX, packed=packed)
        out = pl.pallas_call(
            kern, grid=(R, nrt), in_specs=specs, out_specs=out_specs,
            out_shape=out_shape, scratch_shapes=scratch,
            interpret=interpret)(*ins)
        pool_out = out[2] if sub else None
        if sub and fpad:
            pool_out = pool_out[:, :F0]     # drop the phantom feature
        return out[0], out[1][0, :N], pool_out

    fused_loop.rounds = rounds
    fused_loop.packed = packed
    fused_loop.plan = functools.partial(
        plan_wave_loop, rounds=rounds, num_bins=num_bins,
        precision=precision, deep_precision=deep_precision,
        use_mc=use_mc, packed=packed)
    return fused_loop


def fused_ineligible_reason(*, meta, params, bin_dtype, num_bins,
                            packed=False, bundled=False) -> str:
    """Static eligibility gate — returns the fallback reason (one line of
    the module-docstring taxonomy) or ``""`` when the fused kernel can
    run.  Learner/grower routing gates live in parallel/trainer.py."""
    if bundled:
        return ("EFB bundle-space histograms expand to original features "
                "before the scan")
    if packed and num_bins > 16:
        return "4-bit packed bins hold num_bins <= 16 only"
    if np.dtype(bin_dtype).itemsize > 1:
        return "int16 bins exceed the uint8 one-hot kernel family"
    if num_bins > 256:
        return "num_bins > 256 exceeds the uint8 kernel family"
    if bool(np.asarray(meta.is_categorical).any()):
        return ("categorical sorted-scan (per-feature argsort) has no "
                "kernel lowering")
    if params.extra_trees:
        return "extra_trees draws per-node randomness inside the scan"
    return ""


_BACKEND_LOWERS: dict = {}


def backend_lowers_fused() -> bool:
    """One cached trial compile of a tiny fused round on the current
    backend — the Mosaic-lowering auto-fallback probe (the
    ``predict_pallas`` precedent: opt-in kernel, warn + staged fallback
    when the local backend cannot lower it).  CPU always passes: the
    kernel runs in interpret mode there (the bit-parity lane)."""
    backend = jax.default_backend()
    if backend in _BACKEND_LOWERS:
        return _BACKEND_LOWERS[backend]
    if backend == "cpu":
        _BACKEND_LOWERS[backend] = True
        return True
    from ..utils.log import log_warning

    try:
        F, B, N, S = 4, 8, 64, 2
        meta = FeatureMeta(
            num_bins=jnp.full(F, B, jnp.int32),
            missing_type=jnp.zeros(F, jnp.int32),
            nan_bin=jnp.full(F, -1, jnp.int32),
            zero_bin=jnp.zeros(F, jnp.int32),
            is_categorical=jnp.zeros(F, bool),
            usable=jnp.ones(F, bool),
            monotone_type=jnp.zeros(F, jnp.int32),
        )
        from .split import SplitParams

        fn = make_fused_round(meta=meta, params=SplitParams(),
                              num_bins=B, precision="bf16x2",
                              deep_precision="bf16")
        rng = np.random.RandomState(0)
        binned_t = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.uint8))
        g3_t = jnp.asarray(rng.randn(N, 3).astype(np.float32))
        lids_t = jnp.asarray(rng.randint(0, 2 * S, N).astype(np.int32))
        kw = dict(mask=jnp.ones((2 * S, F), bool),
                  csums=jnp.abs(jnp.asarray(
                      rng.randn(2 * S, 3).astype(np.float32))),
                  constr=jnp.tile(jnp.asarray([-3e38, 3e38], jnp.float32),
                                  (2 * S, 1)),
                  depth=jnp.ones(2 * S, jnp.int32),
                  pout=jnp.zeros(2 * S, jnp.float32))
        # probe the ROUTED round (ISSUE 15: partition folded in) — the
        # superset the serial trainer dispatches — plus the valid-set
        # router; a backend that lowers histograms but not the routing
        # stage must fall back whole, never half
        rkw = dict(feats=jnp.arange(S, dtype=jnp.int32),
                   thrs=jnp.full(S, B // 2, jnp.int32),
                   dls=jnp.zeros(S, bool),
                   leafs=jnp.arange(S, dtype=jnp.int32),
                   nls=jnp.arange(S, dtype=jnp.int32) + S,
                   num_leaves=2 * S)
        jax.jit(lambda b, g, l: fn(
            b, g, None, S, **kw, route=dict(leaf_id=l, **rkw))
        ).lower(binned_t, g3_t, lids_t).compile()
        jax.jit(lambda b, l: fn.route_rows(b, l, **rkw)) \
            .lower(binned_t, lids_t).compile()
        _BACKEND_LOWERS[backend] = True
    except Exception as e:  # noqa: BLE001 — any lowering failure falls back
        log_warning(
            f"hist_method=fused: Mosaic could not lower the fused "
            f"wave-round kernel on backend {backend!r} "
            f"({type(e).__name__}); falling back to the staged "
            "histogram+split path")
        _BACKEND_LOWERS[backend] = False
    return _BACKEND_LOWERS[backend]


def backend_lowers_fused_loop() -> bool:
    """One cached trial compile of a tiny R=2 persistent wave loop on
    the current backend — the loop's own Mosaic probe.  The loop adds
    kernel constructs the single-round probe never exercises (scatter
    updates on scratch, in-kernel top-k, dynamic leaf-slice writes,
    threefry for the int8sr stream), so a backend that lowers the
    single-round kernel but not the loop must fall back WHOLE to the
    single-round dispatch — never half.  CPU always passes (interpret
    mode, the bit-parity lane)."""
    backend = ("loop", jax.default_backend())
    if backend in _BACKEND_LOWERS:
        return _BACKEND_LOWERS[backend]
    if backend[1] == "cpu":
        _BACKEND_LOWERS[backend] = True
        return True
    from ..utils.log import log_warning

    try:
        F, B, N, K, L = 4, 8, 64, 2, 8
        meta = FeatureMeta(
            num_bins=jnp.full(F, B, jnp.int32),
            missing_type=jnp.zeros(F, jnp.int32),
            nan_bin=jnp.full(F, -1, jnp.int32),
            zero_bin=jnp.zeros(F, jnp.int32),
            is_categorical=jnp.zeros(F, bool),
            usable=jnp.ones(F, bool),
            monotone_type=jnp.zeros(F, jnp.int32),
        )
        from .split import SplitParams

        fn = make_fused_wave_loop(
            meta=meta, params=SplitParams(), num_bins=B, precision="f32",
            deep_precision="f32", rounds=2)
        rng = np.random.RandomState(0)
        binned_t = jnp.asarray(rng.randint(0, B, (F, N)).astype(np.uint8))
        g3_t = jnp.asarray(rng.randn(N, 3).astype(np.float32))
        lids_t = jnp.zeros(N, jnp.int32)
        ft_t = jnp.zeros((L, 12), jnp.float32).at[0, 0].set(1.0)
        pool_t = jnp.zeros((L, F, B, 3), jnp.float32)
        key_t = jnp.zeros(2, jnp.uint32)
        jax.jit(lambda b, g, l, f, p, k: fn(
            b, g, l, f, 1, k, K=K, slot_buckets=(K,), quant_buckets=(),
            max_depth=0, base_mask=jnp.ones(F, bool), pool=p)
        ).lower(binned_t, g3_t, lids_t, ft_t, pool_t, key_t).compile()
        _BACKEND_LOWERS[backend] = True
    except Exception as e:  # noqa: BLE001 — any lowering failure falls back
        log_warning(
            f"wave_loop_rounds: Mosaic could not lower the persistent "
            f"wave-loop kernel on backend {backend[1]!r} "
            f"({type(e).__name__}); falling back to single-round fused "
            "dispatch")
        _BACKEND_LOWERS[backend] = False
    return _BACKEND_LOWERS[backend]
