"""Fused wave-round megakernel: histogram + split scan in ONE Pallas pass.

The staged wave round (the r05 phase table) is a pure-bandwidth
round-trip: ``hist_pallas`` writes the ``(slots, F, B, 3)`` histogram
stack to HBM, ``models/grower_wave.subtract_child_hists`` reads it back
to build the 2K-child stack, and ``ops/split.py``'s scan streams that
stack in again — three traversals of a tensor that is consumed exactly
once.  This kernel keeps the round's histograms in VMEM end to end:

* the row-tile grid REUSES ``hist_pallas._kernel`` verbatim (the one-hot
  MXU formulation with its bf16 / bf16x2 / int8 / int8sr precision
  modes) to accumulate each wave slot's histogram into a VMEM scratch
  accumulator,
* on the LAST row tile the same kernel invocation runs the split scan on
  the VMEM-resident stack: the smaller-child-subtraction path reads the
  parent histograms as a kernel input and subtracts in VMEM before
  scanning (the int8sr dequantize multiply folded in), then the staged
  scan's own stages — ``scan_left_sums`` (stacked two-direction cumsum +
  missing-mass adjust), ``scan_direction_gains`` (gain/penalty chain)
  and ``scan_pick_feature`` (tie-band preference argmax, per-feature
  half) — are composed AS THE SAME CODE OBJECTS on the VMEM values, so
  interpret-mode results are bit-identical to the staged path by
  construction, not by re-derivation,
* only an O(F) per-(child, feature) residue (best gain, in-band pick,
  left sums at the pick — ``RES_COLS`` floats per feature) leaves the
  kernel; the grid iterates feature blocks and the cross-feature half of
  ``scan_pick`` runs on the concatenated residue outside the kernel.
  The tie band needs the GLOBAL best gain, so a running in-VMEM
  reduction across feature blocks could mis-pick inside overlapping
  near-tie bands; reducing to the O(F) residue in VMEM and finishing the
  O(F) argmax outside keeps bit-exactness while still shrinking the
  kernel's HBM output from O(F·B) histograms to O(F) floats,
* the packed per-slot SplitInfo (``PACK_COLS`` floats per child) is all
  the round emits in pool-free mode; the subtraction-composed mode also
  emits the K smaller-child histograms (the per-leaf state the NEXT
  round's subtraction needs) — the ``(2K, F, B, 3)`` scan stack itself
  never materializes off-chip in either mode.

Fallback taxonomy (every gate logs once at build time,
parallel/trainer.py):

* categorical features — the sorted two-direction categorical scan
  (``_best_categorical``) argsorts per feature, which has no Mosaic
  lowering; such datasets run the staged path,
* ``extra_trees`` — per-node threshold sampling draws ``jax.random``
  inside the scan,
* EFB bundles / 4-bit packed bins / int16 bins — the scan runs in
  original-feature uint8 bin space only,
* row-sharded learners (``tree_learner=data``/``voting``) — the
  cross-shard histogram reduce needs the explicit histogram on the wire;
  the feature-parallel learner DOES run the kernel per feature slice and
  elects through the existing ``_sync_best_split``,
* Mosaic lowering failure on a device backend — auto-fallback with a
  warning, the ``predict_pallas`` precedent; the CPU backend always runs
  the kernel in interpret mode (the bit-parity lane the tests pin).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..io.binning import MISSING_NAN, MISSING_ZERO
from .hist_pallas import MAX_LANES, _kernel as _hist_tile, _row_tile_for
from .split import (
    NEG_INF,
    FeatureMeta,
    SplitResult,
    gain_shift,
    scan_direction_gains,
    scan_left_sums,
    scan_pick_feature,
    tie_tol,
)

RES_COLS = 6    # fbest, gain_at_sel, sel (direction*B+thr), left g/h/c
PACK_COLS = 10  # gain, feature, threshold, default_left, left(3), right(3)


def _fused_kernel(*refs, nrt, lpad, num_bins, fblk, precision, interpret,
                  params, use_mc, monotone_penalty, has_contri, sub,
                  apply_scale, child_scale, nslots, nchildren):
    """Grid ``(1, row_tiles)``: every tile accumulates its rows via the
    REUSED ``hist_pallas._kernel``; the last tile runs the split scan on
    the VMEM accumulator and writes the per-feature residue (plus, in
    subtraction mode, the raw smaller-child histograms)."""
    names = ["iota", "bins", "g3", "leaf",
             "nb", "mt", "nanb", "zb", "usbl", "mono"]
    if has_contri:
        names.append("contri")
    names += ["mask", "csums", "constr", "depth", "pout"]
    if child_scale:
        names.append("cscale")
    if sub and apply_scale:
        names.append("sscale")
    if sub:
        names += ["sml", "parent"]
    names.append("res")
    if sub:
        names.append("hsmall")
    names.append("acc")
    r = dict(zip(names, refs))

    _hist_tile(r["iota"], r["bins"], r["g3"], r["leaf"], r["acc"],
               lpad=lpad, num_bins=num_bins, fblk=fblk,
               precision=precision, interpret=interpret)

    rt = pl.program_id(1)
    B = num_bins

    @pl.when(rt == nrt - 1)
    def _scan():
        # accumulator rows are (slot-major, channel-minor), lanes are
        # (bin-major, feature-minor) — the same unscramble
        # hist_leaves_pallas applies outside, here on VMEM values
        acc = r["acc"][0]                               # (3*lpad, B*fblk)
        h = acc.reshape(lpad, 3, B, fblk).transpose(0, 3, 2, 1)
        meta_blk = FeatureMeta(
            num_bins=r["nb"][...][0],
            missing_type=r["mt"][...][0],
            nan_bin=r["nanb"][...][0],
            zero_bin=r["zb"][...][0],
            is_categorical=jnp.zeros(fblk, bool),
            usable=r["usbl"][...][0] != 0,
            monotone_type=r["mono"][...][0],
            contri=(r["contri"][...][0] if has_contri else None),
        )
        if sub:
            # smaller-child + parent subtraction IN VMEM — the exact op
            # order of subtract_child_hists (dequant multiply first, then
            # the smaller/larger select), so values are bit-identical
            hsm = h[:nslots]                            # (S, fblk, B, 3)
            r["hsmall"][...] = hsm                      # raw (int on quant)
            if apply_scale:
                hsm = hsm * r["sscale"][...][:, None, None, :]
            sml = (r["sml"][...][:, 0] != 0)[:, None, None, None]
            parent = r["parent"][...]
            h_left = jnp.where(sml, hsm, parent - hsm)
            h_right = parent - h_left
            ch = jnp.stack([h_left, h_right], axis=1).reshape(
                (2 * nslots,) + h_left.shape[1:])       # (2S, fblk, B, 3)
        else:
            ch = h[:nchildren]

        mask = r["mask"][...] != 0                      # (C, fblk)
        csums = r["csums"][...]
        constr = r["constr"][...]
        depth = r["depth"][...][:, 0]
        pout = r["pout"][...][:, 0]
        cscale = (r["cscale"][...] if child_scale
                  else jnp.zeros((nchildren, 3), jnp.float32))

        def child_scan(hc, mask_c, csum_c, constr_c, depth_c, pout_c,
                       hsc_c):
            # the staged scan's OWN stages on the VMEM stack
            left2, _ = scan_left_sums(
                hc, meta_blk, hsc_c if child_scale else None)
            gains, shift = scan_direction_gains(
                left2, csum_c, meta_blk, mask_c, params, constr_c,
                depth_c, monotone_penalty, pout_c, None, None,
                use_mc=use_mc)
            fbest, sel = scan_pick_feature(gains, shift, meta_blk)
            gains_f = jnp.concatenate([gains[0], gains[1]], axis=1)
            gsel = jnp.take_along_axis(gains_f, sel[:, None],
                                       axis=1)[:, 0]
            lsel = left2[sel // B, jnp.arange(fblk), sel % B]  # (fblk, 3)
            return jnp.concatenate(
                [fbest[:, None], gsel[:, None],
                 sel.astype(jnp.float32)[:, None], lsel], axis=1)

        r["res"][...] = jax.vmap(child_scan)(
            ch, mask, csums, constr, depth, pout, cscale)


def fused_wave_scan(binned, g3, label, *, nslots, nchildren, num_bins,
                    precision, interpret, meta, params, use_mc,
                    monotone_penalty, mask, csums, constr, depth, pout,
                    cscale=None, sscale=None, sml=None, parent=None,
                    apply_scale=False, row_tile=0):
    """One fused wave round over all feature blocks.

    ``nslots`` counts the ACCUMULATED slots (smaller children in
    subtraction mode, all 2S children pool-free); slot ``nslots`` is the
    sacrificial dead-row slot, as in ``hist_wave``.  ``parent`` non-None
    selects the subtraction-composed mode.  Returns ``(residue
    (C, F, RES_COLS), hsmall (nslots, F, B, 3) or None)``.
    """
    sub = parent is not None
    C = nchildren
    F = mask.shape[1]
    B = num_bins
    N = binned.shape[1]
    fblk = max(1, min(F, MAX_LANES // B))
    nfb = -(-F // fblk)
    f_pad = nfb * fblk
    L = nslots + 1
    lpad = -(-L // 8) * 8
    m_pad = 3 * lpad
    T = row_tile if row_tile > 0 else _row_tile_for(m_pad, fblk * B, B)
    nrt = -(-N // T)
    n_pad = nrt * T

    # padding identical to hist_leaves_pallas: padded features collect
    # bin 255 (no bin when B < 256; masked unusable below when B == 256),
    # padded rows carry zero g3 and an out-of-range slot id
    binned_rm = jnp.pad(binned, ((0, f_pad - F), (0, n_pad - N)),
                        constant_values=255).T          # (n_pad, f_pad)
    g3t = jnp.pad(g3.astype(jnp.float32), ((0, n_pad - N), (0, 0))).T
    leaf_p = jnp.pad(label.astype(jnp.int32), (0, n_pad - N),
                     constant_values=lpad)[None, :]
    iota_bins = (jnp.arange(B * fblk, dtype=jnp.int32)
                 // fblk).astype(jnp.float32)[None, :]

    def padf(a, cv, dtype=jnp.int32):
        return jnp.pad(a.astype(dtype), (0, f_pad - F),
                       constant_values=cv)[None, :]

    nb_p = padf(meta.num_bins, 1)
    mt_p = padf(meta.missing_type, 0)
    nanb_p = padf(meta.nan_bin, -1)
    zb_p = padf(meta.zero_bin, 0)
    us_p = padf(meta.usable, 0)
    mono_p = padf(meta.monotone_type, 0)
    has_contri = meta.contri is not None
    contri_p = padf(meta.contri, 1.0, jnp.float32) if has_contri else None
    mask_p = jnp.pad(mask.astype(jnp.int8), ((0, 0), (0, f_pad - F)))
    parent_p = (jnp.pad(parent.astype(jnp.float32),
                        ((0, 0), (0, f_pad - F), (0, 0), (0, 0)))
                if sub else None)
    csums2 = csums.astype(jnp.float32)
    constr2 = constr.astype(jnp.float32)
    depth2 = depth.astype(jnp.int32)[:, None]
    pout2 = pout.astype(jnp.float32)[:, None]
    sml2 = sml.astype(jnp.int32)[:, None] if sub else None
    child_scale = cscale is not None

    kern = functools.partial(
        _fused_kernel, nrt=nrt, lpad=lpad, num_bins=B, fblk=fblk,
        precision=precision, interpret=interpret, params=params,
        use_mc=use_mc, monotone_penalty=monotone_penalty,
        has_contri=has_contri, sub=sub, apply_scale=apply_scale,
        child_scale=child_scale, nslots=nslots, nchildren=C)

    def full_spec(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, lambda fb, rt, _n=nd: (0,) * _n)

    res_blocks, hs_blocks = [], []
    for fb in range(nfb):
        sl = slice(fb * fblk, (fb + 1) * fblk)
        ins = [iota_bins, binned_rm[:, sl], g3t, leaf_p,
               nb_p[:, sl], mt_p[:, sl], nanb_p[:, sl], zb_p[:, sl],
               us_p[:, sl], mono_p[:, sl]]
        specs = [
            pl.BlockSpec((1, fblk * B), lambda fb_, rt: (0, 0)),
            pl.BlockSpec((T, fblk), lambda fb_, rt: (rt, 0)),
            pl.BlockSpec((3, T), lambda fb_, rt: (0, rt)),
            pl.BlockSpec((1, T), lambda fb_, rt: (0, rt)),
        ] + [full_spec((1, fblk))] * 6
        if has_contri:
            ins.append(contri_p[:, sl])
            specs.append(full_spec((1, fblk)))
        ins.append(mask_p[:, sl])
        specs.append(full_spec((C, fblk)))
        for a in (csums2, constr2, depth2, pout2):
            ins.append(a)
            specs.append(full_spec(a.shape))
        if child_scale:
            ins.append(cscale.astype(jnp.float32))
            specs.append(full_spec((C, 3)))
        if sub and apply_scale:
            ins.append(sscale.astype(jnp.float32))
            specs.append(full_spec((nslots, 3)))
        if sub:
            ins += [sml2, parent_p[:, sl]]
            specs += [full_spec((nslots, 1)),
                      full_spec((nslots, fblk, B, 3))]
        out_shape = [jax.ShapeDtypeStruct((C, fblk, RES_COLS),
                                          jnp.float32)]
        out_specs = [full_spec((C, fblk, RES_COLS))]
        if sub:
            out_shape.append(
                jax.ShapeDtypeStruct((nslots, fblk, B, 3), jnp.float32))
            out_specs.append(full_spec((nslots, fblk, B, 3)))
        out = pl.pallas_call(
            kern,
            grid=(1, nrt),
            in_specs=specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((1, m_pad, fblk * B), jnp.float32)],
            interpret=interpret,
        )(*ins)
        res_blocks.append(out[0])
        if sub:
            hs_blocks.append(out[1])
    residue = (jnp.concatenate(res_blocks, axis=1)
               if nfb > 1 else res_blocks[0])[:, :F]
    hsmall = None
    if sub:
        hsmall = (jnp.concatenate(hs_blocks, axis=1)
                  if nfb > 1 else hs_blocks[0])[:, :F]
    return residue, hsmall


def _pick_pack(residue_c, shift_c, parent_sum_c, meta, num_bins):
    """Cross-feature half of ``scan_pick`` on one child's O(F) residue,
    plus the non-categorical tail of ``_find_best_split`` (right sums,
    missing default direction) — the packed per-slot SplitInfo the round
    emits.  Formula-for-formula the staged code, evaluated on identical
    inputs, so the pick is bit-identical."""
    fbest = residue_c[:, 0]
    gsel = residue_c[:, 1]
    sel = residue_c[:, 2].astype(jnp.int32)
    gbest = jnp.max(fbest)
    feature = jnp.argmax(fbest >= gbest - tie_tol(gbest, shift_c)) \
        .astype(jnp.int32)                   # first in band = min feature
    best_gain = gsel[feature]
    sc = sel[feature]
    direction = (sc // num_bins).astype(jnp.int32)
    threshold = (sc % num_bins).astype(jnp.int32)
    left = residue_c[feature, 3:6]
    right = parent_sum_c - left
    mtype = meta.missing_type[feature]
    default_left = jnp.where(
        (mtype == MISSING_NAN) | (mtype == MISSING_ZERO),
        direction == 1, False)
    rel_gain = jnp.where(jnp.isfinite(best_gain), best_gain, NEG_INF)
    return jnp.concatenate([
        jnp.stack([rel_gain.astype(jnp.float32),
                   feature.astype(jnp.float32),
                   threshold.astype(jnp.float32),
                   default_left.astype(jnp.float32)]),
        left.astype(jnp.float32), right.astype(jnp.float32)])


def pack_children(res: SplitResult) -> jnp.ndarray:
    """Batched SplitResult -> the (C, PACK_COLS) wire rows (no bitset —
    the fused path never produces categorical splits)."""
    return jnp.concatenate([
        res.gain[:, None],
        res.feature.astype(jnp.float32)[:, None],
        res.threshold_bin.astype(jnp.float32)[:, None],
        res.default_left.astype(jnp.float32)[:, None],
        res.left_sum, res.right_sum], axis=1)


def unpack_children(packed: jnp.ndarray, num_bins: int) -> SplitResult:
    """(C, PACK_COLS) rows -> batched SplitResult (is_cat False, zero
    bitset — the fused gate excludes categorical datasets)."""
    W = -(-num_bins // 32)
    C = packed.shape[0]
    return SplitResult(
        gain=packed[:, 0],
        feature=packed[:, 1].astype(jnp.int32),
        threshold_bin=packed[:, 2].astype(jnp.int32),
        default_left=packed[:, 3] != 0,
        left_sum=packed[:, 4:7],
        right_sum=packed[:, 7:10],
        is_cat=jnp.zeros(C, bool),
        cat_bitset=jnp.zeros((C, W), jnp.uint32),
    )


def make_fused_round(*, meta, params, num_bins, precision, deep_precision,
                     monotone_penalty=0.0, interpret=False,
                     axis_name=None):
    """Build the grower-facing ``fused_round_fn``.

    ``fused_round(binned, g3, label, S, *, deep, quant_key, scaled,
    mask, csums, constr, depth, pout, sml, parent, meta_override,
    feature_rebase) -> (packed (2S, PACK_COLS), hsmall or None,
    slot_scales (nslots, 3))``

    * ``deep`` — sustained-bucket round: the kernel accumulates at
      ``deep_precision`` (the staged deep-dtype policy, so precision per
      bucket cannot drift between the paths).
    * ``quant_key`` non-None — an int8sr-eligible bucket
      (models/grower_wave.py quant gate: the sustained bucket and the
      16-slot ramp of a K>16 wave; root and <=4-slot ramps never reach
      here): the gradients are stochastic-round quantized with the SAME
      ``sr_quantize_g3`` call the staged pass makes, and the dequantize
      multiply folds into the in-VMEM subtraction (or the scan's integer
      cumsum pool-free) exactly where the staged path folds it.
    * ``scaled`` — quant buckets exist this grow (the staged path then
      applies identity scales on non-quant rounds too; mirrored for bit
      parity).
    * ``meta_override``/``feature_rebase`` — the feature-parallel
      learner passes its (traced) per-shard meta slice and block offset;
      packed feature ids come back shard-local and are rebased by the
      caller after the SplitInfo election.
    """
    from .quantize import sr_quantize_g3

    use_mc = bool(np.asarray(meta.monotone_type).any())

    def fused_round(binned, g3, label, S, *, deep=False, quant_key=None,
                    scaled=False, mask=None, csums=None, constr=None,
                    depth=None, pout=None, sml=None, parent=None,
                    meta_override=None):
        sub = parent is not None
        C = 2 * S
        nslots = S if sub else C
        m = meta_override if meta_override is not None else meta
        if quant_key is not None:
            q3, scales = sr_quantize_g3(g3, label, nslots, quant_key,
                                        axis_name=axis_name)
            g3u, prec = q3, "int8sr"
        else:
            scales = jnp.ones((nslots, 3), jnp.float32)
            g3u = g3
            prec = deep_precision if deep else precision
        with jax.named_scope("lgbm.fused_round"):
            residue, hsmall = fused_wave_scan(
                binned, g3u, label, nslots=nslots, nchildren=C,
                num_bins=num_bins, precision=prec, interpret=interpret,
                meta=m, params=params, use_mc=use_mc,
                monotone_penalty=monotone_penalty, mask=mask,
                csums=csums, constr=constr, depth=depth, pout=pout,
                cscale=(scales if (scaled and not sub) else None),
                sscale=(scales if (scaled and sub) else None),
                sml=sml, parent=parent, apply_scale=(scaled and sub))
            shift = jax.vmap(
                lambda ps, po: gain_shift(ps, po, params))(csums, pout)
            packed = jax.vmap(
                lambda rc, sh, ps: _pick_pack(rc, sh, ps, m, num_bins)
            )(residue, shift, csums)
        return packed, hsmall, scales

    return fused_round


def fused_ineligible_reason(*, meta, params, bin_dtype, num_bins,
                            packed=False, bundled=False) -> str:
    """Static eligibility gate — returns the fallback reason (one line of
    the module-docstring taxonomy) or ``""`` when the fused kernel can
    run.  Learner/grower routing gates live in parallel/trainer.py."""
    if bundled:
        return ("EFB bundle-space histograms expand to original features "
                "before the scan")
    if packed:
        return "4-bit packed bins decode outside the fused kernel"
    if np.dtype(bin_dtype).itemsize > 1:
        return "int16 bins exceed the uint8 one-hot kernel family"
    if num_bins > 256:
        return "num_bins > 256 exceeds the uint8 kernel family"
    if bool(np.asarray(meta.is_categorical).any()):
        return ("categorical sorted-scan (per-feature argsort) has no "
                "kernel lowering")
    if params.extra_trees:
        return "extra_trees draws per-node randomness inside the scan"
    return ""


_BACKEND_LOWERS: dict = {}


def backend_lowers_fused() -> bool:
    """One cached trial compile of a tiny fused round on the current
    backend — the Mosaic-lowering auto-fallback probe (the
    ``predict_pallas`` precedent: opt-in kernel, warn + staged fallback
    when the local backend cannot lower it).  CPU always passes: the
    kernel runs in interpret mode there (the bit-parity lane)."""
    backend = jax.default_backend()
    if backend in _BACKEND_LOWERS:
        return _BACKEND_LOWERS[backend]
    if backend == "cpu":
        _BACKEND_LOWERS[backend] = True
        return True
    from ..utils.log import log_warning

    try:
        F, B, N, S = 4, 8, 64, 2
        meta = FeatureMeta(
            num_bins=jnp.full(F, B, jnp.int32),
            missing_type=jnp.zeros(F, jnp.int32),
            nan_bin=jnp.full(F, -1, jnp.int32),
            zero_bin=jnp.zeros(F, jnp.int32),
            is_categorical=jnp.zeros(F, bool),
            usable=jnp.ones(F, bool),
            monotone_type=jnp.zeros(F, jnp.int32),
        )
        from .split import SplitParams

        fn = make_fused_round(meta=meta, params=SplitParams(),
                              num_bins=B, precision="bf16x2",
                              deep_precision="bf16")
        rng = np.random.RandomState(0)
        args = (jnp.asarray(rng.randint(0, B, (F, N)).astype(np.uint8)),
                jnp.asarray(rng.randn(N, 3).astype(np.float32)),
                jnp.asarray(rng.randint(0, 2 * S + 1, N).astype(np.int32)))
        kw = dict(mask=jnp.ones((2 * S, F), bool),
                  csums=jnp.abs(jnp.asarray(
                      rng.randn(2 * S, 3).astype(np.float32))),
                  constr=jnp.tile(jnp.asarray([-3e38, 3e38], jnp.float32),
                                  (2 * S, 1)),
                  depth=jnp.ones(2 * S, jnp.int32),
                  pout=jnp.zeros(2 * S, jnp.float32))
        jax.jit(lambda *a: fn(*a, S, **kw)).lower(*args).compile()
        _BACKEND_LOWERS[backend] = True
    except Exception as e:  # noqa: BLE001 — any lowering failure falls back
        log_warning(
            f"hist_method=fused: Mosaic could not lower the fused "
            f"wave-round kernel on backend {backend!r} "
            f"({type(e).__name__}); falling back to the staged "
            "histogram+split path")
        _BACKEND_LOWERS[backend] = False
    return _BACKEND_LOWERS[backend]
