"""Dataset and Booster — the lightgbm-compatible Python API.

TPU-native re-design of the reference python-package core
(reference: ``python-package/lightgbm/basic.py`` — class Dataset :909 with
lazy construction and reference-alignment, class Booster :1930 with
``update`` :2315, custom-objective ``__boost`` :2381, ``predict`` :2816).

Where the reference marshals numpy through ctypes into C++, this package
keeps data in numpy/JAX arrays end to end; the Booster wraps the device
GBDT driver (models/gbdt.py) directly.
"""

from __future__ import annotations

import json
import os
from copy import deepcopy
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config
from .io.dataset import BinnedDataset
from .io.model_text import LoadedModel, dump_model_dict, model_from_string, model_to_string
from .io.parser import load_data_file
from .metrics import create_metrics
from .models.gbdt import GBDT, create_boosting
from .models.tree import HostTree
from .utils import fileio
from .utils.log import LightGBMError, log_fatal, log_info, log_warning


# rows * trees above which bulk prediction routes to the native C++
# predictor (below it the per-call pack/launch overhead beats the win)
_NATIVE_PREDICT_MIN_WORK = 500_000


class _IterObs:
    """Lazily bound per-iteration training telemetry (obs registry)."""

    __slots__ = ("hist", "count")

    def __init__(self):
        from .obs.metrics import default_registry

        reg = default_registry()
        self.hist = reg.histogram(
            "train_iteration_ms", "Wall time of one boosting iteration",
            buckets=(1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000,
                     5000, 10000, 60000))
        self.count = reg.counter(
            "train_iterations_total", "Boosting iterations completed")

    def observe(self, ms: float) -> None:
        self.hist.observe(ms)
        self.count.inc()


_obs_iter: Optional[_IterObs] = None


def _obs_iteration_metrics() -> _IterObs:
    global _obs_iter
    if _obs_iter is None:
        _obs_iter = _IterObs()
    return _obs_iter


def _is_scipy_sparse(data) -> bool:
    return type(data).__module__.split(".")[0] == "scipy" and hasattr(
        data, "tocsr")


def _to_2d_numpy(data) -> np.ndarray:
    if hasattr(data, "values") and not isinstance(data, np.ndarray):  # pandas
        data = data.values
    if hasattr(data, "toarray"):  # scipy sparse
        data = data.toarray()
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(1, -1)
    return arr


def _objective_string(config: Config) -> str:
    """Objective line for the model file (reference gbdt.cpp ObjectiveName
    + per-objective ToString, e.g. 'binary sigmoid:1')."""
    obj = config.objective
    if obj == "binary":
        return f"binary sigmoid:{config.sigmoid:g}"
    if obj in ("multiclass", "multiclassova"):
        extra = f" sigmoid:{config.sigmoid:g}" if obj == "multiclassova" else ""
        return f"{obj} num_class:{config.num_class}{extra}"
    if obj == "lambdarank":
        return "lambdarank"
    if obj == "quantile":
        return f"quantile alpha:{config.alpha:g}"
    if obj == "huber":
        return f"huber alpha:{config.alpha:g}"
    if obj == "fair":
        return f"fair c:{config.fair_c:g}"
    if obj == "tweedie":
        return f"tweedie tweedie_variance_power:{config.tweedie_variance_power:g}"
    return obj


class Dataset:
    """Training data wrapper with lazy binning (reference basic.py:909)."""

    def __init__(
        self,
        data,
        label=None,
        reference: Optional["Dataset"] = None,
        weight=None,
        group=None,
        init_score=None,
        feature_name="auto",
        categorical_feature="auto",
        params: Optional[Dict[str, Any]] = None,
        free_raw_data: bool = False,
    ):
        self.params = dict(params or {})
        self.reference = reference
        self.free_raw_data = free_raw_data
        self._binned: Optional[BinnedDataset] = None
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature

        from .data.block_cache import is_block_cache

        if isinstance(data, (str, os.PathLike)) and is_block_cache(data):
            # sharded block cache (data/ subsystem): metadata + mappers
            # load resident, the binned row bulk streams per block during
            # training (models/gbdt_stream.py) — the out-of-core path
            from .data.streaming import StreamingDataset

            self._binned = StreamingDataset(str(data))
            self.data = None
            meta = self._binned.metadata
            label = meta.label if label is None else label
            weight = meta.weight if weight is None else weight
            group = meta.group if group is None else group
            init_score = meta.init_score if init_score is None else init_score
            self.feature_name = list(self._binned.feature_names)
        elif isinstance(data, (str, os.PathLike)) and \
                BinnedDataset.is_binary_file(str(data)):
            # binary dataset cache (reference LoadFromBinFile,
            # dataset_loader.cpp:273): skips parsing and binning entirely
            self._binned = BinnedDataset.load_binary(str(data))
            self.data = None
            meta = self._binned.metadata
            label = meta.label if label is None else label
            weight = meta.weight if weight is None else weight
            group = meta.group if group is None else group
            init_score = meta.init_score if init_score is None else init_score
            self.feature_name = list(self._binned.feature_names)
        elif isinstance(data, (str, os.PathLike)):
            cfg = Config.from_dict(self.params)
            if cfg.two_round and reference is None:
                # streaming two-pass load straight into bins (reference:
                # two_round=true, dataset_loader.cpp:208-235); valid sets
                # with a reference still use the in-memory path since they
                # must reuse the training bin mappers
                from .io.parser import load_two_round

                cat2 = []
                cat_named = []
                if categorical_feature not in ("auto", None):
                    cat_named = [c for c in categorical_feature
                                 if isinstance(c, str)]
                    cat2 = [int(c) for c in categorical_feature
                            if not isinstance(c, str)]
                if cat_named:
                    # name resolution needs the constructed header map; the
                    # in-memory path below handles it
                    log_warning(
                        "two_round with named categorical_feature columns "
                        "falls back to the in-memory loader")
                    binned = None
                else:
                    binned = load_two_round(str(data), cfg, cat2)
                if binned is not None:
                    self._binned = binned
                    self.data = None
                    meta = binned.metadata
                    label = meta.label if label is None else label
                    weight = meta.weight if weight is None else weight
                    group = meta.group if group is None else group
                    init_score = (meta.init_score if init_score is None
                                  else init_score)
                    self.feature_name = list(binned.feature_names)
            if self._binned is None:
                df = load_data_file(
                    str(data),
                    has_header=cfg.header,
                    label_column=cfg.label_column,
                    weight_column=cfg.weight_column,
                    group_column=cfg.group_column,
                    ignore_column=cfg.ignore_column,
                    num_threads=cfg.num_threads,
                    # initscore_filename describes the TRAINING data only;
                    # valid sets use valid_data_initscores (reference:
                    # config.h initscore_filename doc, application.cpp:90)
                    init_score_file=(cfg.initscore_filename
                                     if reference is None else ""),
                )
                self.data = df.X
                label = df.label if label is None else label
                weight = df.weight if weight is None else weight
                group = df.group if group is None else group
                init_score = getattr(df, "init_score", None) \
                    if init_score is None else init_score
                if df.feature_names and feature_name == "auto":
                    self.feature_name = df.feature_names
        elif _is_scipy_sparse(data):
            # kept sparse: construct() feeds the CSR triplets straight into
            # the EFB bundling path (reference: LGBM_DatasetCreateFromCSR)
            self.data = data.tocsr()
        else:
            self.data = _to_2d_numpy(data) if data is not None else None

        self.label = None if label is None else np.asarray(label, dtype=np.float64).ravel()
        self.weight = None if weight is None else np.asarray(weight, dtype=np.float64).ravel()
        self.group = None if group is None else np.asarray(group, dtype=np.int64).ravel()
        self.init_score = None if init_score is None else np.asarray(init_score, dtype=np.float64)
        if self._binned is not None:
            # binary-cache path: explicit fields override the cached metadata
            if label is not None:
                self.set_label(self.label)
            if weight is not None:
                self.set_weight(self.weight)
            if group is not None:
                self.set_group(self.group)
            if init_score is not None:
                self.set_init_score(self.init_score)

    # ------------------------------------------------------------------
    @classmethod
    def from_binned(cls, binned: "BinnedDataset",
                    params: Optional[Dict[str, Any]] = None) -> "Dataset":
        """Wrap an ALREADY-binned :class:`BinnedDataset` (e.g. the
        distributed loader's process shard, ``parallel/dist_data.py``)
        in the Dataset surface the Booster consumes — no re-parse, no
        re-bin; ``construct()`` is a no-op."""
        ds = cls(None, params=params)
        ds._binned = binned
        return ds

    def construct(self) -> "Dataset":
        if self._binned is not None:
            return self
        if self.data is None:
            log_fatal("Cannot construct Dataset: raw data was freed")
        cfg = Config.from_dict(self.params)
        cat = []
        if self.categorical_feature not in ("auto", None):
            names = self._feature_names_list()
            for c in self.categorical_feature:
                if isinstance(c, str):
                    cat.append(names.index(c))
                else:
                    cat.append(int(c))
        ref_binned = self.reference.construct()._binned if self.reference is not None else None
        if _is_scipy_sparse(self.data):
            csr = self.data
            self._binned = BinnedDataset.from_csr(
                csr.indptr, csr.indices, csr.data,
                num_data=csr.shape[0], num_features=csr.shape[1],
                label=self.label,
                weight=self.weight,
                group=self.group,
                init_score=self.init_score,
                config=cfg,
                categorical_features=cat,
                feature_names=self._feature_names_list(),
                reference=ref_binned,
            )
        else:
            self._binned = BinnedDataset.from_numpy(
                self.data,
                label=self.label,
                weight=self.weight,
                group=self.group,
                init_score=self.init_score,
                config=cfg,
                categorical_features=cat,
                feature_names=self._feature_names_list(),
                reference=ref_binned,
            )
        if self.free_raw_data:
            self.data = None
        return self

    def _feature_names_list(self) -> Optional[List[str]]:
        if isinstance(self.feature_name, (list, tuple)):
            return list(self.feature_name)
        if self.data is not None:
            return [f"Column_{i}" for i in range(self.data.shape[1])]
        return None

    # ------------------------------------------------------------------
    def save_binary(self, filename: str) -> "Dataset":
        """Save the binned dataset cache (reference basic.py save_binary →
        Dataset::SaveBinaryFile)."""
        self.construct()
        self._binned.save_binary(str(filename))
        return self

    def save_block_cache(self, path: str,
                         block_rows: Optional[int] = None) -> "Dataset":
        """Write the sharded binary block cache (data/block_cache.py):
        parse-once, then train out-of-core from ``path`` with the
        row-block streaming trainer (``Dataset(path)`` streams it)."""
        from .data.block_cache import write_block_cache

        self.construct()
        cfg = Config.from_dict(self.params)
        if block_rows is None:
            block_rows = cfg.stream_block_rows
        write_block_cache(self._binned, str(path), block_rows=block_rows,
                          bin_layout=cfg.bin_layout)
        return self

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(
            data, label=label, reference=self, weight=weight, group=group,
            init_score=init_score, params=params or self.params,
        )

    def set_label(self, label) -> "Dataset":
        self.label = np.asarray(label, dtype=np.float64).ravel()
        if self._binned is not None:
            self._binned.metadata.label = self.label.astype(np.float32)
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = None if weight is None else np.asarray(weight, dtype=np.float64).ravel()
        if self._binned is not None:
            self._binned.metadata.weight = (
                None if weight is None else self.weight.astype(np.float32))
        return self

    def set_group(self, group) -> "Dataset":
        self.group = None if group is None else np.asarray(group, dtype=np.int64).ravel()
        if self._binned is not None:
            self._binned.metadata.set_group(self.group)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = None if init_score is None else np.asarray(init_score, np.float64)
        if self._binned is not None:
            self._binned.metadata.init_score = self.init_score
        return self

    def set_field(self, field_name: str, data) -> "Dataset":
        return {
            "label": self.set_label,
            "weight": self.set_weight,
            "group": self.set_group,
            "init_score": self.set_init_score,
        }[field_name](data)

    def get_field(self, field_name: str):
        return {
            "label": self.label,
            "weight": self.weight,
            "group": self.group,
            "init_score": self.init_score,
        }[field_name]

    def get_label(self):
        return self.label

    def get_weight(self):
        return self.weight

    def get_group(self):
        return self.group

    def get_init_score(self):
        return self.init_score

    def num_data(self) -> int:
        if self._binned is not None:
            return self._binned.num_data
        return 0 if self.data is None else self.data.shape[0]

    def num_feature(self) -> int:
        if self._binned is not None:
            return self._binned.num_features
        return 0 if self.data is None else self.data.shape[1]

    def subset(self, used_indices, params=None) -> "Dataset":
        if self.data is None:
            log_fatal("Cannot subset: raw data was freed")
        idx = np.asarray(used_indices)
        sub = Dataset(
            self.data[idx],
            label=None if self.label is None else self.label[idx],
            weight=None if self.weight is None else self.weight[idx],
            init_score=None if self.init_score is None else self.init_score[idx],
            params=params or self.params,
            reference=self,
            feature_name=self.feature_name,
            categorical_feature=self.categorical_feature,
        )
        return sub


def _reference_capture_supported() -> bool:
    """Model-reference capture (obs/model.py) reads the raw score cache
    host-side; under multi-process training that array spans
    non-addressable devices and a single-rank read ABORTS inside the
    runtime rather than raising — so capture is a single-process
    feature until the multi-host collective capture lands."""
    try:
        import jax

        return jax.process_count() <= 1
    except Exception:  # noqa: BLE001 — no backend = no device arrays
        return True


class Booster:
    """Gradient boosting model handle (reference basic.py:1930)."""

    def __init__(
        self,
        params: Optional[Dict[str, Any]] = None,
        train_set: Optional[Dataset] = None,
        model_file: Optional[str] = None,
        model_str: Optional[str] = None,
        init_model: Optional[Union[str, "Booster"]] = None,
    ):
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._gbdt: Optional[GBDT] = None
        self._loaded: Optional[LoadedModel] = None
        self._loaded_str: Optional[str] = None   # source text of _loaded
                                                 # (checkpoint bundles
                                                 # re-embed it verbatim)
        self.train_set = train_set
        self._name_valid_sets: List[str] = []
        self._pred_objective = None
        # model-quality observability (ISSUE 14): the engine loop
        # appends metric curves here ({"dataset:metric": [values]});
        # capture_model_reference() caches its result
        self._metric_history: Dict[str, List[float]] = {}
        self._model_reference = None

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("train_set must be a Dataset")
            train_set.params = {**self.params, **train_set.params} \
                if train_set.params else dict(self.params)
            train_set.params.update(self.params)
            train_set.construct()
            self.config = Config.from_dict(self.params)
            init_raw = None
            if init_model is not None:
                # continued training (reference: CreateBoosting(type, file)
                # boosting.cpp:46+, init score from the old model's
                # prediction, application.cpp:90-93)
                if isinstance(init_model, Booster):
                    base_str = init_model.model_to_string()
                else:
                    with fileio.open_file(init_model) as fh:
                        base_str = fh.read()
                self._loaded = model_from_string(base_str)
                self._loaded_str = base_str
                if self._loaded.average_output:
                    log_fatal("Continued training from an RF (average_output)"
                              " model is not supported")
                init_raw = self._loaded_raw_scores(train_set,
                                                   "continued training")
                if train_set.init_score is not None:
                    # reference stacks the loaded model's scores ON TOP of
                    # the dataset init_score (ScoreUpdater ctor + AddScore)
                    init_raw = init_raw + np.asarray(
                        train_set.init_score, np.float64).reshape(
                            init_raw.shape[0], -1)
            self._gbdt = create_boosting(self.config, train_set._binned,
                                         init_raw_scores=init_raw)
        elif model_file is not None:
            with fileio.open_file(model_file) as fh:
                self._init_from_string(fh.read())
        elif model_str is not None:
            self._init_from_string(model_str)
        else:
            raise TypeError("Need at least one of train_set, model_file, model_str")

    # ------------------------------------------------------------------
    def _init_from_string(self, s: str) -> None:
        self._loaded = model_from_string(s)
        self._loaded_str = s
        params = {"objective": self._loaded.objective}
        if self._loaded.num_class > 1:
            params["num_class"] = self._loaded.num_class
        op = self._loaded.objective_params
        if "sigmoid" in op:
            params["sigmoid"] = float(op["sigmoid"])
        if "alpha" in op:
            params["alpha"] = float(op["alpha"])
        self.config = Config.from_dict(params)
        from .objectives import create_objective

        self._pred_objective = create_objective(self.config)

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if self._gbdt is None:
            log_fatal("Cannot add validation data to a loaded model")
        data.construct()
        init_raw = None
        if self._loaded is not None and self._loaded.trees:
            # continued training: valid scores resume from the loaded trees
            init_raw = self._loaded_raw_scores(data, "continued training")
            if data.init_score is not None:
                init_raw = init_raw + np.asarray(
                    data.init_score, np.float64).reshape(init_raw.shape[0], -1)
        self._gbdt.add_valid(data._binned, name, init_raw=init_raw)
        self._name_valid_sets.append(name)
        return self

    def _loaded_raw_scores(self, dataset: Dataset, why: str) -> np.ndarray:
        """Raw predictions of the loaded trees on a dataset's raw features."""
        X = dataset.data
        if X is None:
            log_fatal(f"Raw data is required for {why} "
                      "(dataset was constructed with free_raw_data=True)")
        K = max(self._loaded.num_tree_per_iteration, 1)
        raw = np.zeros((X.shape[0], K), dtype=np.float64)
        for i, t in enumerate(self._loaded.trees):
            raw[:, i % K] += t.predict(X)
        return raw

    def update(self, train_set: Optional[Dataset] = None,
               fobj: Optional[Callable] = None) -> bool:
        """One boosting iteration; returns True when no further splits are
        possible (reference basic.py:2315 update / __boost :2381)."""
        from .obs import trace

        if self._gbdt is None:
            log_fatal("Cannot update a loaded model")
        if train_set is not None:
            log_fatal("Resetting train_set is not supported")
        t0_ns = trace.now_ns()
        if fobj is None:
            finished = self._gbdt.train_one_iter()
        else:
            preds = self._gbdt.raw_train_scores()
            if self._gbdt.num_class == 1:
                preds = preds[:, 0]
            grad, hess = fobj(preds, self.train_set)
            finished = self._gbdt.train_one_iter(
                custom_grad=np.asarray(grad), custom_hess=np.asarray(hess)
            )
        # finite_guard=warn|raise: one scalar device read per iteration
        # boundary; off (default) costs nothing (models/gbdt.py)
        self._gbdt.check_finite_boundary()
        # observability: per-iteration wall into the shared registry
        # (always on — one histogram observe vs a ms-scale iteration);
        # an armed tracer additionally gets the iteration span (+ the
        # estimated phase children when a profile is installed)
        _obs_iteration_metrics().observe(
            (trace.now_ns() - t0_ns) / 1e6)
        if trace.enabled():
            trace.iteration_span_end(t0_ns, self._gbdt.iter - 1)
        return finished

    def rollback_one_iter(self) -> "Booster":
        if self._gbdt is not None:
            self._gbdt.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        n = 0
        if self._loaded is not None:
            n += self._loaded.num_iterations
        if self._gbdt is not None:
            n += self._gbdt.iter
        return n

    def num_trees(self) -> int:
        n = 0
        if self._loaded is not None:
            n += len(self._loaded.trees)
        if self._gbdt is not None:
            n += self._gbdt.num_trees()
        return n

    def num_model_per_iteration(self) -> int:
        if self._gbdt is not None:
            return self._gbdt.num_model_per_iteration
        return self._loaded.num_tree_per_iteration

    def num_feature(self) -> int:
        if self._gbdt is not None:
            return self._gbdt.train_set.num_features
        return self._loaded.max_feature_idx + 1

    def feature_name(self) -> List[str]:
        if self._gbdt is not None:
            return list(self._gbdt.train_set.feature_names)
        return list(self._loaded.feature_names)

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        self.params.update(params)
        if self._gbdt is not None:
            self._gbdt.config.update(params)
        return self

    # ------------------------------------------------------------------
    def eval_train(self, feval=None):
        out = [("training",) + tuple(r[1:]) for r in self._gbdt.eval_train()]
        return self._add_feval(out, feval, "training", self._gbdt.raw_train_scores(),
                               self.train_set)

    def eval_valid(self, feval=None):
        results = self._gbdt.eval_valid()
        out = list(results)
        if feval is not None:
            for i, name in enumerate(self._name_valid_sets):
                scores = np.asarray(self._gbdt._valid_scores[i].score)
                vs = self._gbdt._valid_sets[i]
                out = self._add_feval(out, feval, name, scores, vs)
        return out

    def _add_feval(self, out, feval, name, raw_scores, dataset):
        if feval is None:
            return out
        fevals = feval if isinstance(feval, (list, tuple)) else [feval]
        preds = raw_scores[:, 0] if raw_scores.shape[1] == 1 else raw_scores
        for f in fevals:
            res = f(preds, dataset)
            if isinstance(res, tuple):
                res = [res]
            for metric_name, value, hb in res:
                out.append((name, metric_name, value, hb))
        return out

    # ------------------------------------------------------------------
    def _all_trees(self) -> List[HostTree]:
        trees: List[HostTree] = []
        if self._loaded is not None:
            trees.extend(self._loaded.trees)
        if self._gbdt is not None:
            trees.extend(self._gbdt.materialize_host_trees())
        return trees

    def predict(
        self,
        data,
        start_iteration: int = 0,
        num_iteration: Optional[int] = None,
        raw_score: bool = False,
        pred_leaf: bool = False,
        pred_contrib: bool = False,
        **kwargs,
    ) -> np.ndarray:
        """Prediction on raw features (reference basic.py:2816 / Predictor)."""
        if _is_scipy_sparse(data) and data.shape[0] > 65536:
            # chunked densification bounds peak memory on wide-sparse input
            outs = [
                self.predict(data[i:i + 65536].toarray(),
                             start_iteration=start_iteration,
                             num_iteration=num_iteration,
                             raw_score=raw_score, pred_leaf=pred_leaf,
                             pred_contrib=pred_contrib, **kwargs)
                for i in range(0, data.shape[0], 65536)
            ]
            return np.concatenate(outs, axis=0)
        if isinstance(data, (str, os.PathLike)):
            df = load_data_file(str(data), is_predict=True)
            X = df.X
            # prediction files usually carry the label column like training
            # files do (reference Predictor convention); detect by column
            # count and strip it
            if X.shape[1] == self.num_feature() + 1:
                X = X[:, 1:]
        else:
            X = _to_2d_numpy(data)
        if X.shape[1] != self.num_feature():
            # reference predictor.hpp:170-174 / c_api predict shape guard
            disable = bool(kwargs.get(
                "predict_disable_shape_check",
                self.params.get("predict_disable_shape_check", False)))
            if not disable:
                from .utils.log import log_fatal

                log_fatal(
                    f"The number of features in data ({X.shape[1]}) is not "
                    f"the same as it was in training data "
                    f"({self.num_feature()}).\nYou can set "
                    f"``predict_disable_shape_check=true`` to discard this "
                    f"error, but please be aware what you are doing.")
        trees = self._all_trees()
        K = self.num_model_per_iteration()
        if num_iteration is None or num_iteration < 0:
            num_iteration = (self.best_iteration
                             if self.best_iteration and self.best_iteration > 0
                             else len(trees) // K)
        trees = trees[start_iteration * K: (start_iteration + num_iteration) * K]

        n = X.shape[0]
        es = bool(kwargs.get("pred_early_stop",
                             self.params.get("pred_early_stop", False)))
        es_freq = int(kwargs.get("pred_early_stop_freq",
                                 self.params.get("pred_early_stop_freq", 10)))
        es_margin = float(kwargs.get(
            "pred_early_stop_margin",
            self.params.get("pred_early_stop_margin", 10.0)))

        # device inference engine (models/predict.py): depth-stepped
        # all-trees walk / Pallas kernel / legacy scan pin, behind
        # predict_method; contrib and prediction-early-stop stay host-side
        method = str(kwargs.get("predict_method",
                                self.params.get("predict_method", "auto")))
        raw = None
        if method in ("depthwise", "pallas", "fused", "scan") and trees \
                and not pred_contrib and not (es and not raw_score):
            bp = self._device_predictor(trees, K, start_iteration, method,
                                        kwargs)
            if bp is not None:
                if pred_leaf:
                    return bp.predict_leaf(X)
                f64 = bool(kwargs.get(
                    "predict_f64_scores",
                    self.params.get("predict_f64_scores", False)))
                raw = np.asarray(bp.predict_raw(X, f64_exact=f64),
                                 np.float64)
                if raw.shape[1] != K:   # scan pin returns (N, 1)
                    raw = raw.reshape(n, K)

        if pred_leaf:
            out = np.stack([t.predict_leaf_index(X) for t in trees], axis=1)
            return out
        if pred_contrib:
            return self._predict_contrib(X, trees, K)

        if raw is not None:
            pass
        elif es and not raw_score:
            raw = np.zeros((n, K), dtype=np.float64)
            # reference: PredictionEarlyStopInstance
            # (src/boosting/prediction_early_stop.cpp:75) — every freq trees,
            # rows whose decision margin exceeds the threshold stop
            # accumulating further trees
            active = np.ones(n, dtype=bool)
            n_iters = len(trees) // K if K else 0
            for it in range(n_iters):
                idx = np.flatnonzero(active)
                if idx.size == 0:
                    break
                for k in range(K):
                    t = trees[it * K + k]
                    raw[idx, k] += t.predict(X[idx])
                if (it + 1) % es_freq == 0:
                    if K == 1:
                        margin = 2.0 * np.abs(raw[idx, 0])
                    else:
                        part = np.partition(raw[idx], K - 2, axis=1)
                        margin = part[:, K - 1] - part[:, K - 2]
                    active[idx[margin >= es_margin]] = False
        else:
            raw = np.zeros((n, K), dtype=np.float64)
            native = None
            if method == "native" or (
                    method != "host"
                    and n * len(trees) >= _NATIVE_PREDICT_MIN_WORK):
                # native C++ predictor (the reference Predictor role,
                # predictor.hpp:29-160): per-row walks over flattened
                # arrays, threaded; ~10x the vectorized numpy walk
                native = self._predict_raw_native(
                    X, trees, K, start_iteration)
            if native is not None:
                raw = native
            else:
                for i, t in enumerate(trees):
                    raw[:, i % K] += t.predict(X)
        # the boost-from-average constant lives inside tree leaf values
        # (AddBias, reference gbdt.cpp:381-383), so no base term is added
        from .models.gbdt import RF

        avg = (self._loaded.average_output if self._loaded is not None
               else isinstance(self._gbdt, RF))
        if avg and trees:
            raw = raw / (len(trees) // K)
        if raw_score:
            return raw[:, 0] if K == 1 else raw
        obj = self._gbdt.objective if self._gbdt is not None else self._pred_objective
        if obj is not None:
            converted = obj.convert_output(raw if K > 1 else raw[:, 0])
            return np.asarray(converted)
        return raw[:, 0] if K == 1 else raw

    def _predict_raw_native(self, X, trees, K, start_iteration=0):
        """Native bulk prediction; None -> numpy fallback.  The flattened
        ensemble pack is cached per (slice start, tree count, model
        version) — the version counter bumps on every ``iter`` move, and
        every in-place ensemble mutation (tree append, rollback
        truncation, DART drop-rescale of existing trees) happens inside an
        update/rollback that moves ``iter``; the slice start distinguishes
        same-length windows (start_iteration paging).  Tree object
        identity is deliberately NOT part of the key: host trees may be
        freshly materialized per call (id() would never hit) and CPython
        id() can alias after GC."""
        from .native import build_ensemble_pack, predict_ensemble

        key = (start_iteration, len(trees),
               self._gbdt.model_version if self._gbdt is not None else -1)
        cached = getattr(self, "_native_pred_cache", None)
        if cached is None or cached[0] != key:
            pack = build_ensemble_pack(trees, K)
            self._native_pred_cache = (key, pack)
        else:
            pack = cached[1]
        if pack is None or X.shape[1] <= pack["max_feat"]:
            # narrow X must fail loudly on the numpy path (IndexError),
            # never read out of bounds natively
            return None
        nt = int(self.params.get("num_threads", 0) or 0)
        return predict_ensemble(X, pack, num_threads=nt)

    def _device_predictor(self, trees, K, start_iteration, method, kwargs):
        """Device inference engine (models/predict.BatchPredictor), cached
        per (slice start, tree count, model version, method) — the same
        key discipline as the native pack cache: any ensemble mutation
        (update/rollback/DART drop-rescale) moves ``model_version`` and
        drops the predictor, its serving tables and its compiled-walk
        cache wholesale.  None -> host fallback (e.g. categorical model
        without raw category sets, scan with K>1)."""
        key = (start_iteration, len(trees),
               self._gbdt.model_version if self._gbdt is not None else -1,
               method)
        cached = getattr(self, "_device_pred_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        from .models.predict import BatchPredictor

        def p(name, dflt):
            return kwargs.get(name, self.params.get(name, dflt))

        try:
            bp = BatchPredictor(
                trees, K, self.num_feature(), method=method,
                prebin=str(p("predict_prebin", "auto")),
                code_layout=str(p("predict_code_layout", "auto")),
                num_shards=int(p("predict_num_shards", 0)),
                bucket_min=int(p("predict_bucket_min", 256)),
                chunk_rows=int(p("predict_chunk_rows", 131072)),
                cache_entries=int(p("predict_cache_entries", 64)),
            )
        except Exception as e:  # noqa: BLE001 — host fallback
            log_warning(f"device predict unavailable "
                        f"({type(e).__name__}: {e}); using the host path")
            bp = None
        self._device_pred_cache = (key, bp)
        return bp

    def refit(self, data, label, decay_rate: float = 0.9) -> "Booster":
        """Refit the existing model's leaf values on new data
        (reference: basic.py:2873 refit → GBDT::RefitTree gbdt.cpp:266-290 →
        FitByExistingTree; ``leaf_output = decay_rate * old +
        (1 - decay_rate) * new``).  Tree structures are kept; only outputs
        are re-estimated from the new data's gradients."""
        from copy import deepcopy

        from .objectives import create_objective

        X = _to_2d_numpy(data)
        y = np.asarray(label, dtype=np.float32).ravel()
        trees = [deepcopy(t) for t in self._all_trees()]
        if not trees:
            log_fatal("Cannot refit an empty model")
        K = self.num_model_per_iteration()
        cfg = getattr(self, "config", None) or Config.from_dict(self.params)
        obj = create_objective(cfg)
        if obj is None:
            raise LightGBMError("Cannot refit due to null objective function.")

        from .io.dataset import Metadata

        meta = Metadata()
        meta.label = y
        obj.init(meta, len(y))
        l1, l2 = cfg.lambda_l1, cfg.lambda_l2
        scores = np.zeros((len(y), K), dtype=np.float64)
        import jax

        for i, t in enumerate(trees):
            k = i % K
            s = scores[:, 0] if K == 1 else scores
            grad, hess = jax.device_get(obj.get_gradients(
                np.asarray(s, np.float32)))
            grad = np.asarray(grad).reshape(len(y), -1)[:, k]
            hess = np.asarray(hess).reshape(len(y), -1)[:, k]
            leaf = t.predict_leaf_index(X)
            for lf in range(t.num_leaves):
                rows = leaf == lf
                if not rows.any():
                    continue
                sg, sh = grad[rows].sum(), hess[rows].sum()
                thr = np.sign(sg) * max(abs(sg) - l1, 0.0)
                new_out = (-thr / (sh + l2)) * t.shrinkage
                t.leaf_value[lf] = (decay_rate * t.leaf_value[lf]
                                    + (1.0 - decay_rate) * new_out)
            scores[:, k] += t.leaf_value[leaf]

        new_booster = Booster.__new__(Booster)
        new_booster.params = dict(self.params)
        new_booster.best_iteration = -1
        new_booster.best_score = {}
        new_booster._gbdt = None
        new_booster.train_set = None
        new_booster._name_valid_sets = []
        new_booster._loaded_str = None
        if self._loaded is not None and self._gbdt is None:
            loaded = deepcopy(self._loaded)
        else:
            loaded = model_from_string(self.model_to_string())
        loaded.trees = trees
        new_booster._loaded = loaded
        new_booster.config = cfg
        new_booster._pred_objective = obj
        return new_booster

    def _predict_contrib(self, X, trees, K):
        """Exact TreeSHAP feature contributions (reference:
        Tree::PredictContrib tree.h:138, src/io/tree.cpp TreeSHAP); the
        last column per class is the expected value (base)."""
        from .models.treeshap import tree_shap

        n, F = X.shape
        out = np.zeros((n, K * (F + 1)), dtype=np.float64)
        for ti, t in enumerate(trees):
            k = ti % K
            contribs = tree_shap(t, X)
            out[:, k * (F + 1): k * (F + 1) + F + 1] += contribs
        return out[:, : F + 1] if K == 1 else out

    # ------------------------------------------------------------------
    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        trees = self._all_trees()
        K = self.num_model_per_iteration()
        if num_iteration is None or num_iteration < 0:
            num_iteration = (self.best_iteration
                             if self.best_iteration and self.best_iteration > 0
                             else len(trees) // K)
        trees = trees[start_iteration * K: (start_iteration + num_iteration) * K]
        if self._gbdt is not None:
            cfg = self.config
            ds = self._gbdt.train_set
            feature_names = list(ds.feature_names)
            feature_infos = ds.feature_infos()
            objective_string = _objective_string(cfg)
            from .models.gbdt import RF

            average_output = isinstance(self._gbdt, RF)
            params = {
                "boosting": cfg.boosting, "objective": cfg.objective,
                "metric": ",".join(cfg.metric), "learning_rate": cfg.learning_rate,
                "num_leaves": cfg.num_leaves, "max_depth": cfg.max_depth,
                "min_data_in_leaf": cfg.min_data_in_leaf,
                "min_sum_hessian_in_leaf": cfg.min_sum_hessian_in_leaf,
                "bagging_fraction": cfg.bagging_fraction,
                "bagging_freq": cfg.bagging_freq,
                "feature_fraction": cfg.feature_fraction,
                "lambda_l1": cfg.lambda_l1, "lambda_l2": cfg.lambda_l2,
                "max_bin": cfg.max_bin, "seed": cfg.seed,
            }
        else:
            lm = self._loaded
            feature_names = lm.feature_names
            feature_infos = lm.feature_infos
            objective_string = lm.objective + "".join(
                f" {k}:{v}" for k, v in lm.objective_params.items())
            average_output = lm.average_output
            params = lm.parameters
        return model_to_string(
            trees,
            objective_string=objective_string,
            num_class=self.config.num_class if self._gbdt is not None else self._loaded.num_class,
            num_tree_per_iteration=K,
            feature_names=feature_names,
            feature_infos=feature_infos,
            average_output=average_output,
            parameters=params,
            # reference: saved_feature_importance_type selects split counts
            # (0) or total gains (1) in the model's importance block
            # (application.cpp:204, gbdt.cpp:779-800)
            importance_type=(self.config.saved_feature_importance_type
                             if self._gbdt is not None else 0),
        )

    def save_model(self, filename, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        # crash-consistent by construction: tmp+fsync+rename, so a kill
        # mid-save leaves the previous model file intact instead of a
        # truncated one (the pre-PR-6 snapshot failure mode)
        fileio.atomic_write_text(
            str(filename), self.model_to_string(num_iteration,
                                                start_iteration),
            site=str(filename))
        return self

    # ------------------------------------------------------------------
    def capture_model_reference(self, score_bins: Optional[int] = None):
        """Training-time reference capture (ISSUE 14, obs/model.py):
        one pass over the already-binned training matrix (streamed per
        block on the out-of-core path) records per-feature
        bin-occupancy histograms over the ensemble's own BinMapper
        bins, NaN rates, and the raw training-score distribution.
        Returns the :class:`~lightgbmv1_tpu.obs.model.ModelReference`
        the serving side re-bins sampled requests against (and caches
        it on the Booster for checkpoint/publish plumbing)."""
        if self._gbdt is None:
            log_fatal("capture_model_reference() requires a training "
                      "Booster")
        from .obs.model import capture_reference

        if score_bins is None:
            score_bins = self.config.drift_score_bins
        self._model_reference = capture_reference(
            self._gbdt.train_set,
            np.asarray(self._gbdt.raw_train_scores()),
            score_bins=score_bins)
        return self._model_reference

    def quality_snapshot(self, top_k: int = 8) -> Dict:
        """Trainer quality telemetry (obs/model.py): per-iteration
        split-gain / leaf / depth aggregates, gain+split feature
        importance and the recorded train/valid metric curves —
        computed after the fact from host trees, never perturbing the
        training loop."""
        from .obs.model import quality_snapshot

        return quality_snapshot(self, top_k=top_k)

    # ------------------------------------------------------------------
    def save_checkpoint(self, path, write_file: bool = True,
                        with_reference: bool = True) -> "Booster":
        """Write a crash-consistent full-trainer-state bundle
        (io/checkpoint.py): model text + score caches + RNG/bagging/DART
        state + iteration counter, atomically.  A training run resumed
        from this bundle (:meth:`resume_from_checkpoint`) continues
        BIT-EXACTLY — the final model text matches the uninterrupted
        run's byte for byte (tests/test_checkpoint.py).

        Under multi-process training the state capture is a COLLECTIVE
        (cross-process score caches are gathered): every rank must call
        this in lockstep, with ``write_file=False`` on the non-writing
        ranks (parallel/elastic_worker.py — one bundle, rank 0's)."""
        if self._gbdt is None:
            log_fatal("save_checkpoint() requires a training Booster")
        from .io.checkpoint import write_checkpoint

        manifest, arrays = self._gbdt.capture_state()
        manifest["num_trees_total"] = self.num_trees()
        if write_file:
            ref_bytes = b""
            if with_reference and _reference_capture_supported():
                # the bundle carries the training reference (ISSUE 14)
                # so a resumed/served model keeps its drift baseline;
                # capture is host-side only (no collective), which is
                # why it runs on the WRITING rank alone — and why it is
                # SKIPPED under multi-process training: reading the
                # cross-process score cache from one rank aborts inside
                # the runtime (not a catchable Python error), and a
                # collective capture belongs to the multi-host item
                try:
                    ref_bytes = self.capture_model_reference().to_bytes()
                except Exception as e:  # noqa: BLE001 — e.g. sparse
                    # bundle-only datasets keep no per-feature matrix
                    log_warning(f"checkpoint: reference capture skipped "
                                f"({type(e).__name__}: {e})")
            write_checkpoint(str(path), manifest, arrays,
                             model_text=self.model_to_string(),
                             base_model_text=(self._loaded_str
                                              if self._loaded is not None
                                              else "") or "",
                             reference_bytes=ref_bytes)
        return self

    def resume_from_checkpoint(self, path_or_bundle) -> "Booster":
        """Restore a bundle into this FRESH training Booster (same data,
        same config, valid sets already attached).  Accepts a path or a
        pre-loaded ``io.checkpoint.load_checkpoint`` dict.  The bundle is
        fully validated (digests + ``validate_host_tree`` on the model
        text) before any state is touched; raises ``CheckpointError``
        otherwise."""
        if self._gbdt is None:
            log_fatal("resume_from_checkpoint() requires a training "
                      "Booster (construct with train_set=...)")
        from .io.checkpoint import load_checkpoint
        from .io.model_text import model_from_string

        bundle = (path_or_bundle
                  if isinstance(path_or_bundle, dict)
                  else load_checkpoint(str(path_or_bundle)))
        base = bundle.get("base_model_text", "")
        if base and self._loaded is None:
            # the checkpointed run itself continued from an input_model:
            # restore the loaded-tree prefix so tree indexing matches
            self._loaded = model_from_string(base)
            self._loaded_str = base
        self._gbdt.restore_state(bundle["manifest"], bundle["arrays"])
        return self

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> Dict:
        trees = self._all_trees()
        K = self.num_model_per_iteration()
        if num_iteration is None or num_iteration < 0:
            num_iteration = len(trees) // K
        trees = trees[start_iteration * K: (start_iteration + num_iteration) * K]
        if self._gbdt is not None:
            ds = self._gbdt.train_set
            names, infos = list(ds.feature_names), ds.feature_infos()
            objective_string = _objective_string(self.config)
            num_class = self.config.num_class
        else:
            names, infos = self._loaded.feature_names, self._loaded.feature_infos
            objective_string = self._loaded.objective
            num_class = self._loaded.num_class
        return dump_model_dict(
            trees, objective_string=objective_string, num_class=num_class,
            num_tree_per_iteration=K, feature_names=names, feature_infos=infos,
        )

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        trees = self._all_trees()
        K = self.num_model_per_iteration()
        if iteration is not None and iteration >= 0:
            trees = trees[: iteration * K]
        F = self.num_feature()
        out = np.zeros(F, dtype=np.float64)
        for t in trees:
            for i in range(t.num_leaves - 1):
                f = t.split_feature[i]
                if importance_type == "split":
                    out[f] += 1
                else:
                    out[f] += t.split_gain[i]
        if importance_type == "split":
            return out.astype(np.int64)
        return out

    def __copy__(self):
        return self

    def free_dataset(self) -> "Booster":
        return self

    def free_network(self) -> "Booster":
        return self
