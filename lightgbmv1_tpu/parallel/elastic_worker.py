"""Elastic training worker — one rank of an elastic fleet.

Launched by :class:`~lightgbmv1_tpu.parallel.elastic.ElasticCoordinator`
as ``python -m lightgbmv1_tpu.parallel.elastic_worker key=value ...``.
Composes the pieces the recovery contract names:

* ``cluster.init_cluster`` — jax.distributed bootstrap (gloo CPU
  collectives + jittered retry);
* ``dist_data.load_distributed`` — this rank's row shard with globally
  agreed bins, RELOADED identically on every re-bootstrap (the shard is
  a pure function of (file, rank, world));
* PR-6 checkpoint bundles — rank 0 writes
  ``<model_out>.ckpt_iter_<k>`` every ``snapshot_freq`` iterations
  (training is implicitly barriered by the per-iteration collectives,
  so a bundle at iteration k means EVERY rank completed k); on respawn
  every rank resumes bit-exactly from the newest intact bundle via the
  CLI's validated resume-point scan;
* ``elastic.LeaseBoard`` heartbeats + peer-loss abort
  (``EXIT_PEER_LOST``), so a dead peer costs a bounded detection
  window instead of an infinite collective hang.

Fault seam: ``faults.fire("peer_dead", site="rank<r>:iter<i>")`` at
every iteration boundary — a chaos plan with ``mode="kill"`` and a
matching site is THE deterministic kill-at-k (utils/faults.py arms it
from ``LGBMV1_FAULTS``; the armed flight recorder dumps the worker's
forensic bundle on the way out).

argv keys: ``rank world port leases_dir lease_timeout_s generation
data model_out iterations snapshot_freq num_leaves min_data_in_leaf
seed objective``.
"""

from __future__ import annotations

import os
import sys


def _parse_kv(argv):
    out = {}
    for a in argv:
        k, _, v = a.partition("=")
        out[k] = v
    return out


def main(argv) -> int:
    kv = _parse_kv(argv)
    rank = int(kv["rank"])
    world = int(kv["world"])
    port = kv["port"]
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from ..obs import dump as obs_dump
    from ..obs import events as obs_events

    obs_events.set_identity(role=os.environ.get(
        "LGBMV1_OBS_ROLE", f"trainer-r{rank}"))
    crash_dir = os.environ.get("LGBMV1_CRASH_DIR", "")
    if crash_dir:
        obs_dump.arm(crash_dir)
    if os.environ.get("LGBMV1_OBS_DIR", ""):
        # span tracer armed so the per-iteration spans land in this
        # rank's artifact — the fleet-merged Perfetto trace gets one
        # lane per worker (obs/agg.py)
        from ..obs import trace as obs_trace

        obs_trace.arm()

    from .cluster import init_cluster

    init_cluster(coordinator_address=f"127.0.0.1:{port}",
                 num_processes=world, process_id=rank)

    from ..basic import Booster, Dataset
    from ..cli import _find_resume_point, _prune_snapshots
    from ..config import Config
    from ..parallel.dist_data import load_distributed
    from ..utils import faults
    from ..utils.log import log_info
    from .elastic import EXIT_PEER_LOST, HeartbeatMonitor, LeaseBoard

    params = {
        "objective": kv.get("objective", "binary"),
        "num_leaves": int(kv.get("num_leaves", 7)),
        "min_data_in_leaf": int(kv.get("min_data_in_leaf", 20)),
        "tree_learner": "data" if world > 1 else "serial",
        "enable_bundle": False,
        "seed": int(kv.get("seed", 7)),
        "verbosity": -1,
    }
    if world > 1 and kv.get("collective"):
        # pod-scale passthrough (ISSUE 16): the hierarchical two-level
        # collective over the real process fleet (one host row per rank).
        # num_hosts falls back to the CURRENT world so a shrunk fleet
        # rebuilds a valid (host, chip) mesh without coordinator help.
        params["data_parallel_collective"] = kv["collective"]
        params["num_hosts"] = int(kv.get("num_hosts", 0)) or world
    cfg = Config.from_dict(params)
    # shard reload: each generation re-derives exactly this rank's rows
    # + the globally agreed bin mappers from the immutable data file (or,
    # for a block cache, this rank's manifest shard range — re-derived
    # from the CURRENT (rank, world), so a shrunk fleet repartitions)
    binned = load_distributed(kv["data"], cfg)

    model_out = kv["model_out"]
    iterations = int(kv.get("iterations", 8))
    snapshot_freq = int(kv.get("snapshot_freq", 2))

    booster = Booster(params=params,
                      train_set=Dataset.from_binned(binned, params=params))
    done_iters = 0
    if not os.path.exists(model_out):
        kind, path, done_iters, bundle = _find_resume_point(model_out)
        if kind == "ckpt":
            booster.resume_from_checkpoint(bundle)
            log_info(f"elastic worker {rank}: resumed bit-exactly from "
                     f"{path} ({done_iters} iterations done)")
        else:
            done_iters = 0

    board = LeaseBoard(kv["leases_dir"], rank=rank, world=world,
                       timeout_s=float(kv.get("lease_timeout_s", 3.0)))
    monitor = HeartbeatMonitor(
        board, obs_export_dir=os.environ.get("LGBMV1_OBS_DIR", "")).start()

    try:
        for i in range(done_iters, iterations):
            booster.update()
            board.beat(iteration=i + 1)
            # deterministic kill-at-k seam: a peer_dead kill plan lands
            # HERE, after iteration i+1's collectives completed everywhere
            faults.fire("peer_dead", site=f"rank{rank}:iter{i + 1}")
            if snapshot_freq > 0 and (i + 1) % snapshot_freq == 0:
                # COLLECTIVE capture on every rank (cross-process score
                # gather); one bundle on disk — rank 0's
                booster.save_checkpoint(f"{model_out}.ckpt_iter_{i + 1}",
                                        write_file=(rank == 0))
                if rank == 0:
                    _prune_snapshots(model_out, keep=2)
    except BaseException:
        # a failed collective under a dying peer is a PEER LOSS, not a
        # crash of this worker: wait out the lease window for the
        # verdict, and exit for re-bootstrap without burning a forensic
        # bundle (the killed peer's own bundle is the crash evidence).
        # No stale peer -> a genuine local crash: re-raise into the
        # armed flight recorder.
        dead = board.wait_stale()
        if not dead:
            raise
        from ..obs import events as _ev

        _ev.publish("fleet.peer_lost",
                    f"collective failed and rank(s) {dead} lease went "
                    "stale — aborting for re-bootstrap",
                    severity="error", dead_ranks=list(dead), rank=rank)
        obs_dir = os.environ.get("LGBMV1_OBS_DIR", "")
        if obs_dir:
            try:
                from ..obs import agg as obs_agg

                obs_agg.export_process_artifacts(obs_dir)
            except Exception:   # noqa: BLE001
                pass
        return EXIT_PEER_LOST
    monitor.stop()
    if monitor.lost:
        return EXIT_PEER_LOST
    if rank == 0:
        booster.save_model(model_out)

    obs_dir = os.environ.get("LGBMV1_OBS_DIR", "")
    if obs_dir:
        from ..obs import agg as obs_agg

        obs_agg.export_process_artifacts(obs_dir)
    print(f"ELASTIC RANK {rank} DONE iters={iterations}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
