"""Distributed (multi-process) data loading and bin finding.

TPU-native equivalent of the reference's distributed loader path
(reference: ``DatasetLoader::LoadFromFile(filename, rank, num_machines)``
src/io/dataset_loader.cpp:167 — loader-level row pre-partition per rank —
and the distributed bin-mapper construction ``dataset_loader.cpp:913-996``,
where each rank bins a feature shard and ``Network::Allgather``s the
serialized mappers so every rank owns identical bin boundaries).

Here each process loads ONLY its contiguous row shard; bin boundaries are
agreed by allgathering the per-process value samples (small:
``bin_construct_sample_cnt`` rows) with ``jax.experimental.multihost_utils``
— the ICI/DCN analog of the reference's socket allgather — and every
process then runs the identical deterministic GreedyFindBin on the gathered
sample, guaranteeing byte-identical mappers without exchanging them.

Use after ``cluster.init_cluster``::

    init_cluster(...)
    ds = load_distributed(path, config)     # local row shard, global bins

Trainer contract: ``load_distributed`` provides the loader-level rank
pre-partition and the cross-process bin agreement, and
``make_process_sharded`` (below) converts the local shard into the
process-sharded storage the data-parallel trainer consumes directly
(``parallel/trainer.py row_sharded``) — each process keeps only its own
binned rows, with labels/weights allgathered for objectives/metrics.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..config import Config
from ..io.binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper
from ..io.dataset import BinnedDataset
from ..io.parser import load_data_file, shard_rows  # noqa: F401 (re-export)
from ..utils.log import log_info


def find_bins_distributed(local_samples: List[np.ndarray], sample_cnt: int,
                          max_bins, categorical, config: Config,
                          num_data: int = 0) -> List[BinMapper]:
    """Bin-finding with cross-process sample allgather (the analog of the
    reference's serialized-mapper Allgather, dataset_loader.cpp:913-996).

    ``local_samples``: per-feature sample arrays from THIS process's shard.
    Every process receives the concatenated global sample and runs the same
    deterministic GreedyFindBin, so mappers agree bit-for-bit.
    """
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        # pad local samples to one common length so the allgather has a
        # single static shape; NaN marks padding, real missing values are
        # carried as explicit per-feature counts and re-appended after
        n_local = max((len(s) for s in local_samples), default=0)
        n_max = int(multihost_utils.process_allgather(
            np.asarray(n_local)).max())
        F = len(local_samples)
        mat = np.full((F, n_max), np.nan)
        na_cnt = np.zeros(F, np.int64)
        for j, s in enumerate(local_samples):
            valid = s[~np.isnan(s)]
            na_cnt[j] = len(s) - len(valid)
            mat[j, : len(valid)] = valid
        gathered = np.asarray(multihost_utils.process_allgather(
            mat)).reshape(-1, F, n_max)                 # (world, F, n_max)
        na_all = np.asarray(multihost_utils.process_allgather(
            na_cnt)).reshape(-1, F).sum(axis=0)         # (F,)
        samples = []
        for j in range(F):
            vals = gathered[:, j, :].ravel()
            vals = vals[~np.isnan(vals)]
            samples.append(np.concatenate(
                [vals, np.full(int(na_all[j]), np.nan)]))
        total_cnt = int(multihost_utils.process_allgather(
            np.asarray(sample_cnt)).sum())
        total_rows = int(multihost_utils.process_allgather(
            np.asarray(num_data)).sum())
    else:
        samples = local_samples
        total_cnt = sample_cnt
        total_rows = num_data

    from ..io.binning import get_forced_bins

    forced = get_forced_bins(config.forcedbins_filename, len(samples),
                             categorical)
    return [
        BinMapper.find_bin(
            np.asarray(samples[j], np.float64),
            total_sample_cnt=total_cnt,
            max_bin=max_bins[j],
            min_data_in_bin=config.min_data_in_bin,
            bin_type=BIN_CATEGORICAL if j in categorical else BIN_NUMERICAL,
            use_missing=config.use_missing,
            zero_as_missing=config.zero_as_missing,
            forced_bounds=forced[j],
            pre_filter=config.feature_pre_filter,
            filter_cnt=int(config.min_data_in_leaf * total_cnt
                           / max(total_rows, total_cnt, 1)),
        )
        for j in range(len(samples))
    ]


def make_process_sharded(ds: BinnedDataset, config: Config) -> BinnedDataset:
    """Convert a process-LOCAL shard dataset into the trainer's
    process-sharded form: the binned matrix stays local (this is the memory
    win — reference per-machine memory drops 176 GB -> 11 GB at 16 ranks,
    docs/Experiments.rst:228-240), while labels/weights are allgathered so
    objectives/metrics see the global view (they are O(N) scalars, a few
    bytes/row against the binned matrix's F bytes/row).

    Every process pads its shard to the common per-process row count R
    (a multiple of its local device count); padded rows carry weight 0, so
    they contribute nothing to gradients, counts, or metrics.  The trainer
    turns the local (F, R) shards into one global (F, R*world) device array
    via ``jax.make_array_from_process_local_data``."""
    import jax
    from jax.experimental import multihost_utils

    world = jax.process_count()
    if world <= 1 or getattr(ds, "is_row_sharded", False):
        return ds
    if ds.metadata.group is not None:
        log_info("process-sharded training with query data keeps the "
                 "host-replicated layout (query-aligned sharding is not "
                 "yet wired); memory scaling applies to non-ranking tasks")
        return ds
    d_local = jax.local_device_count()
    n_local = ds.num_data
    n_all = np.asarray(multihost_utils.process_allgather(
        np.asarray(n_local))).reshape(-1)
    R = int(-(-n_all.max() // d_local) * d_local)
    F = ds.binned.shape[0]

    binned_local = np.zeros((F, R), dtype=ds.binned.dtype)
    binned_local[:, :n_local] = ds.binned

    def gather_field(x, cols=1):
        """Allgather an (n_local,) or (n_local, cols) per-row field into the
        (world*R, ...) padded-global layout (pad rows zero)."""
        loc = np.zeros((R, cols), np.float64)
        if x is not None:
            loc[:n_local] = np.asarray(x, np.float64).reshape(n_local, cols)
        g = np.asarray(multihost_utils.process_allgather(
            loc)).reshape(world * R, cols)
        return g[:, 0] if cols == 1 else g

    g_label = gather_field(ds.metadata.label)
    # weight 0 marks padded rows globally (real rows default to weight 1)
    w_local = (np.asarray(ds.metadata.weight, np.float64).ravel()
               if ds.metadata.weight is not None else np.ones(n_local))
    g_weight = gather_field(w_local)
    g_init = None
    if ds.metadata.init_score is not None:
        k = len(np.asarray(ds.metadata.init_score).ravel()) // max(n_local, 1)
        g_init = gather_field(ds.metadata.init_score, cols=max(k, 1))
    g_valid = gather_field(np.ones(n_local))

    from ..io.dataset import Metadata

    meta = Metadata(label=g_label.astype(np.float32),
                    weight=g_weight.astype(np.float32),
                    init_score=g_init,
                    valid_rows=g_valid > 0.5)
    out = BinnedDataset(binned_local, ds.bin_mappers, meta,
                        ds.feature_names, max_bin=ds.max_bin)
    out.num_data = R * world                        # GLOBAL padded rows
    out.is_row_sharded = True
    out.local_rows = R
    out.row_valid = g_valid > 0.5                   # phantom pad rows: count 0
    log_info(f"Process-sharded dataset: {R} local rows/process x {world} "
             f"processes = {R * world} global (binned matrix stays local)")
    return out


def load_block_cache_distributed(path: str, config: Config,
                                 shard_to_trainer: bool = True
                                 ) -> BinnedDataset:
    """Host-sharded streaming load (ISSUE 16): each process opens a SHARD
    VIEW of the block cache — only its own contiguous block run is read
    off disk, so dataset size scales with the fleet, not the host.  Bin
    mappers come from the cache's meta shard (already global: binning
    happened at write time), so no cross-process bin agreement is needed;
    the local rows then enter the trainer through the same
    ``make_process_sharded`` contract the file loader uses."""
    import jax

    from ..data.streaming import StreamingDataset

    rank, world = jax.process_index(), jax.process_count()
    shard = (rank, world) if world > 1 else None
    sds = StreamingDataset(path, shard=shard)
    # materialize THIS shard only: (F, local_rows) — the O(shard) memory
    # the host-sharded contract promises (never the global matrix)
    local = sds.materialize()
    log_info(f"Process {rank}/{world}: streamed {local.num_data} local "
             f"rows from block cache {path}"
             + (f" (global rows [{sds.shard_row_range[0]}, "
                f"{sds.shard_row_range[1]}))" if shard else ""))
    if shard_to_trainer and world > 1 \
            and config.tree_learner == "data":
        local = make_process_sharded(local, config)
    return local


def load_distributed(path: str, config: Config,
                     categorical_features=None,
                     shard_to_trainer: bool = True) -> BinnedDataset:
    """Load this process's row shard of ``path`` and bin it with globally
    agreed boundaries.  Single-process: equivalent to the normal loader.

    Delegates to ``BinnedDataset.from_numpy`` with the ``bin_finder`` hook,
    so sampling, validation, metadata handling and dtype selection stay in
    one place; only the shard parsing and the cross-process bin agreement
    are distributed concerns."""
    import jax

    from ..data.block_cache import is_block_cache

    if is_block_cache(path):
        return load_block_cache_distributed(
            path, config, shard_to_trainer=shard_to_trainer)

    rank, world = jax.process_index(), jax.process_count()
    # pre_partition=true: each process's data file already holds ONLY its
    # rows, so the loader-level rank row-shard is skipped (reference:
    # config.h is_pre_partition / dataset_loader.cpp:167 LoadFromFile with
    # used_data_indices bypass when pre-partitioned)
    shard_here = world > 1 and not config.pre_partition
    df = load_data_file(
        path,
        has_header=config.header,
        label_column=config.label_column,
        weight_column=config.weight_column,
        group_column=config.group_column,
        ignore_column=config.ignore_column,
        rank=rank if shard_here else None,
        num_machines=world,
    )
    log_info(f"Process {rank}/{world}: {df.X.shape[0]} local rows "
             + ("(pre-partitioned input)" if config.pre_partition and world > 1
                else "(reference rank pre-partition)"))
    if world > 1:
        import dataclasses

        # keep the GLOBAL gathered sample within the configured budget (each
        # rank contributes its share; the gather concatenates them), and
        # keep EFB off: bundling needs a cross-process-agreed layout
        # (conflict masks would have to be allgathered like the bin samples)
        config = dataclasses.replace(
            config,
            bin_construct_sample_cnt=max(
                1, config.bin_construct_sample_cnt // world),
            enable_bundle=False)
    ds = BinnedDataset.from_numpy(
        df.X, label=df.label, weight=df.weight, group=df.group,
        init_score=getattr(df, "init_score", None),
        config=config, categorical_features=categorical_features,
        feature_names=df.feature_names,
        bin_finder=find_bins_distributed,
    )
    # process-sharded storage applies to the data-parallel learner only
    # (the reference's row pre-partition is likewise data-parallel,
    # data_parallel_tree_learner.cpp); feature/voting learners replicate
    if shard_to_trainer and config.tree_learner == "data":
        ds = make_process_sharded(ds, config)
    return ds
