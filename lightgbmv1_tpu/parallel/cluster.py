"""Multi-host (multi-process) cluster initialization.

TPU-native replacement for the reference's Network/Linkers bring-up
(reference: ``Network::Init`` src/network/network.cpp:30, socket linker
``src/network/linkers_socket.cpp`` — machine-list parsing, rank discovery,
TCP mesh connect; MPI linker ``linkers_mpi.cpp``).  Here the transport is
jax.distributed's gRPC coordination service + the XLA runtime's ICI/DCN
collectives; after ``init_cluster`` the data/feature/voting-parallel
learners in ``trainer.py`` span every process's devices through the SAME
shard_map code path (``jax.devices()`` becomes the global device list).

Configuration mirrors the reference's network parameters:

* ``machines``       — comma-separated ``host:port`` list; the first entry
  is the coordinator (reference: config.h machines / machine_list_filename)
* ``num_machines``   — world size
* ``machine_rank``   — this process's rank; when absent it is discovered by
  matching a local interface address against ``machines``, exactly like the
  socket linker's rank discovery.

Standard cluster launchers (SLURM, Cloud TPU pods) are auto-detected by
``jax.distributed.initialize()`` when no explicit arguments are given.
"""

from __future__ import annotations

import socket
from typing import List, Optional

from ..config import Config
from ..utils.log import log_fatal, log_info, log_warning

_initialized = False


def _local_addresses() -> List[str]:
    addrs = {"127.0.0.1", "localhost"}
    try:
        hostname = socket.gethostname()
        addrs.add(hostname)
        addrs.add(socket.gethostbyname(hostname))
    except OSError:
        pass
    return list(addrs)


def parse_machine_list(spec: str) -> List[str]:
    """reference: socket linker machine-list parsing (machines config or
    mlist file contents, one host:port per entry)."""
    entries = [m.strip() for m in spec.replace("\n", ",").split(",")]
    return [m for m in entries if m]


def discover_rank(machines: List[str]) -> Optional[int]:
    """Find this process's rank by local address match; multiple local
    entries (several processes on one host) are disambiguated by port
    bindability — the same trick the reference socket linker uses
    (linkers_socket.cpp binds local_listen_port to claim a rank)."""
    local = set(_local_addresses())
    candidates = []
    for i, m in enumerate(machines):
        host, _, port = m.rpartition(":")
        if (host or m) in local:
            candidates.append((i, int(port) if port.isdigit() else 0))
    if len(candidates) == 1:
        return candidates[0][0]
    for i, port in candidates:
        if port <= 0:
            continue
        try:
            with socket.socket() as s:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("0.0.0.0", port))
            return i
        except OSError:
            continue
    return candidates[0][0] if candidates else None


def init_cluster(
    config: Optional[Config] = None,
    *,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize jax.distributed so a process-spanning Mesh is available.

    Call once per process before building any trainer.  With a ``Config``
    carrying ``machines``/``num_machines`` the reference CLI semantics
    apply; with no arguments, jax's cluster auto-detection is used.
    """
    global _initialized
    import jax

    if _initialized:
        log_warning("init_cluster called twice; ignoring")
        return

    if config is not None and config.machines and num_processes is None:
        machines = parse_machine_list(config.machines)
        if config.num_machines > 1 and len(machines) != config.num_machines:
            log_fatal(f"machines lists {len(machines)} hosts but "
                      f"num_machines={config.num_machines}")
        coordinator_address = machines[0]
        num_processes = len(machines)
        process_id = discover_rank(machines)
        if process_id is None:
            log_fatal("Could not find the local machine in the machines "
                      "list (reference rank discovery failed)")

    kw = {}
    if config is not None and config.time_out > 0:
        # reference: network time_out is in MINUTES (config.h:692); it bounds
        # the socket-linker connect phase, here the coordinator barrier
        kw["initialization_timeout"] = config.time_out * 60
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kw,
    )
    _initialized = True
    log_info(
        f"Cluster initialized: process {jax.process_index()} of "
        f"{jax.process_count()}, {jax.local_device_count()} local / "
        f"{jax.device_count()} global devices")
