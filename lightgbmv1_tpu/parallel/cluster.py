"""Multi-host (multi-process) cluster initialization.

TPU-native replacement for the reference's Network/Linkers bring-up
(reference: ``Network::Init`` src/network/network.cpp:30, socket linker
``src/network/linkers_socket.cpp`` — machine-list parsing, rank discovery,
TCP mesh connect; MPI linker ``linkers_mpi.cpp``).  Here the transport is
jax.distributed's gRPC coordination service + the XLA runtime's ICI/DCN
collectives; after ``init_cluster`` the data/feature/voting-parallel
learners in ``trainer.py`` span every process's devices through the SAME
shard_map code path (``jax.devices()`` becomes the global device list).

Configuration mirrors the reference's network parameters:

* ``machines``       — comma-separated ``host:port`` list; the first entry
  is the coordinator (reference: config.h machines / machine_list_filename)
* ``num_machines``   — world size
* ``machine_rank``   — this process's rank; when absent it is discovered by
  matching a local interface address against ``machines``, exactly like the
  socket linker's rank discovery.

Standard cluster launchers (SLURM, Cloud TPU pods) are auto-detected by
``jax.distributed.initialize()`` when no explicit arguments are given.
"""

from __future__ import annotations

import random
import socket
import time
from typing import List, Optional

from ..config import Config
from ..utils.log import log_fatal, log_info, log_warning

_initialized = False


def find_free_port(host: str = "127.0.0.1") -> int:
    """Ask the kernel for a currently-free TCP port.  The port is
    released before returning, so callers that hand it to a coordinator
    must be prepared for the (rare) collision where another process
    grabs it first — pair with :func:`init_cluster`'s bootstrap retry
    or re-allocate on failure (tests/test_multihost.py does both)."""
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return int(s.getsockname()[1])


def enable_cpu_collectives() -> bool:
    """Turn on cross-process collectives for the CPU backend (gloo).

    jax's CPU backend ships with collectives DISABLED: a 2-process
    ``jax.distributed`` run bootstraps fine and then every multiprocess
    computation dies with "Multiprocess computations aren't implemented
    on the CPU backend".  The gloo implementation (when this jaxlib
    carries it) makes the 2-process CPU harness — the multihost tests,
    the elastic-recovery chaos scenario — actually run the collectives
    instead of hanging or failing.  Returns True when the option was
    available (already-gloo counts); False on jax builds without it.
    No-op for TPU/GPU backends (the flag only affects CPU clients)."""
    import jax

    flag = "jax_cpu_collectives_implementation"
    values = getattr(jax.config, "values", {})
    if flag not in values:
        return False
    try:
        if values.get(flag) in (None, "", "none"):
            jax.config.update(flag, "gloo")
        return True
    except Exception as e:  # noqa: BLE001 — backend already initialized
        log_warning(f"cluster: could not enable CPU collectives ({e}); "
                    "multiprocess CPU computations may fail")
        return False


def cpu_multiprocess_supported() -> bool:
    """Cheap capability probe: does this jax build carry a CPU
    cross-process collectives implementation at all?  (Bootstrap
    succeeding proves only the gRPC coordination service; the first
    psum needs gloo.)"""
    import jax

    return "jax_cpu_collectives_implementation" in getattr(
        jax.config, "values", {})


def _local_addresses() -> List[str]:
    addrs = {"127.0.0.1", "localhost"}
    try:
        hostname = socket.gethostname()
        addrs.add(hostname)
        addrs.add(socket.gethostbyname(hostname))
    except OSError:
        pass
    return list(addrs)


def parse_machine_list(spec: str) -> List[str]:
    """reference: socket linker machine-list parsing (machines config or
    mlist file contents, one host:port per entry)."""
    entries = [m.strip() for m in spec.replace("\n", ",").split(",")]
    return [m for m in entries if m]


def discover_rank(machines: List[str]) -> Optional[int]:
    """Find this process's rank by local address match; multiple local
    entries (several processes on one host) are disambiguated by port
    bindability — the same trick the reference socket linker uses
    (linkers_socket.cpp binds local_listen_port to claim a rank)."""
    local = set(_local_addresses())
    candidates = []
    for i, m in enumerate(machines):
        host, _, port = m.rpartition(":")
        if (host or m) in local:
            candidates.append((i, int(port) if port.isdigit() else 0))
    if len(candidates) == 1:
        return candidates[0][0]
    for i, port in candidates:
        if port <= 0:
            continue
        try:
            with socket.socket() as s:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("0.0.0.0", port))
            return i
        except OSError:
            continue
    return candidates[0][0] if candidates else None


def init_cluster(
    config: Optional[Config] = None,
    *,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    bootstrap_retries: int = 3,
    bootstrap_backoff_s: float = 0.5,
) -> None:
    """Initialize jax.distributed so a process-spanning Mesh is available.

    Call once per process before building any trainer.  With a ``Config``
    carrying ``machines``/``num_machines`` the reference CLI semantics
    apply; with no arguments, jax's cluster auto-detection is used.

    The coordinator bootstrap is retried ``bootstrap_retries`` times with
    deterministic jittered exponential backoff (seeded per (rank,
    attempt)): a coordinator that is a beat late to bind, or an
    ephemeral-port collision on a busy CI host, costs a retry instead of
    the whole run — the reference's socket linker spins the same way
    inside its ``time_out`` window (linkers_socket.cpp TryBind/Connect
    loops).
    """
    global _initialized
    import jax

    if _initialized:
        log_warning("init_cluster called twice; ignoring")
        return

    if config is not None and config.machines and num_processes is None:
        machines = parse_machine_list(config.machines)
        if config.num_machines > 1 and len(machines) != config.num_machines:
            log_fatal(f"machines lists {len(machines)} hosts but "
                      f"num_machines={config.num_machines}")
        coordinator_address = machines[0]
        num_processes = len(machines)
        process_id = discover_rank(machines)
        if process_id is None:
            log_fatal("Could not find the local machine in the machines "
                      "list (reference rank discovery failed)")

    kw = {}
    if config is not None and config.time_out > 0:
        # reference: network time_out is in MINUTES (config.h:692); it bounds
        # the socket-linker connect phase, here the coordinator barrier
        kw["initialization_timeout"] = config.time_out * 60
    # the CPU backend needs gloo for any cross-process computation; set
    # it BEFORE the first backend touch (no-op on TPU/GPU)
    enable_cpu_collectives()
    attempts = max(int(bootstrap_retries), 1)
    for attempt in range(attempts):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kw,
            )
            break
        except Exception as e:  # noqa: BLE001 — barrier timeout / bind race
            if attempt + 1 >= attempts:
                raise
            jitter = random.Random(
                (process_id or 0) * 1_000_003 + attempt).random()
            delay = bootstrap_backoff_s * (2 ** attempt) * (1.0 + jitter)
            log_warning(
                f"cluster: bootstrap attempt {attempt + 1}/{attempts} "
                f"failed ({type(e).__name__}: {e}); retrying in "
                f"{delay:.2f}s")
            time.sleep(delay)
    _initialized = True
    log_info(
        f"Cluster initialized: process {jax.process_index()} of "
        f"{jax.process_count()}, {jax.local_device_count()} local / "
        f"{jax.device_count()} global devices")


def make_mesh(num_shards: int, axis: str):
    """One-axis device mesh for the distributed learners (trainer.py).
    ``num_shards == 0`` spans every visible device — the reference's
    ``num_machines`` world-size role, with XLA's ICI/DCN collectives in
    place of the socket/MPI linkers."""
    import numpy as np
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    n = num_shards if num_shards > 0 else len(devices)
    if n > len(devices):
        log_fatal(f"num_shards={n} exceeds available devices "
                  f"({len(devices)})")
    return Mesh(np.array(devices[:n]), (axis,))


# ---------------------------------------------------------------------------
# Analytic comm accounting (the measurement role the reference's Network
# layer plays implicitly through its buffer sizes, src/network/network.cpp).
#
# Convention: every figure is the OUTPUT PAYLOAD a collective materializes
# per device — the array bytes each chip must end up holding, computed
# exactly from shapes + dtypes.  This is the quantity the learner design
# controls (an allreduced histogram lands F*B*3 values on every chip; a
# reduce-scattered one lands F/D of that) and is proportional to, not equal
# to, the wire traffic of any particular ring/tree lowering.  The trainer
# logs a table per learner at build time, tools/dryrun_multichip records it
# into the MULTICHIP record, and tools/perf_report.py renders it in
# PERF.md's "Cross-chip comms" section.
# ---------------------------------------------------------------------------

HIST_CH = 3             # [sum_grad, sum_hess, count] channels per bin
F32 = 4                 # bytes; int32 (the int8sr integer domain) matches


def split_pack_floats(num_bins: int) -> int:
    """f32 words of one packed SplitInfo on the wire (trainer._pack_split):
    [gain, feature, threshold, default_left, is_cat] + left/right (3,)
    sums + the categorical bitset words."""
    return 11 + (-(-num_bins // 32))


def collective_bytes(n_elems: int, ndev: int, kind: str,
                     itemsize: int = F32) -> int:
    """Payload bytes per device of one collective over ``ndev`` devices."""
    if ndev <= 1:
        return 0
    if kind == "psum":                # allreduce: full array everywhere
        return n_elems * itemsize
    if kind == "psum_scatter":        # each device keeps its 1/D slice
        return (n_elems // ndev) * itemsize
    if kind == "all_gather":          # per-device contribution times D
        return n_elems * ndev * itemsize
    raise ValueError(f"unknown collective kind: {kind}")


def comm_table_per_round(learner: str, collective: str, *, k: float,
                         F: int, B: int, ndev: int,
                         sel_k: Optional[int] = None,
                         int8sr: bool = False) -> dict:
    """Per-ROUND comm bytes of one wave round with ``k`` splits (smaller-
    child subtraction: k histogram slots cross the wire, 2k children are
    searched), by collective:

    * ``hist_bytes``       — the histogram reduction (psum of
      (k, F, B, 3) under "allreduce"; psum_scatter of the F-padded array
      under "reduce_scatter", where each chip keeps ceil(F/D) features).
    * ``split_sync_bytes`` — the SplitInfo sync: 2k children x an
      all_gather of one packed SplitInfo per device ("reduce_scatter" and
      the feature-parallel learner; zero under "allreduce", where split
      selection is replicated).
    * ``vote_bytes``       — voting learner only: the GlobalVoting psum
      of (F,) vote counts per child.
    * ``g3_bytes_per_tree``— the root grad/hess/count totals psum, once
      per tree (not per round).

    ``int8sr`` flags rounds whose histograms cross as raw int32
    (ops/quantize.py global-scale quantization) — same 4-byte elements,
    recorded in ``hist_dtype`` because integer summation is also
    reduction-order exact.
    """
    F_pad = -(-F // ndev) * ndev
    spf = split_pack_floats(B)
    sync = collective_bytes(int(round(2 * k)) * spf, ndev, "all_gather")
    out = {"g3_bytes_per_tree": collective_bytes(HIST_CH, ndev, "psum"),
           "hist_dtype": "int32" if int8sr else "float32"}
    if learner == "feature":
        # histograms are feature-local by construction; only SplitInfo
        # crosses chips (SyncUpGlobalBestSplit)
        out.update(hist_bytes=0, split_sync_bytes=sync)
    elif learner == "voting":
        nsel = sel_k if sel_k is not None else F
        vote = collective_bytes(int(round(2 * k)) * F, ndev, "psum")
        if collective == "reduce_scatter":
            nsel_pad = -(-nsel // ndev) * ndev
            hist = collective_bytes(
                int(round(2 * k)) * nsel_pad * B * HIST_CH, ndev,
                "psum_scatter")
            out.update(hist_bytes=hist, split_sync_bytes=sync,
                       vote_bytes=vote)
        else:
            hist = collective_bytes(
                int(round(2 * k)) * nsel * B * HIST_CH, ndev, "psum")
            out.update(hist_bytes=hist, split_sync_bytes=0,
                       vote_bytes=vote)
    elif collective == "reduce_scatter":
        hist = collective_bytes(
            int(round(k)) * F_pad * B * HIST_CH, ndev, "psum_scatter")
        out.update(hist_bytes=hist, split_sync_bytes=sync)
    else:
        hist = collective_bytes(
            int(round(k)) * F * B * HIST_CH, ndev, "psum")
        out.update(hist_bytes=hist, split_sync_bytes=0)
    out["total_bytes"] = (out["hist_bytes"] + out["split_sync_bytes"]
                          + out.get("vote_bytes", 0))
    return out


def publish_comm_metrics(learner: str, table: dict) -> None:
    """Publish one learner's analytic per-round comm table into the
    unified obs registry (gauges labeled ``{learner, part}``) — the same
    numbers the trainer logs at build and dryrun_multichip records, now
    scrapeable from ``GET /metrics`` alongside everything else."""
    from ..obs.metrics import default_registry

    g = default_registry().gauge(
        "comm_bytes_per_round",
        "Analytic per-device collective payload per wave round",
        label_names=("learner", "part"))
    for part in ("hist_bytes", "split_sync_bytes", "vote_bytes",
                 "total_bytes"):
        if table.get(part) is not None:
            g.labels(learner=learner,
                     part=part[:-6]).set(float(table[part]))


def predict_comm_table(n_rows: int, num_features: int, ndev: int, *,
                       itemsize: int = 4, K: int = 1,
                       bytes_per_row: Optional[int] = None) -> dict:
    """Per-device payloads of one row-sharded predict batch (the serving
    analog of ``comm_table_per_round``): inference is embarrassingly
    parallel — NO collective runs at all — so the only traffic is the H2D
    of each chip's row shard (``itemsize`` 1 for uint8 serving codes, 2
    for uint16, 4 for raw f32 — the prebinned path's 4x HBM shrink shows
    up here) and the D2H of its (rows, K) scores.  ``bytes_per_row``
    overrides the ``num_features * itemsize`` product for transports no
    integer itemsize expresses — the 4-bit packed serving codes ship
    ``ceil(F / 2)`` bytes per row (BatchPredictor.h2d_bytes(1)).
    Recorded into the MULTICHIP record by tools/dryrun_multichip."""
    rows = -(-int(n_rows) // max(int(ndev), 1))
    per_row = (int(bytes_per_row) if bytes_per_row is not None
               else int(num_features) * int(itemsize))
    return {
        "h2d_bytes": rows * per_row,
        "d2h_bytes": rows * int(K) * 4,
        "collective_bytes": 0,
    }


def comm_guard_ok(rs_hist_bytes: float, allreduce_hist_bytes: float,
                  ndev: int) -> bool:
    """The comm-bytes regression guard (tools/dryrun_multichip -> MULTICHIP
    record ``comm_ok``): the reduce-scatter histogram path must beat the
    recorded allreduce bytes by essentially the full D-fold —
    ``rs <= allreduce / (D * 0.9)`` — so a silent fallback to a
    full-width reduction (or an accidental allgather of the scattered
    slices) trips the guard instead of hiding in the record."""
    if ndev <= 1:
        return True
    return rs_hist_bytes <= allreduce_hist_bytes / (ndev * 0.9)


# ---------------------------------------------------------------------------
# Pod-scale topology (ISSUE 16): the flat one-axis mesh treats every link
# as equal, but a real pod has two very different links — intra-host ICI
# (fast) and inter-host DCN (an order of magnitude slower).  The
# hierarchical collective reduce-scatters over the ICI axis FIRST so only
# the F/D-sliced partials ever cross DCN, and the voting learner's top-2k
# election additionally compresses WHAT crosses.  This block provides the
# (host, chip) mesh and the per-level analytic pricing the trainer logs,
# dryrun_multichip records, and tools/perf_report.py renders as the
# "Pod-scale comms" section.
# ---------------------------------------------------------------------------

# Per-level bandwidth terms for the analytic ms estimates (GB/s per
# device-link, order-of-magnitude constants: TPU-generation ICI links run
# ~O(100 GB/s) while inter-host DCN NICs run ~O(10 GB/s) — the exact
# ratio varies by platform; what the model needs is the ~10x gap that
# makes the flat collective DCN-priced).  These are only the DEFAULTS of
# the validated config knobs ``hier_ici_gbps`` / ``hier_dcn_gbps``
# (config.py) — the trainer threads the config values into
# hier_comm_table_per_round, so a pod capture calibrates the modeled-ms
# column from measured per-round ms without a code change.  The knobs
# are observational: byte columns (and hence the hier_comm_ok guard,
# which compares bytes, not ms) never depend on them.
ICI_GBPS = 100.0
DCN_GBPS = 10.0


def hier_axis_sizes(ndev: int, num_hosts: int = 0):
    """Resolve ``(num_hosts, chips_per_host)`` for a ``ndev``-device
    fleet.  ``num_hosts == 0`` auto-detects: the real process count in a
    multi-process run, else 1 (a single host has no DCN level).  A fleet
    that does not divide evenly into hosts is a config error — the
    two-level mesh must be rectangular."""
    import jax

    H = int(num_hosts)
    if H <= 0:
        H = jax.process_count() if jax.process_count() > 1 else 1
    if ndev % H != 0:
        log_fatal(f"hierarchical mesh: {ndev} devices do not divide "
                  f"into num_hosts={H} equal hosts")
    return H, ndev // H


def make_hier_mesh(num_shards: int, num_hosts: int = 0,
                   axes=("host", "chip")):
    """Two-axis ``(host, chip)`` mesh with process identity.  In a real
    multi-process run ``jax.devices()`` is process-major, so reshaping to
    ``(H, C)`` puts each process's devices on one "host" row and the
    "chip" axis never crosses a process boundary; a single-process run
    (the 8-virtual-device test rig) models the same topology by grouping
    contiguous blocks of C devices into virtual hosts."""
    import numpy as np
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    n = num_shards if num_shards > 0 else len(devices)
    if n > len(devices):
        log_fatal(f"num_shards={n} exceeds available devices "
                  f"({len(devices)})")
    H, C = hier_axis_sizes(n, num_hosts)
    if jax.process_count() > 1:
        # each host row must be process-pure: the ICI axis may never
        # cross a process (= host) boundary
        procs = [d.process_index for d in devices[:n]]
        for h in range(H):
            row = set(procs[h * C:(h + 1) * C])
            if len(row) > 1:
                log_fatal(f"hierarchical mesh: host row {h} spans "
                          f"processes {sorted(row)} — device list is not "
                          "process-major or num_hosts mismatches the "
                          "process count")
    return Mesh(np.array(devices[:n]).reshape(H, C), axes)


def wire_bytes(n_elems: int, n: int, kind: str, itemsize: int = F32) -> int:
    """Ring SEND bytes per device of one collective over ``n`` devices —
    the per-LEVEL convention of the hierarchical table, distinct from
    :func:`collective_bytes`'s output-payload convention.  The
    distinction is load-bearing: a hierarchical reduce-scatter's DCN
    OUTPUT payload mathematically equals the flat reduce-scatter's
    (both end holding M/D elements per device), so output payload
    cannot express what the topology changes — the traffic on each
    link class can.  Ring lowerings: reduce-scatter of M elements sends
    M*(n-1)/n per device, allreduce 2*M*(n-1)/n, all-gather of a
    per-device M-element chunk sends M*(n-1)."""
    if n <= 1:
        return 0
    if kind == "reduce_scatter":
        return (n_elems * (n - 1) // n) * itemsize
    if kind == "allreduce":
        return (2 * n_elems * (n - 1) // n) * itemsize
    if kind == "all_gather":
        return n_elems * (n - 1) * itemsize
    raise ValueError(f"unknown collective kind: {kind}")


def hier_comm_table_per_round(learner: str, *, k: float, F: int, B: int,
                              ndev: int, num_hosts: int,
                              sel_k: Optional[int] = None,
                              int8sr: bool = False,
                              ici_gbps: float = ICI_GBPS,
                              dcn_gbps: float = DCN_GBPS) -> dict:
    """Per-round comm table of the two-level hierarchical collective,
    split by level (``ici`` / ``dcn``), in the per-level ring SEND-byte
    convention of :func:`wire_bytes`.

    Structure per round (k splits, subtraction trick — k slots cross):

    * histogram — intra-host reduce-scatter of the full (k, F_pad, B, 3)
      stack over the C-chip ICI axis, then inter-host reduce-scatter of
      the surviving 1/C slice over the H-host DCN axis: only
      ``M/C * (H-1)/H`` bytes ever cross the slow link, vs the flat
      single-level ring's ``M * (D-1)/D`` (recorded as
      ``flat_hist_wire_bytes`` — the guard denominator).
    * votes — voting learner only: the (2k, F) election psum crosses
      BOTH levels at full width (it is the payload that buys the
      selective reduce, and it is priced here — satellite: the vote
      vector must never ride uncounted).
    * split sync — the packed-SplitInfo all-gather, chip level then host
      level of the concatenated chip row.

    The analytic ms terms price each level at its own bandwidth, and the
    flat baseline at DCN speed (a flat ring's slowest hop is a DCN hop,
    which is exactly why the hierarchy pays): ``hier_ms`` vs ``flat_ms``
    is the modeled speedup the MULTICHIP record carries.
    """
    H, C = max(int(num_hosts), 1), ndev // max(int(num_hosts), 1)
    spf = split_pack_floats(B)
    n2k = int(round(2 * k))
    if learner == "voting":
        nsel = sel_k if sel_k is not None else F
        nsel_pad = -(-nsel // ndev) * ndev
        M = n2k * nsel_pad * B * HIST_CH
        vote_elems = n2k * F
    else:
        F_pad = -(-F // ndev) * ndev
        M = int(round(k)) * F_pad * B * HIST_CH
        vote_elems = 0
    sync_elems = n2k * spf
    ici = {
        "hist_bytes": wire_bytes(M, C, "reduce_scatter"),
        "split_sync_bytes": wire_bytes(sync_elems, C, "all_gather"),
        "vote_bytes": wire_bytes(vote_elems, C, "allreduce"),
    }
    dcn = {
        "hist_bytes": wire_bytes(M // max(C, 1), H, "reduce_scatter"),
        "split_sync_bytes": wire_bytes(sync_elems * C, H, "all_gather"),
        "vote_bytes": wire_bytes(vote_elems, H, "allreduce"),
    }
    for level in (ici, dcn):
        level["total_bytes"] = (level["hist_bytes"]
                                + level["split_sync_bytes"]
                                + level["vote_bytes"])
    flat_hist = wire_bytes(M, ndev, "reduce_scatter")
    giga = 1e9
    ici_ms = ici["total_bytes"] / (ici_gbps * giga) * 1e3
    dcn_ms = dcn["total_bytes"] / (dcn_gbps * giga) * 1e3
    flat_ms = (flat_hist + wire_bytes(sync_elems, ndev, "all_gather")
               + wire_bytes(vote_elems, ndev, "allreduce")) \
        / (dcn_gbps * giga) * 1e3
    return {
        "num_hosts": H, "chips_per_host": C,
        "hist_dtype": "int32" if int8sr else "float32",
        "ici": ici, "dcn": dcn,
        "flat_hist_wire_bytes": flat_hist,
        "ici_ms": ici_ms, "dcn_ms": dcn_ms,
        "hier_ms": ici_ms + dcn_ms, "flat_ms": flat_ms,
    }


def hier_comm_ok(dcn_hist_bytes: float, flat_hist_bytes: float,
                 num_hosts: int,
                 vote_bound_bytes: Optional[float] = None) -> bool:
    """The pod-scale comm guard (``hier_comm_ok`` in the MULTICHIP record,
    required by ``tools/ci_gate.py --require-guards``): the hierarchical
    collective's DCN histogram bytes must be <= the flat reduce-scatter
    wire bytes / num_hosts — i.e. the ICI pre-reduction must actually
    shrink what crosses the slow link by at least the host fan-in.  The
    voting learner additionally passes its top-2k analytic bound (the
    elected-features slice): exceeding it means the selective reduce
    silently widened to all features."""
    if num_hosts <= 1:
        return True
    ok = dcn_hist_bytes <= flat_hist_bytes / num_hosts
    if vote_bound_bytes is not None:
        ok = ok and dcn_hist_bytes <= vote_bound_bytes
    return ok


def publish_hier_comm_metrics(learner: str, table: dict) -> None:
    """Publish the per-level hierarchical comm table as gauges labeled
    ``{learner, level, part}`` — the pod-scale sibling of
    :func:`publish_comm_metrics`."""
    from ..obs.metrics import default_registry

    g = default_registry().gauge(
        "hier_comm_bytes_per_round",
        "Analytic per-device ring send bytes per wave round, by level",
        label_names=("learner", "level", "part"))
    for level in ("ici", "dcn"):
        for part in ("hist_bytes", "split_sync_bytes", "vote_bytes",
                     "total_bytes"):
            g.labels(learner=learner, level=level,
                     part=part[:-6]).set(float(table[level][part]))
