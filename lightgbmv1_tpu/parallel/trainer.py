"""Distributed tree learners over a jax.sharding Mesh.

TPU-native re-design of the reference's parallel tree learners and network
stack:

* ``tree_learner=data``  — DataParallelTreeLearner
  (reference: src/treelearner/data_parallel_tree_learner.cpp): rows are
  sharded over the ``data`` mesh axis; each device builds local histograms
  and — faithfully to the reference now — a ``lax.psum_scatter`` over the
  feature axis replaces its ReduceScatter of histogram blocks
  (``FindBestSplits`` :155-173, ``HistogramSumReducer`` bin.h:44-57): each
  device reduces and KEEPS only its ``F/D`` feature slice, searches its
  local best split there, and an all_gather + deterministic-tie-break
  argmax over packed SplitInfo (``SyncUpGlobalBestSplit``,
  parallel_tree_learner.h:190-213) elects the winner — so only split
  metadata, never histograms, crosses chips after the reduce, cutting
  histogram comm payload ~D-fold per round.  Under
  ``hist_dtype_deep=int8sr`` the reduce runs on raw int32 histograms
  (global-scale quantization, ops/quantize.py) and dequantization folds
  into the now-local split scan.  ``config.data_parallel_collective=
  "allreduce"`` keeps the previous full-histogram ``lax.psum`` (split
  selection replicated, no split sync) as the parity pin; both paths grow
  identical trees thanks to the reduction-order-invariant tie-break
  (ops/split.py tie_tol).  The root grad/hess Allreduce (:126-151) stays a
  ``psum`` of the g3 totals either way.
* ``tree_learner=feature`` — FeatureParallelTreeLearner
  (reference: src/treelearner/feature_parallel_tree_learner.cpp): every
  device holds all rows (data replicated) but builds histograms and searches
  splits only for its feature shard; the winning split is chosen by an
  ``all_gather`` of packed SplitInfo + argmax — the analog of
  ``SyncUpGlobalBestSplit``'s Allreduce-max over serialized SplitInfo pairs
  (parallel_tree_learner.h:190-213).
* ``tree_learner=voting`` — VotingParallelTreeLearner (PV-Tree)
  (reference: src/treelearner/voting_parallel_tree_learner.cpp): row-sharded
  like ``data``, but each shard votes for its local top-k features, the
  global top-2k winners are selected by a vote psum (``GlobalVoting``
  :152-180), and only those features' histograms are reduced across shards
  (``CopyLocalHistogram``) — comm drops from O(F·B) to O(2k·B) per split.
  The selective reduce rides the same sharded primitive as the data
  learner: under ``data_parallel_collective=reduce_scatter`` the selected
  features' histograms are psum_scattered so each chip keeps 2k/D of them
  and syncs only SplitInfo, and under int8sr the reduce sums the RAW
  quantized integers with one dequantize after the collective (the
  selective reduce honors the integer domain — previously only the data
  branch did; its wire dtype stays f32 because the op is shared with
  full-precision rounds, but the summed values are exact integers).  With
  ``top_k >= num_features`` it is exactly the data-parallel learner.

The socket/MPI ``Network``/``Linkers`` machinery of the reference
(src/network/) has no equivalent here by design: XLA emits the collectives
over ICI/DCN. Multi-host scaling initializes ``jax.distributed`` through
``parallel/cluster.py`` and spans the same Mesh across processes.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..models.grower import make_leafwise_grower
from ..models.grower_wave import make_wave_grower
from ..models.tree import TreeArrays
from ..obs import xla as obs_xla
from ..ops.histogram import (default_hist_method, hist_one_leaf, hist_wave,
                             hist_wave_quant)
from ..ops.split import (FeatureMeta, SplitParams, SplitResult,
                         find_best_split, leaf_gain, tie_tol)
from ..utils.log import log_fatal, log_info, log_warning
from .cluster import (comm_table_per_round, hier_comm_table_per_round,
                      make_hier_mesh, make_mesh, publish_comm_metrics,
                      publish_hier_comm_metrics)

try:  # jax >= 0.6 exposes shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore


def shard_map(*args, **kwargs):
    """shard_map across jax versions: new jax spells the replication check
    ``check_vma``, jax <= 0.4.x spells it ``check_rep`` — map the call
    rather than pinning a version (the container and the device driver
    run different jax releases)."""
    try:
        return _shard_map(*args, **kwargs)
    except TypeError:
        if "check_vma" not in kwargs:
            raise
        kwargs = dict(kwargs)
        kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)


def _make_mesh(num_shards: int, axis: str) -> Mesh:
    return make_mesh(num_shards, axis)   # parallel/cluster.py (topology home)


def shard_rows(fn, mesh: Mesh, axis: str = "rows", n_replicated: int = 0):
    """Row-shard a batch function over ``mesh``: the first
    ``n_replicated`` arguments (model tables) are replicated on every
    chip, the remaining arguments split on their leading (row) axis, and
    outputs come back row-sharded.  No collective runs at all — this is
    the embarrassingly-parallel serving layout (the reference's OMP
    row-partitioned Predictor, predictor.hpp:105-135, mapped onto chips);
    used by models/predict.BatchPredictor for sharded inference."""

    def wrapped(*args):
        in_specs = tuple([P()] * n_replicated
                         + [P(axis)] * (len(args) - n_replicated))
        sharded = shard_map(fn, mesh=mesh, in_specs=in_specs,
                            out_specs=P(axis), check_vma=False)
        return sharded(*args)

    return wrapped


def _pack_split(res: SplitResult) -> jnp.ndarray:
    """SplitInfo wire format for the cross-shard argmax (reference:
    SplitInfo::CopyTo, split_info.hpp — fixed-size serialization). The
    categorical bitset words ride along bit-exactly via a f32 bitcast."""
    bits_f32 = lax.bitcast_convert_type(res.cat_bitset, jnp.float32)
    return jnp.concatenate([
        jnp.stack([res.gain, res.feature.astype(jnp.float32),
                   res.threshold_bin.astype(jnp.float32),
                   res.default_left.astype(jnp.float32),
                   res.is_cat.astype(jnp.float32)]),
        res.left_sum, res.right_sum, bits_f32,
    ])


def _unpack_split(v: jnp.ndarray) -> SplitResult:
    return SplitResult(
        gain=v[0],
        feature=v[1].astype(jnp.int32),
        threshold_bin=v[2].astype(jnp.int32),
        default_left=v[3] > 0.5,
        left_sum=v[5:8],
        right_sum=v[8:11],
        is_cat=v[4] > 0.5,
        cat_bitset=lax.bitcast_convert_type(v[11:], jnp.uint32),
    )


def _sync_best_split(local: SplitResult, parent_sum, params: SplitParams,
                     axis) -> SplitResult:
    """Elect the global best split from per-shard locals — the reference's
    ``SyncUpGlobalBestSplit`` Allreduce-max over serialized SplitInfo
    (parallel_tree_learner.h:190-213), shared by the feature-parallel,
    reduce-scatter data-parallel and sharded voting learners.  ``axis``
    may be a tuple of mesh axes (the hierarchical ``("host", "chip")``
    mesh): the all_gather then spans both levels, major axis first, so
    the election sees every shard in device-linear order.

    The winner must be DEVICE-COUNT-INVARIANT: gains carry f32
    reduction-order noise, so candidates within ``tie_tol`` of the best
    (ops/split.py — the same band the per-shard search used internally)
    are tied and the LOWEST FEATURE ID wins, matching the serial search's
    first-feature-in-band rule exactly (SplitInfo::operator> tie-break,
    split_info.hpp:147-152)."""
    packed = _pack_split(local)
    allp = lax.all_gather(packed, axis)            # (ndev, 11 + W)
    g = allp[:, 0]
    m = jnp.max(g)
    scale = leaf_gain(parent_sum[0], parent_sum[1], params)
    in_band = g >= m - tie_tol(m, scale)
    feat = jnp.where(in_band, allp[:, 1], jnp.inf)
    return _unpack_split(allp[jnp.argmin(feat)])


def parse_interaction_constraints(spec, num_features: int):
    """'[0,1,2],[2,3]' -> (G, F) bool group matrix, or None when unset
    (reference: config.h:517 interaction_constraints,
    Config::Set -> interaction_constraints_vector)."""
    import re

    if not spec:
        return None
    groups = []
    for m in re.findall(r"\[([\d,\s]*)\]", str(spec)):
        idx = [int(x) for x in m.replace(",", " ").split()]
        row = np.zeros(num_features, bool)
        row[[i for i in idx if i < num_features]] = True
        groups.append(row)
    if not groups:
        return None
    return np.stack(groups)


def _cegb_lazy(config: Config, num_features: int, learner: str,
               levelwise: bool):
    """cegb_penalty_feature_lazy validated -> (F,) np array or None.
    Implemented by the masked sequential leaf-wise grower (per-row marks);
    other learners/growth orders warn and drop it, like the reference's
    serial-learner-only CEGB."""
    pen = config.cegb_penalty_feature_lazy
    if not pen:
        return None
    if len(pen) != num_features:
        log_fatal("cegb_penalty_feature_lazy should be the same size as "
                  f"feature number ({len(pen)} vs {num_features})")
    if learner not in ("serial", "") or levelwise:
        log_warning("cegb_penalty_feature_lazy requires the serial "
                    "leaf-wise learner; lazy feature costs are ignored for "
                    f"tree_learner={learner or 'serial'}"
                    + (", tree_growth=levelwise" if levelwise else ""))
        return None
    return np.asarray(pen, np.float64)


def _cegb_coupled(config: Config, num_features: int):
    """cegb_penalty_feature_coupled padded/validated -> (F,) or None."""
    pen = config.cegb_penalty_feature_coupled
    if not pen:
        return None
    if len(pen) != num_features:
        log_fatal("cegb_penalty_feature_coupled should be the same size as "
                  f"feature number ({len(pen)} vs {num_features})")
    return np.asarray(pen, np.float64)


def parse_forced_splits(filename: str, bin_mappers, num_leaves: int):
    """forcedsplits_filename JSON -> (S, 5) [parent_step, side, feature, bin,
    dl] in BFS order (reference: SerialTreeLearner::ForceSplits,
    serial_tree_learner.cpp:427-539; JSON format {'feature': f,
    'threshold': t, 'left': {...}, 'right': {...}}).

    Leaf ids are NOT precomputed: a forced step can be skipped at runtime
    (empty child), which shifts every later leaf index, so each entry names
    its PARENT forced step (-1 = root) and which child leaf (0 = left,
    1 = right) it splits; the grower resolves the realized leaf id from the
    tracked per-step [left, right] leaves (the analog of the reference's
    ``left_``/``right_`` queues carrying actual leaf indices)."""
    import json

    if not filename:
        return None
    from ..utils.fileio import open_file

    with open_file(filename) as fh:
        spec = json.load(fh)
    if not spec:
        return None
    out = []
    queue = [(spec, -1, 0)]
    step = 0
    while queue and step < num_leaves - 1:
        node, pstep, side = queue.pop(0)
        f = int(node["feature"])
        thr = float(node["threshold"])
        b = int(bin_mappers[f].value_to_bin(np.asarray([thr]))[0])
        dl = bool(node.get("default_left", False))
        depth = 0 if pstep < 0 else int(out[pstep][5]) + 1
        out.append([pstep, side, f, b, int(dl), depth])
        if node.get("left"):
            queue.append((node["left"], step, 0))
        if node.get("right"):
            queue.append((node["right"], step, 1))
        step += 1
    return np.asarray(out, np.int64) if out else None


def resolve_deep_dtype(requested: str, precision: str, backend: str) -> str:
    """``hist_dtype_deep`` resolution policy, one pure function so the
    tests can pin it per backend (tests/test_wave_pipeline.py).

    ``"auto"`` (ROADMAP item 3a) resolves by backend: ``int8sr`` on TPU —
    the int8 MXU path the mode was built for, with the default flip gated
    on bench.py's ``precision_expt`` AUC-parity record — and full
    ``bf16x2`` everywhere else (no int8 MXU economics off-TPU; full
    precision is the honest default there).  Opt out by setting any
    explicit dtype.  ``""`` keeps the legacy policy: bf16x2 drops to
    single-pass bf16 on sustained rounds, any other explicit
    ``hist_dtype`` is used unchanged."""
    if requested == "auto":
        requested = "int8sr" if backend == "tpu" else "bf16x2"
    return requested or ("bf16" if precision == "bf16x2" else precision)


def select_bin_layout(config: Config, *, num_total_bin: int, bin_dtype,
                      bundled: bool) -> str:
    """Resolve ``config.bin_layout`` to the device layout actually built
    (``"u8"`` or ``"packed4"``) — ONE call per GBDT build, which also
    owns the once-per-build engagement/refusal logging (the wave-loop
    logging precedent).

    Eligibility for ``packed4`` (the reference ``DenseBin<.., IS_4BIT>``
    gate, dense_bin.hpp:52): every feature fits 4 bits
    (``num_total_bin <= 16``), uint8 bins (int16-binned data exceeds the
    nibble), no EFB bundling (bundle offsets address byte bins), a
    pallas-family hist method (scatter/onehot gathers address unpacked
    bins), ``tree_learner != "feature"`` (feature shards split the byte
    pairing), and not ``gpu_use_dp`` (an explicit request for the widest
    histogram datapath; packing narrows the read stream — dp wins, the
    int8sr precedent).  ``auto`` packs exactly when eligible, silently on
    refusal; an EXPLICIT ``packed4`` refusal logs the staged warning."""
    if config.bin_layout == "u8":
        return "u8"
    explicit = config.bin_layout == "packed4"
    method = default_hist_method(config.hist_method, bin_dtype)
    reason = ""
    if np.dtype(bin_dtype).itemsize > 1:
        reason = "int16-binned data exceeds the 4-bit nibble"
    elif num_total_bin > 16:
        reason = (f"num_total_bin={num_total_bin} needs more than 4 bits "
                  "per bin")
    elif bundled:
        reason = "EFB bundle offsets address unpacked byte bins"
    elif method != "pallas":
        reason = (f"hist method {method!r} gathers unpacked bins "
                  "(pallas-family kernels unpack nibbles in VMEM)")
    elif config.tree_learner == "feature":
        reason = ("tree_learner=feature shards features, not byte "
                  "pairs")
    elif config.gpu_use_dp:
        reason = ("gpu_use_dp requests the widest histogram datapath; "
                  "packed bins narrow the read stream")
    if reason:
        if explicit:
            log_warning(f"bin_layout=packed4: {reason}; storing u8 bins")
        return "u8"
    log_info("bin_layout=packed4: 4-bit packed bins engaged — two bins "
             "per byte, the (F, N) binned read and the streaming cache "
             "shards halve (ops/hist_pallas.pack4bit)")
    return "packed4"


def build_trainer(
    config: Config,
    binned_np: np.ndarray,           # (F, N) bins or (BF, N) EFB bundles
    meta: FeatureMeta,
    params: SplitParams,
    num_bins: int,
    bin_mappers=None,
    bundle=None,                     # io/bundle.py BundleArrays (EFB) or None
    bundle_num_bins: Optional[int] = None,   # padded bundle-space bin count
    row_sharded: bool = False,       # binned_np is THIS process's row shard
    packed: bool = False,            # binned_np is 4-bit packed (2 feat/byte)
) -> Tuple[Callable, jax.Array, int]:
    """Return ``(grow_fn, binned_device, num_data)`` for the configured
    tree_learner.  ``grow_fn(binned_device, g3, base_mask, key)`` has the
    serial grower's signature; ``binned_device`` is already placed/padded
    for the chosen topology.  With ``bundle`` set, histograms run in bundle
    space and the split search expands them back to original features
    (io/bundle.py expand_bundle_hist — the FixHistogram analog)."""
    learner = config.tree_learner
    method = default_hist_method(config.hist_method, binned_np.dtype)
    precision = config.hist_dtype
    # hist_method=bench: time the applicable implementations on the real
    # shapes and pick the winner (the reference's GetShareStates
    # col-wise/row-wise auto-benchmark, src/io/dataset.cpp:590-684);
    # hist_method=auto measures only when the static choice is genuinely
    # ambiguous (uint8 bins on a device with a very wide feature axis,
    # where pallas-vs-onehot tiling economics flip) so the common paths
    # keep zero startup cost.  Multi-process runs always take the static
    # pick: per-host wall-clock timing could choose DIFFERENT programs
    # around the same collectives (the reference makes one GetShareStates
    # decision, not one per rank).
    wants_bench = config.hist_method == "bench" or (
        config.hist_method == "auto"
        and jax.default_backend() != "cpu"
        and np.dtype(binned_np.dtype).itemsize == 1
        and binned_np.shape[0] > 256)
    if wants_bench and jax.process_count() > 1:
        log_warning("hist_method=bench: multi-process run takes the "
                    "static method pick (a per-host timed choice could "
                    "diverge across ranks)")
        wants_bench = False
    if wants_bench:
        from ..ops.histogram import benchmark_hist_methods

        # force_col_wise/force_row_wise name a histogram build strategy
        # (config.__post_init__ maps them onto scatter/onehot for
        # hist_method=auto); an EXPLICIT bench request used to ignore
        # them — the candidate lists never contained the forced method
        # on device.  Seed the list with it so the force competes in the
        # timing (the reference fatals on such conflicts in
        # CheckParamConflict; timing the forced method keeps the
        # measured evidence on the log instead).
        forced_method = ("scatter" if config.force_col_wise
                         else "onehot" if config.force_row_wise else None)
        method = benchmark_hist_methods(
            binned_np,
            bundle_num_bins if bundle is not None else num_bins,
            precision, packed, int(meta.num_bins.shape[0]),
            must_include=(forced_method
                          if config.hist_method == "bench" else None))
    N = binned_np.shape[1]
    if row_sharded:
        if learner != "data":
            log_fatal("row-sharded datasets require tree_learner=data")
        # binned_np holds only THIS process's rows; the global row count is
        # world * R (parallel/dist_data.py make_process_sharded contract)
        N = binned_np.shape[1] * jax.process_count()
    F = int(meta.num_bins.shape[0])  # ORIGINAL feature count
    B = num_bins
    Bh = bundle_num_bins if bundle is not None else B   # histogram bin axis

    if config.device_type in ("gpu", "cuda"):
        # reference configs select the OpenCL/CUDA learners here; this
        # framework's accelerated path is the TPU/XLA backend
        log_warning(f"device_type={config.device_type}: this framework's "
                    f"device path is XLA ({jax.default_backend()} backend); "
                    "the GPU-learner role is filled by the Pallas histogram "
                    "kernel")

    from ..models.grower import make_levelwise_grower
    from ..ops.histogram import hist_frontier

    levelwise = config.tree_growth == "levelwise"

    # hist_method=pallas on the CPU backend runs the kernels through the
    # Pallas interpreter — the bit-parity lane the fused wave-round
    # kernel is pinned against (ops/wave_fused.py; the BatchPredictor
    # precedent for interpret-on-CPU)
    pallas_interpret = (method == "pallas"
                        and jax.default_backend() == "cpu")

    def local_hist(binned, g3, leaf_id, target):
        return hist_one_leaf(binned, g3, leaf_id, target, Bh,
                             method=method, precision=precision,
                             packed=packed, num_features=F,
                             interpret=pallas_interpret)

    def local_frontier(binned, g3, leaf_id, L_level):
        return hist_frontier(binned, g3, leaf_id, L_level, Bh,
                             method=method, precision=precision,
                             packed=packed, num_features=F,
                             interpret=pallas_interpret)

    # depth-adaptive wave precision: the grower flags sustained
    # (largest-bucket) rounds of big waves with deep=True — those run a
    # cheaper dtype; ramp rounds + the root pass keep full precision.
    # Default policy: bf16x2 (the default dtype) drops to single-pass bf16
    # on deep rounds — measured 1.11x end-to-end at EQUAL-or-better
    # 500-iter AUC (0.91345 vs 0.91338, tools/precision_expt.py r5); deep
    # leaves hold small aggregates, where bf16's 8-bit mantissa is ample.
    # int8 deep was measured and REJECTED (-0.007 AUC).  Any other
    # explicit hist_dtype is respected everywhere; hist_dtype_deep
    # overrides (set hist_dtype_deep=bf16x2 to force full precision).
    deep_precision = resolve_deep_dtype(config.hist_dtype_deep, precision,
                                        jax.default_backend())
    # hist_dtype_deep="int8sr": stochastic-rounded int8 histograms
    # (ops/quantize.py) — eligible wave rounds route to a separate
    # quantized pass (hist_wave_quant_fn below) instead of the plain deep
    # dtype; any residual deep=True call keeps full precision.  The mode
    # is structurally incompatible with gpu_use_dp (an explicit request
    # for the HIGHEST histogram precision): dp wins, with a warning.
    use_int8sr = deep_precision == "int8sr"
    if use_int8sr and config.gpu_use_dp:
        log_warning("hist_dtype_deep=int8sr conflicts with gpu_use_dp "
                    "(double-precision histograms requested); int8sr "
                    "disabled, deep rounds run f32")
        use_int8sr = False
        deep_precision = "f32"
    elif use_int8sr:
        deep_precision = precision

    def local_wave(binned, g3, label, nslots, deep=False):
        return hist_wave(binned, g3, label, nslots, Bh,
                         method=method,
                         precision=deep_precision if deep else precision,
                         packed=packed, num_features=F,
                         interpret=pallas_interpret)

    def local_wave_quant(binned, g3, label, nslots, key, axis_name=None):
        # axis_name: row-sharded learners pass their mesh axis so the
        # quantization scale is pmax'd globally and shard histograms are
        # summable in the raw integer domain (ops/quantize.py)
        return hist_wave_quant(binned, g3, label, nslots, Bh, key,
                               method=method, packed=packed,
                               num_features=F, axis_name=axis_name,
                               interpret=pallas_interpret)

    # EFB: split search + decisions speak ORIGINAL features; only the
    # histogram pass runs over bundle columns
    if bundle is not None:
        from ..io.bundle import bundle_bins_of_feat, expand_bundle_hist

        def split_bundle(hist, parent, mask, key, uid, constraint, depth,
                         parent_output, cegb_pen=None):
            h = expand_bundle_hist(hist, parent, bundle, B)
            rk = jax.random.fold_in(key,
                                    uid + 1_000_003 + params.extra_seed) \
                if params.extra_trees else None
            return find_best_split(h, parent, meta, mask, params,
                                   constraint, depth,
                                   config.monotone_penalty, parent_output,
                                   rk, cegb_pen)

        split_local = split_bundle

        def bins_feat_fn(binned, f):
            return bundle_bins_of_feat(binned, f, bundle)
    elif packed:
        # 4-bit packed bins: decisions decode the nibble of their feature
        # (reference DenseBin<.., IS_4BIT>::data access, dense_bin.hpp:425)
        from ..ops.hist_pallas import packed_bins_of_feat

        split_local = None
        bins_feat_fn = packed_bins_of_feat
    else:
        split_local = None
        bins_feat_fn = None

    # the wave-batched best-first schedule is the leaf-wise default; CEGB
    # needs the sequential grower's exact split ORDER (its penalties depend
    # on the features used by earlier splits of the same tree), and forced
    # splits occupy the first steps of the sequential order
    use_cegb = (config.cegb_tradeoff * config.cegb_penalty_split > 0
                or bool(config.cegb_penalty_feature_coupled)
                or bool(config.cegb_penalty_feature_lazy))
    cegb_lazy = _cegb_lazy(config, F, learner, levelwise)
    wave_size = config.leafwise_wave_size
    if wave_size == 0:   # auto: batched for big trees, sequential for small.
        # num_leaves // 4 (= 63 at 255 leaves): with the smaller-child
        # subtraction pass the per-round histogram cost halved, moving the
        # measured optimum from K=32 to ~64 (PERF.md round-4 sweep).
        # Small trees (num_leaves <= 7) stay at K=1 — the reference's exact
        # sequential best-first order, which the golden parity fixtures pin.
        from ..models.grower_wave import auto_wave_size

        wave_size = auto_wave_size(config.num_leaves)
    # cap bounds the unrolled per-round decision loop's compile-time graph
    if wave_size > 128:
        log_warning(f"leafwise_wave_size={wave_size} capped to 128 (the "
                    "per-round decision pass unrolls over the wave)")
        wave_size = 128
    mono_mode = config.monotone_constraints_method or "basic"
    has_mono = bool(config.monotone_constraints) and any(
        config.monotone_constraints)
    if has_mono and mono_mode == "advanced":
        log_warning("monotone_constraints_method=advanced (slow constraint "
                    "recomputation) is approximated by 'intermediate'")
        mono_mode = "intermediate"
    # auto wave_size == 1 routes to the sequential grower (same trees,
    # compacted-segment histograms); an EXPLICIT leafwise_wave_size >= 1
    # forces the wave grower (K=1 == sequential order, used by parity
    # tests), as does intermediate-mode monotonicity (implemented there)
    wants_inter = has_mono and mono_mode == "intermediate"
    use_wave = (config.tree_growth == "leafwise"
                and not use_cegb
                and (config.leafwise_wave_size >= 1 or wave_size > 1
                     or wants_inter))
    if has_mono and mono_mode == "intermediate" and (
            not use_wave or bool(config.forcedsplits_filename)):
        # forced splits route leaf-wise growth to the sequential grower,
        # which implements basic-mode constraints only
        log_warning("monotone_constraints_method=intermediate is "
                    "implemented by the wave-batched leaf-wise grower; "
                    f"falling back to 'basic' for this configuration "
                    f"(tree_growth={config.tree_growth}"
                    + (", forced splits" if config.forcedsplits_filename
                       else "") + ")")
        mono_mode = "basic"

    common = dict(
        num_leaves=config.num_leaves,
        num_bins=B,
        meta=meta,
        params=params,
        max_depth=config.max_depth,
        feature_fraction_bynode=config.feature_fraction_bynode,
        monotone_penalty=config.monotone_penalty,
        interaction_groups=parse_interaction_constraints(
            config.interaction_constraints, F),
        cegb_coupled=_cegb_coupled(config, F),
    )
    wave_common = {k: v for k, v in common.items() if k != "cegb_coupled"}
    wave_common["wave_size"] = wave_size
    wave_common["monotone_mode"] = mono_mode
    wave_common["fused_bookkeeping"] = config.fused_bookkeeping
    wave_common["async_wave_pipeline"] = config.async_wave_pipeline
    # sequential-grower histogram pool cap (reference histogram_pool_size;
    # the wave/level growers use frontier-sized buffers and need no cap)
    lw_pool = dict(hist_pool_mb=config.histogram_pool_size, num_features=F)
    forced = None
    if config.forcedsplits_filename:
        if bin_mappers is None:
            log_warning("forcedsplits_filename requires bin mappers; ignored")
        else:
            forced = parse_forced_splits(config.forcedsplits_filename,
                                         bin_mappers, config.num_leaves)

    # ---- hist_method=fused: the wave-round megakernel dispatch ----------
    # (ops/wave_fused.py — histogram + smaller-child subtraction + split
    # scan in one Pallas invocation, histograms resident in VMEM).  The
    # static gates below are the documented fallback taxonomy; every
    # ineligible config logs its reason once and runs the staged path.
    fused_builder = None
    if config.hist_method == "fused":
        from ..ops import wave_fused

        fused_reason = wave_fused.fused_ineligible_reason(
            meta=meta, params=params, bin_dtype=binned_np.dtype,
            num_bins=B, packed=packed, bundled=bundle is not None)
        if not fused_reason and (levelwise or not use_wave
                                 or forced is not None):
            fused_reason = ("the fused kernel is a wave-round kernel; "
                            "this config routes to the "
                            + ("level-wise" if levelwise else "sequential")
                            + " grower")
        if not fused_reason and learner in ("data", "voting"):
            fused_reason = (f"tree_learner={learner} reduces histograms "
                            "across row shards (the collective needs the "
                            "explicit histogram)")
        if not fused_reason and jax.default_backend() != "cpu" \
                and not wave_fused.backend_lowers_fused():
            fused_reason = "Mosaic lowering failed (warned above)"
        if fused_reason:
            log_warning(f"hist_method=fused: {fused_reason}; running the "
                        "staged histogram+split path")
        else:
            fused_builder = wave_fused.make_fused_round
            log_info("hist_method=fused: wave rounds run the fused "
                     "histogram+split kernel with partition, valid "
                     "routing and top-k folded into the same dispatch "
                     "(ops/wave_fused.py, single-pass wave round"
                     + (", 4-bit packed bins" if packed else "")
                     + (", interpret mode"
                        if jax.default_backend() == "cpu" else "") + ")")

    if learner in ("serial", ""):
        fused_loop = None   # set by the wave branch when the loop engages
        if levelwise:
            grow = make_levelwise_grower(
                hist_frontier_fn=local_frontier, split_fn=split_local,
                bins_of_fn=bins_feat_fn, forced_splits=forced,
                **common)
        elif use_wave and forced is None:
            # wave-batched best-first: the leaf-wise default schedule
            # (models/grower_wave.py)
            fused_fn = None
            if fused_builder is not None:
                fused_fn = fused_builder(
                    meta=meta, params=params, num_bins=B,
                    precision=precision, deep_precision=deep_precision,
                    monotone_penalty=config.monotone_penalty,
                    interpret=jax.default_backend() == "cpu",
                    packed=packed)
            # ---- persistent multi-round wave loop (ROADMAP item 1) ----
            # wave_loop_rounds > 1 on the fused path: ONE Pallas launch
            # runs R consecutive rounds with the frontier state resident
            # in VMEM (ops/wave_fused.make_fused_wave_loop).  The gates
            # below are the loop's own fallback-taxonomy legs — every
            # staged leg the kernel cannot replicate in-loop (per-node
            # feature re-masking, monotone constraint propagation) and
            # the Mosaic probe, each falling back to SINGLE-ROUND fused
            # dispatch with a logged reason.  The VMEM planner runs at
            # trace time inside the grower (shape-dependent).
            fused_loop = None
            if fused_fn is not None and config.wave_loop_rounds > 1:
                from ..models import grower_wave as _gw

                loop_reason = None
                if common["interaction_groups"] is not None:
                    loop_reason = ("interaction constraints re-mask "
                                   "features per split; the loop kernel "
                                   "freezes the round-0 mask")
                elif config.feature_fraction_bynode < 1.0:
                    loop_reason = ("feature_fraction_bynode draws a "
                                   "fresh per-node mask every round")
                elif has_mono:
                    loop_reason = ("monotone constraints propagate "
                                   "child bounds between rounds outside "
                                   "the kernel")
                elif jax.default_backend() != "cpu" \
                        and not wave_fused.backend_lowers_fused_loop():
                    loop_reason = "Mosaic lowering failed (warned above)"
                if loop_reason:
                    log_warning(f"wave_loop_rounds="
                                f"{config.wave_loop_rounds}: "
                                f"{loop_reason}; running single-round "
                                "fused dispatch")
                else:
                    fused_loop = wave_fused.make_fused_wave_loop(
                        meta=meta, params=params, num_bins=B,
                        precision=precision,
                        deep_precision=deep_precision,
                        rounds=config.wave_loop_rounds,
                        monotone_penalty=config.monotone_penalty,
                        interpret=jax.default_backend() == "cpu",
                        packed=packed)
                    # replicate the grower's trace-time plan for the
                    # dispatch label / log line (shape statics only)
                    K_eff = max(1, min(wave_size,
                                       max(config.num_leaves - 1, 1)))
                    sb = _gw.slot_buckets_for(K_eff, N)
                    qb = ()
                    if use_int8sr and len(sb) > 1:
                        qb = tuple(S for S in sb
                                   if (S == K_eff and K_eff >= 32)
                                   or (S == 16 and S < K_eff))
                    use_sub_t = (config.num_leaves * F * B * 3 * 4
                                 <= _gw._SUB_STATE_CAP_BYTES)
                    plan = fused_loop.plan(
                        N=N, F=F, K=K_eff, L=config.num_leaves,
                        use_sub=use_sub_t, slot_buckets=sb,
                        quant_buckets=qb)
                    if not plan["eligible"]:
                        log_warning(f"wave_loop_rounds="
                                    f"{config.wave_loop_rounds}: "
                                    f"{plan['reason']}; running "
                                    "single-round fused dispatch")
                        fused_loop = None
                    else:
                        log_info("wave_loop_rounds="
                                 f"{plan['rounds']}: persistent "
                                 "multi-round wave loop engaged — "
                                 "frontier state resident in VMEM "
                                 f"({plan['total_bytes'] >> 10} KiB of "
                                 f"{plan['vmem_budget'] >> 20} MiB "
                                 "budget, ops/wave_fused.py"
                                 + (", interpret mode"
                                    if jax.default_backend() == "cpu"
                                    else "") + ")")
            grow = make_wave_grower(hist_wave_fn=local_wave,
                                    hist_wave_quant_fn=(
                                        local_wave_quant if use_int8sr
                                        else None),
                                    split_fn=split_local,
                                    bins_of_fn=bins_feat_fn,
                                    fused_round_fn=fused_fn,
                                    fused_loop_fn=fused_loop,
                                    **wave_common)
        else:
            # sequential best-first (the reference's exact split order):
            # DataPartition fast path by default; tree_growth=leafwise_masked
            # keeps the O(N)-per-split variant; per-row lazy feature costs
            # need the masked variant's leaf ids
            grow = make_leafwise_grower(
                hist_fn=local_hist, forced_splits=forced,
                split_fn=split_local, bins_of_fn=bins_feat_fn,
                cegb_lazy=cegb_lazy,
                partition=(config.tree_growth != "leafwise_masked"
                           and cegb_lazy is None),
                **lw_pool, **common)
        # the instrumented jit copies grow.__dict__ (the jax.jit /
        # functools.wraps contract), so the wave grower's
        # _supports_valids capability flag — valid rows routed through
        # each round's splits instead of per-tree walks — rides the
        # wrapped callable automatically; compile telemetry (obs/xla.py)
        # labels this dispatch per learner — `grow.fused_round` when the
        # fused megakernel is engaged, so compile counters, cost
        # analysis (flops / bytes accessed) and the roofline join track
        # the fused executable as its own watched row
        label = ("grow.fused_loop" if fused_loop is not None
                 else "grow.fused_round" if fused_builder is not None
                 else "grow.serial")   # gates above null the builder
                                       # whenever a non-wave grower runs
        return obs_xla.instrument_jit(grow, label), \
            jnp.asarray(binned_np), N

    if learner == "voting" and levelwise:
        log_warning("tree_learner=voting requires the leaf-wise grower; "
                    "using tree_learner=data for tree_growth=levelwise")
        learner = "data"

    if forced is not None and learner in ("voting", "feature"):
        log_warning(f"forcedsplits_filename is not supported with "
                    f"tree_learner={learner}; ignored")
        forced = None

    if learner == "voting":
        # PV-Tree voting (reference: VotingParallelTreeLearner,
        # src/treelearner/voting_parallel_tree_learner.cpp:152-310): rows are
        # sharded like the data-parallel learner, but instead of reducing the
        # full (F, B) histogram block, each shard votes for its local top-k
        # features, the global top-2k vote winners are selected
        # (GlobalVoting :152-180), and only the selected features' histograms
        # are summed across shards (CopyLocalHistogram) — comm volume drops
        # from O(F·B) to O(2k·B).
        from ..ops.split import per_feature_best_gain

        collective = config.data_parallel_collective
        hier = collective == "hierarchical"
        if hier:
            # two-level (host, chip) mesh (ISSUE 16): the vote psum and
            # the selective reduce run level-by-level so only the
            # 1/C-sliced partials cross the slow DCN axis
            mesh = make_hier_mesh(config.num_shards, config.num_hosts)
            NH, NC = (int(s) for s in mesh.devices.shape)
            row_axes = ("host", "chip")
        else:
            mesh = _make_mesh(config.num_shards, "data")
            NH = NC = 0
            row_axes = "data"
        ndev = mesh.devices.size
        N_pad = ((N + ndev - 1) // ndev) * ndev
        binned_p = np.zeros((binned_np.shape[0], N_pad),
                            dtype=binned_np.dtype)
        binned_p[:, :N] = binned_np
        binned_dev = jax.device_put(
            jnp.asarray(binned_p), NamedSharding(mesh, P(None, row_axes))
        )
        top_k = max(1, min(config.top_k, F))
        sel_k = min(2 * top_k, F)
        use_hier = hier and ndev > 1
        use_rs = (collective == "reduce_scatter" and ndev > 1) or use_hier
        sel_pad = -(-sel_k // ndev) * ndev
        sel_loc = sel_pad // ndev
        log_info(f"Voting-parallel training over {ndev} devices "
                 f"(top_k={top_k}, {sel_k} features reduced per split, "
                 f"{collective} selective reduce)")
        _comm_tbl = comm_table_per_round(
            "voting", "reduce_scatter" if hier else collective,
            k=wave_size, F=F, B=B, ndev=ndev, sel_k=sel_k,
            int8sr=use_int8sr)
        log_info("comm/round (analytic, K=%d wave): %s"
                 % (wave_size, _comm_tbl))
        # the top-2k ELECTION payload itself — the (2K, F) vote psum that
        # buys the selective reduce — is priced next to the histograms it
        # compresses (vote_bytes), never riding uncounted
        log_info("voting election payload (GlobalVoting vote psum): "
                 "%d B/round analytic, recorded as vote_bytes"
                 % _comm_tbl.get("vote_bytes", 0))
        publish_comm_metrics("voting", _comm_tbl)
        if hier:
            _hier_tbl = hier_comm_table_per_round(
                "voting", k=wave_size, F=F, B=B, ndev=ndev, num_hosts=NH,
                sel_k=sel_k, int8sr=use_int8sr,
                ici_gbps=config.hier_ici_gbps,
                dcn_gbps=config.hier_dcn_gbps)
            log_info("hier comm/round (per-level ring wire, K=%d wave): %s"
                     % (wave_size, _hier_tbl))
            publish_hier_comm_metrics("voting", _hier_tbl)

        def hist_fn(binned, g3, leaf_id, target):
            # local histogram only — the reduce happens per-split in split_fn
            # (local_hist handles 4-bit packed and bundle-space bins)
            return local_hist(binned, g3, leaf_id, target)

        def sums_fn(g3):
            return lax.psum(g3.sum(axis=0), row_axes)

        def voting_wave_quant(binned, g3, label, nslots, key):
            # global (pmax'd) scales: the selective reduce in split_fn can
            # then sum the RAW integer histograms across shards (the
            # int8sr integer-domain contract the data learner follows);
            # under the hierarchical mesh the pmax spans both levels
            return local_wave_quant(binned, g3, label, nslots, key,
                                    axis_name=row_axes)

        def split_fn(local_hist, parent, mask, key, uid, constraint, depth,
                     parent_output, cegb_pen=None, hist_scale=None):
            # ``hist_scale`` non-None marks a quantized round whose
            # histogram is still raw integers (wave grower hands custom
            # split_fns the integer stack when accepts_hist_scale is set):
            # votes are computed on a locally-dequantized view (no comm),
            # while the cross-shard selective reduce below sums the raw
            # integer values and dequantizes only after the collective
            hist_f = (local_hist if hist_scale is None
                      else local_hist * hist_scale[None, None, :])
            # local parent stats: any feature's bin sums cover the shard rows
            local_parent = hist_f[0].sum(axis=0)
            gains = per_feature_best_gain(hist_f, local_parent, meta,
                                          mask, params, parent_output)
            if cegb_pen is not None:
                # CEGB must influence WHICH features win the vote, not just
                # the final reduced search (serial-semantics parity)
                gains = jnp.where(jnp.isfinite(gains), gains - cegb_pen,
                                  gains)
            _, local_top = lax.top_k(gains, top_k)
            votes = jnp.zeros(F, jnp.float32).at[local_top].add(
                jnp.where(jnp.isfinite(gains[local_top]), 1.0, 0.0))
            votes = lax.psum(votes, row_axes)             # GlobalVoting
            # tie-break deterministically by feature index
            order_score = votes * (F + 1) - jnp.arange(F, dtype=jnp.float32)
            _, selected = lax.top_k(order_score, sel_k)   # (sel_k,)
            rk = jax.random.fold_in(key, uid + 1_000_003 + params.extra_seed) \
                if params.extra_trees else None
            # int8sr integer domain: quantized rounds reduce the RAW
            # integer values and the one dequantize multiply runs AFTER
            # the reduce (find_best_split's hist_scale fold) on the
            # reduced slice only.  Unlike the data learner's per-bucket
            # wrapper, this collective is shared by quantized and
            # full-precision rounds (hist_scale is identity on the
            # latter), so the wire dtype stays f32 — integer sums are
            # still exact (|values| << 2^24) and reduction-order-free.
            wire = local_hist[selected]                   # (sel_k, B, 3)
            if use_rs:
                # CopyLocalHistogram via the sharded primitive: each chip
                # reduces+keeps sel_k/D of the voted features, searches
                # them, and only SplitInfo crosses chips
                wire = jnp.pad(wire, ((0, sel_pad - sel_k), (0, 0), (0, 0)))
                if use_hier:
                    # two-level selective reduce: full (sel_pad, B, 3)
                    # wire rides the fast ICI ring only; the slow DCN hop
                    # carries the 1/C chip slice of the ELECTED features
                    sl = lax.psum_scatter(wire, "chip", scatter_dimension=0,
                                          tiled=True)      # (sel_pad/C,...)
                    sl = lax.psum_scatter(sl, "host", scatter_dimension=0,
                                          tiled=True)      # (sel_loc, B, 3)
                    lo = (lax.axis_index("chip") * (sel_pad // NC)
                          + lax.axis_index("host") * sel_loc)
                else:
                    sl = lax.psum_scatter(wire, "data", scatter_dimension=0,
                                          tiled=True)      # (sel_loc, B, 3)
                    lo = lax.axis_index("data") * sel_loc
                sl = sl.astype(jnp.float32)
                sel_p = jnp.pad(selected, (0, sel_pad - sel_k),
                                constant_values=F)        # F = drop slot
                mine = lax.dynamic_slice(sel_p, (lo,), (sel_loc,))
                full = jnp.zeros((F, B, 3), jnp.float32) \
                    .at[mine].set(sl, mode="drop")
                sel_mask = jnp.zeros(F, bool).at[mine].set(True, mode="drop")
                local = find_best_split(full, parent, meta, mask & sel_mask,
                                        params, constraint, depth,
                                        config.monotone_penalty,
                                        parent_output, rk, cegb_pen,
                                        hist_scale=hist_scale)
                return _sync_best_split(local, parent, params, row_axes)
            hist_sel = lax.psum(wire, row_axes).astype(jnp.float32)
            full = jnp.zeros((F, B, 3), jnp.float32).at[selected].set(hist_sel)
            sel_mask = jnp.zeros(F, bool).at[selected].set(True)
            return find_best_split(full, parent, meta, mask & sel_mask,
                                   params, constraint, depth,
                                   config.monotone_penalty, parent_output,
                                   rk, cegb_pen, hist_scale=hist_scale)

        # the wave grower must hand quantized rounds' INTEGER histograms
        # through (bundle-space hists would mix units in expand, so EFB
        # keeps the pre-dequantized path)
        split_fn.accepts_hist_scale = bundle is None

        if use_wave:
            # the wave grower's vmapped split_fn batches the vote psum and
            # the selective histogram reduce across all 2K children of a
            # round — same PV-Tree semantics, one collective round-trip
            grow = make_wave_grower(hist_wave_fn=local_wave,
                                    hist_wave_quant_fn=(
                                        voting_wave_quant if use_int8sr
                                        else None),
                                    split_fn=split_fn, sums_fn=sums_fn,
                                    bins_of_fn=bins_feat_fn, **wave_common)
        else:
            grow = make_leafwise_grower(
                hist_fn=hist_fn, split_fn=split_fn, sums_fn=sums_fn,
                bins_of_fn=bins_feat_fn, **lw_pool, **common)
        sharded = shard_map(
            grow,
            mesh=mesh,
            in_specs=(P(None, row_axes), P(row_axes, None), P(), P(), P()),
            out_specs=(
                jax.tree_util.tree_map(lambda _: P(), TreeArrays(
                    *([0] * len(TreeArrays._fields)))),
                P(row_axes),
                P(),
            ),
            check_vma=False,
        )

        def grow_fn(binned, g3, base_mask, key, cegb_used):
            pad = N_pad - N
            g3p = jnp.pad(g3, ((0, pad), (0, 0)))
            tree, leaf_id, root = sharded(binned, g3p, base_mask, key,
                                          cegb_used)
            return tree, leaf_id[:N], root

        return obs_xla.instrument_jit(grow_fn, f"grow.{learner}"), \
            binned_dev, N

    if learner == "data":
        collective = config.data_parallel_collective
        if forced is not None and collective in ("reduce_scatter",
                                                 "hierarchical"):
            # forced splits read left/right sums straight off the leaf
            # histogram (models/grower.forced_split_stats) — a shard-
            # resident slice cannot serve a forced feature outside the
            # shard, so the full-histogram path carries them
            log_warning("forcedsplits_filename requires full histograms "
                        "on every shard; data_parallel_collective falls "
                        "back to allreduce")
            collective = "allreduce"
        hier = collective == "hierarchical"
        if hier:
            # two-level (host, chip) mesh (ISSUE 16): histograms
            # reduce-scatter over the fast ICI axis first, and only the
            # 1/C-sliced partials cross the slow DCN axis
            mesh = make_hier_mesh(config.num_shards, config.num_hosts)
            NH, NC = (int(s) for s in mesh.devices.shape)
            row_axes = ("host", "chip")
        else:
            mesh = _make_mesh(config.num_shards, "data")
            NH = NC = 0
            row_axes = "data"
        ndev = mesh.devices.size
        sharding = NamedSharding(mesh, P(None, row_axes))
        if row_sharded:
            # process-local shards -> one global sharded array; no process
            # ever materializes the full matrix (the reference's per-rank
            # memory win, dataset_loader.cpp:167 + Experiments.rst:228-240)
            N_pad = N                      # already world * R, R % d == 0
            binned_dev = jax.make_array_from_process_local_data(
                sharding, binned_np)
        else:
            N_pad = ((N + ndev - 1) // ndev) * ndev
            binned_p = np.zeros((binned_np.shape[0], N_pad),
                                dtype=binned_np.dtype)
            binned_p[:, :N] = binned_np
            if jax.process_count() > 1:
                # host-replicated multi-host input: every process carries
                # the full array and contributes its addressable shards
                binned_dev = jax.make_array_from_callback(
                    binned_p.shape, sharding,
                    lambda idx: jnp.asarray(binned_p[idx]))
            else:
                binned_dev = jax.device_put(jnp.asarray(binned_p), sharding)
        use_hier = hier and ndev > 1
        use_rs = (collective == "reduce_scatter" and ndev > 1) or use_hier
        # the HISTOGRAM column axis being sharded: bundle columns under
        # EFB, original features otherwise (4-bit packed histograms are
        # already unpacked to F columns by the pallas kernel)
        FH = binned_np.shape[0] if bundle is not None else F
        FH_pad = -(-FH // ndev) * ndev
        FH_loc = FH_pad // ndev
        log_info(f"Data-parallel training over {ndev} devices "
                 f"({N_pad // ndev} rows/device, "
                 f"{jax.process_count()} processes, {collective} collective"
                 + (", process-sharded storage" if row_sharded else "")
                 + ")")
        _comm_tbl = comm_table_per_round(
            "data", "reduce_scatter" if hier else collective, k=wave_size,
            F=FH, B=Bh, ndev=ndev, int8sr=use_int8sr)
        log_info("comm/round (analytic, K=%d wave): %s"
                 % (wave_size, _comm_tbl))
        publish_comm_metrics("data", _comm_tbl)
        if hier:
            _hier_tbl = hier_comm_table_per_round(
                "data", k=wave_size, F=FH, B=Bh, ndev=ndev, num_hosts=NH,
                int8sr=use_int8sr,
                ici_gbps=config.hier_ici_gbps,
                dcn_gbps=config.hier_dcn_gbps)
            log_info("hier comm/round (per-level ring wire, K=%d wave): %s"
                     % (wave_size, _hier_tbl))
            publish_hier_comm_metrics("data", _hier_tbl)

        def _scatter_keep(h, int_domain=False):
            """The reference's ReduceScatter of histogram blocks
            (data_parallel_tree_learner.cpp:155-173): reduce over the
            row shards, each device KEEPING only its FH_loc-column
            feature slice.  The slice is placed at its offset of a
            zeros-elsewhere full-width array so every downstream shape
            (leaf_hist state, subtraction, split scan) is unchanged; the
            allgather the old psum implied is replaced by the SplitInfo
            sync in _split_sharded.  ``int_domain``: quantized rounds
            cross the wire as raw int32 (exact, order-invariant sums;
            ops/quantize.py global scales make shard partials
            commensurable)."""
            nb = h.ndim - 3                   # leading slot axes (0 or 1)
            hp = jnp.pad(h, [(0, 0)] * nb
                         + [(0, FH_pad - FH), (0, 0), (0, 0)])
            if int_domain:
                hp = hp.astype(jnp.int32)
            if use_hier:
                # level 1 (ICI): the full FH_pad block rides the fast
                # intra-host ring; level 2 (DCN): only the FH_pad/C chip
                # slice crosses hosts — 1/C of the flat wire volume
                sl = lax.psum_scatter(hp, "chip", scatter_dimension=nb,
                                      tiled=True)
                sl = lax.psum_scatter(sl, "host", scatter_dimension=nb,
                                      tiled=True)
            else:
                sl = lax.psum_scatter(hp, "data", scatter_dimension=nb,
                                      tiled=True)
            lo = _shard_lo()
            full = jnp.zeros(hp.shape, jnp.float32)
            full = lax.dynamic_update_slice(
                full, sl.astype(jnp.float32), (0,) * nb + (lo, 0, 0))
            return full[..., :FH, :, :] if FH_pad > FH else full

        def _shard_lo():
            """First histogram column this device owns after the
            reduce-scatter.  Hierarchical keep-slices are chip-major
            (the second scatter subdivides the chip slice by host), so
            the offset composes both axis indices."""
            if use_hier:
                return (lax.axis_index("chip") * (FH_pad // NC)
                        + lax.axis_index("host") * FH_loc)
            return lax.axis_index("data") * FH_loc

        if bundle is not None:
            _shard_col = bundle.bundle_of            # (F,) hist column
        else:
            _shard_col = jnp.arange(F, dtype=jnp.int32)

        def _split_sharded(hist, parent, mask, key, uid, constraint, depth,
                           parent_output, cegb_pen=None, hist_scale=None):
            """Local best split over this shard's feature slice + the
            SplitInfo sync — FindBestSplitsFromHistograms restricted to
            OWN features, as the reference data-parallel learner does
            after its ReduceScatter (data_parallel_tree_learner.cpp:
            175-199)."""
            lo = _shard_lo()
            in_shard = (_shard_col >= lo) & (_shard_col < lo + FH_loc)
            if bundle is not None:
                from ..io.bundle import expand_bundle_hist

                # zeroed out-of-shard bundle columns expand to garbage
                # zero-bin fixes — masked out by in_shard below
                hist = expand_bundle_hist(hist, parent, bundle, B)
            rk = jax.random.fold_in(key, uid + 1_000_003 + params.extra_seed) \
                if params.extra_trees else None
            local = find_best_split(hist, parent, meta, mask & in_shard,
                                    params, constraint, depth,
                                    config.monotone_penalty, parent_output,
                                    rk, cegb_pen, hist_scale=hist_scale)
            return _sync_best_split(local, parent, params, row_axes)

        # integer histograms cannot cross expand_bundle_hist (its zero-bin
        # fix mixes real-unit parent sums in), so EFB keeps the grower's
        # pre-dequantized path; the collective still moved int32
        _split_sharded.accepts_hist_scale = bundle is None

        def hist_fn(binned, g3, leaf_id, target):
            h = local_hist(binned, g3, leaf_id, target)
            return _scatter_keep(h) if use_rs else lax.psum(h, row_axes)

        def sums_fn(g3):
            return lax.psum(g3.sum(axis=0), row_axes)

        split_dp = _split_sharded if use_rs else split_local

        if levelwise:
            def frontier_fn(binned, g3, leaf_id, L_level):
                h = local_frontier(binned, g3, leaf_id, L_level)
                return _scatter_keep(h) if use_rs else lax.psum(h, row_axes)

            grow = make_levelwise_grower(
                hist_frontier_fn=frontier_fn, sums_fn=sums_fn,
                split_fn=split_dp, bins_of_fn=bins_feat_fn,
                forced_splits=forced, **common)
        elif use_wave and forced is None:
            # one histogram collective per ROUND (up to 2K child
            # histograms batched) instead of one per split — the wave
            # schedule's distributed dividend
            def wave_fn(binned, g3, label, nslots, deep=False):
                h = local_wave(binned, g3, label, nslots, deep)
                return _scatter_keep(h) if use_rs else lax.psum(h, row_axes)

            if use_rs:
                def wave_quant_fn(binned, g3, label, nslots, key):
                    # GLOBAL (pmax'd) scales make the shard partials one
                    # integer system: the collective reduces raw int32
                    # and the single dequantize multiply happens at the
                    # consumer (subtraction pass / split scan hist_scale)
                    # — the quantized pipeline's cross-chip contract.
                    # Hierarchical runs pmax the scale across BOTH levels
                    # and cross int32 on both hops (exact, order-free).
                    h, sc = local_wave_quant(binned, g3, label, nslots,
                                             key, axis_name=row_axes)
                    return _scatter_keep(h, int_domain=True), sc
            else:
                def wave_quant_fn(binned, g3, label, nslots, key):
                    # legacy allreduce: each shard quantizes with its
                    # LOCAL per-pass scales (unbiasedness is per-row, so
                    # the psum of dequantized shard histograms stays an
                    # unbiased estimator); the psum therefore runs on
                    # dequantized f32 and the grower sees identity scales
                    h, sc = local_wave_quant(binned, g3, label, nslots,
                                             key)
                    h = lax.psum(h * sc[:, None, None, :], row_axes)
                    return h, jnp.ones_like(sc)

            grow = make_wave_grower(hist_wave_fn=wave_fn, sums_fn=sums_fn,
                                    hist_wave_quant_fn=(
                                        wave_quant_fn if use_int8sr
                                        else None),
                                    split_fn=split_dp,
                                    bins_of_fn=bins_feat_fn, **wave_common)
        else:
            grow = make_leafwise_grower(hist_fn=hist_fn, sums_fn=sums_fn,
                                        split_fn=split_dp,
                                        bins_of_fn=bins_feat_fn,
                                        forced_splits=forced,
                                        **lw_pool, **common)
        sharded = shard_map(
            grow,
            mesh=mesh,
            in_specs=(P(None, row_axes), P(row_axes, None), P(), P(), P()),
            out_specs=(
                jax.tree_util.tree_map(lambda _: P(), TreeArrays(
                    *([0] * len(TreeArrays._fields)))),
                P(row_axes),
                P(),
            ),
            check_vma=False,
        )

        def grow_fn(binned, g3, base_mask, key, cegb_used):
            pad = N_pad - N
            g3p = jnp.pad(g3, ((0, pad), (0, 0)))
            tree, leaf_id, root = sharded(binned, g3p, base_mask, key,
                                          cegb_used)
            return tree, leaf_id[:N], root

        return obs_xla.instrument_jit(grow_fn, f"grow.{learner}"), \
            binned_dev, N

    if learner == "feature":
        mesh = _make_mesh(config.num_shards, "feature")
        ndev = mesh.devices.size
        F_pad = ((F + ndev - 1) // ndev) * ndev
        F_loc = F_pad // ndev
        binned_p = np.zeros((F_pad, N), dtype=binned_np.dtype)
        binned_p[:F] = binned_np
        # every device holds ALL rows and ALL features (reference feature-
        # parallel replicates the data); only histogram build + split search
        # are feature-sharded
        binned_dev = jax.device_put(
            jnp.asarray(binned_p), NamedSharding(mesh, P(None, None))
        )
        pad_f = F_pad - F
        meta_p = FeatureMeta(
            num_bins=jnp.pad(meta.num_bins, (0, pad_f), constant_values=1),
            missing_type=jnp.pad(meta.missing_type, (0, pad_f)),
            nan_bin=jnp.pad(meta.nan_bin, (0, pad_f), constant_values=-1),
            zero_bin=jnp.pad(meta.zero_bin, (0, pad_f)),
            is_categorical=jnp.pad(meta.is_categorical, (0, pad_f)),
            usable=jnp.pad(meta.usable, (0, pad_f)),
            monotone_type=jnp.pad(meta.monotone_type, (0, pad_f)),
            contri=(jnp.pad(meta.contri, (0, pad_f), constant_values=1.0)
                    if meta.contri is not None else None),
        )
        log_info(f"Feature-parallel training over {ndev} devices "
                 f"({F_loc} features/device)")
        _comm_tbl = comm_table_per_round("feature", "allreduce",
                                         k=wave_size, F=F, B=B, ndev=ndev)
        log_info("comm/round (analytic, K=%d wave): %s"
                 % (wave_size, _comm_tbl))
        publish_comm_metrics("feature", _comm_tbl)

        def hist_fn(binned, g3, leaf_id, target):
            # build histograms only for this device's feature block, placed
            # at the right offset of a full-width (zero elsewhere) array
            lo = lax.axis_index("feature") * F_loc
            block = lax.dynamic_slice(binned, (lo, 0), (F_loc, N))
            h = hist_one_leaf(block, g3, leaf_id, target, B,
                              method=method, precision=precision,
                              interpret=pallas_interpret)
            full = jnp.zeros((F_pad, B, 3), jnp.float32)
            return lax.dynamic_update_slice(full, h, (lo, 0, 0))

        def hist_wave_fp(binned, g3, label, nslots, deep=False):
            lo = lax.axis_index("feature") * F_loc
            block = lax.dynamic_slice(binned, (lo, 0), (F_loc, N))
            h = hist_wave(block, g3, label, nslots, B,
                          method=method,
                          precision=deep_precision if deep else precision,
                          interpret=pallas_interpret)
            full = jnp.zeros((nslots, F_pad, B, 3), jnp.float32)
            return lax.dynamic_update_slice(full, h, (0, lo, 0, 0))

        def hist_wave_quant_fp(binned, g3, label, nslots, key):
            # g3/label/key are replicated, so every shard derives the SAME
            # per-pass scales — the feature-block histograms compose into
            # one consistently-quantized full-width array (zeros outside
            # the shard dequantize to zero)
            lo = lax.axis_index("feature") * F_loc
            block = lax.dynamic_slice(binned, (lo, 0), (F_loc, N))
            h, sc = hist_wave_quant(block, g3, label, nslots, B, key,
                                    method=method,
                                    interpret=pallas_interpret)
            full = jnp.zeros((nslots, F_pad, B, 3), jnp.float32)
            return lax.dynamic_update_slice(full, h, (0, lo, 0, 0)), sc

        def split_fn(hist, parent, mask, key, uid, constraint, depth,
                     parent_output, cegb_pen=None):
            # search only this device's features, then Allreduce-max over
            # packed SplitInfo (reference SyncUpGlobalBestSplit) with the
            # reduction-order-invariant tie-break (_sync_best_split)
            lo = lax.axis_index("feature") * F_loc
            in_shard = (
                lax.broadcasted_iota(jnp.int32, (F_pad, 1), 0)[:, 0] >= lo
            ) & (
                lax.broadcasted_iota(jnp.int32, (F_pad, 1), 0)[:, 0] < lo + F_loc
            )
            rk = jax.random.fold_in(key, uid + 1_000_003 + params.extra_seed) \
                if params.extra_trees else None
            local = find_best_split(hist, parent, meta_p, mask & in_shard,
                                    params, constraint, depth,
                                    config.monotone_penalty, parent_output,
                                    rk, cegb_pen)
            return _sync_best_split(local, parent, params, "feature")

        coupled_fp = _cegb_coupled(config, F)
        if coupled_fp is not None:
            coupled_fp = np.pad(coupled_fp, (0, pad_f))
        fp_kwargs = dict(
            num_leaves=config.num_leaves, num_bins=B, meta=meta_p,
            params=params, max_depth=config.max_depth,
            feature_fraction_bynode=config.feature_fraction_bynode,
            monotone_penalty=config.monotone_penalty,
            interaction_groups=parse_interaction_constraints(
                config.interaction_constraints, F_pad),
        )
        if not levelwise and use_wave:
            # the wave grower implements intermediate-mode monotonicity;
            # the level-wise grower is basic-only (warned above)
            fp_kwargs["monotone_mode"] = mono_mode
            fp_kwargs["async_wave_pipeline"] = config.async_wave_pipeline
        # hist_method=fused per feature slice (ISSUE 13): each shard runs
        # the fused kernel over its OWN feature block — histograms stay
        # in that shard's VMEM, nothing crosses chips but the packed
        # SplitInfo the existing _sync_best_split election already moves
        fused_fp = None
        if fused_builder is not None and use_wave and not levelwise:
            from ..ops.wave_fused import pack_children, unpack_children

            # partition-specific fallback (the ISSUE 15 taxonomy leg):
            # the in-kernel routing stage decides with the committed
            # split feature's GLOBAL column, but each shard's kernel
            # sees only its own feature slice — so the feature-parallel
            # learner keeps the staged (S, N) partition + valid routing
            # (the wrapper below deliberately lacks supports_route)
            # while still fusing histogram + scan per slice
            log_info("hist_method=fused: feature-parallel keeps the "
                     "staged partition (in-kernel routing needs the "
                     "split feature's global column; each shard holds a "
                     "feature slice) — histogram+split stay fused per "
                     "slice through the SplitInfo election")
            base_fused = fused_builder(
                meta=meta_p, params=params, num_bins=B,
                precision=precision, deep_precision=deep_precision,
                monotone_penalty=config.monotone_penalty,
                interpret=jax.default_backend() == "cpu")

            def _slice_meta(lo):
                def sl(a, wide=F_loc):
                    return lax.dynamic_slice(a, (lo,), (wide,))
                return FeatureMeta(
                    num_bins=sl(meta_p.num_bins),
                    missing_type=sl(meta_p.missing_type),
                    nan_bin=sl(meta_p.nan_bin),
                    zero_bin=sl(meta_p.zero_bin),
                    is_categorical=sl(meta_p.is_categorical),
                    usable=sl(meta_p.usable),
                    monotone_type=sl(meta_p.monotone_type),
                    contri=(sl(meta_p.contri)
                            if meta_p.contri is not None else None),
                )

            def fused_fp(binned, g3, label, S, *, deep=False,
                         quant_key=None, scaled=False, mask=None,
                         csums=None, constr=None, depth=None, pout=None,
                         sml=None, parent=None, meta_override=None,
                         route=None):
                del meta_override
                assert route is None, (
                    "feature-parallel fused rounds keep the staged "
                    "partition (no supports_route); the grower must not "
                    "request in-kernel routing here")
                lo = lax.axis_index("feature") * F_loc
                block = lax.dynamic_slice(binned, (lo, 0), (F_loc, N))
                mask_loc = lax.dynamic_slice(
                    mask, (0, lo), (2 * S, F_loc))
                par_loc = (lax.dynamic_slice(
                    parent, (0, lo, 0, 0), (S, F_loc, B, 3))
                    if parent is not None else None)
                packed, hsm, sc = base_fused(
                    block, g3, label, S, deep=deep, quant_key=quant_key,
                    scaled=scaled, mask=mask_loc, csums=csums,
                    constr=constr, depth=depth, pout=pout, sml=sml,
                    parent=par_loc, meta_override=_slice_meta(lo))
                # shard-local feature ids -> global, then the SplitInfo
                # election (reference SyncUpGlobalBestSplit) per child
                local = unpack_children(packed, B)
                local = local._replace(feature=local.feature + lo)
                synced = jax.vmap(
                    lambda lc, ps: _sync_best_split(lc, ps, params,
                                                    "feature")
                )(local, csums)
                packed_g = pack_children(synced)
                if hsm is not None:
                    # re-embed the shard's smaller-child block at its
                    # offset of the full-width (zeros elsewhere) state —
                    # the hist_wave_fp layout the subtraction table uses
                    full = jnp.zeros((S, F_pad, B, 3), jnp.float32)
                    hsm = lax.dynamic_update_slice(full, hsm,
                                                   (0, lo, 0, 0))
                return packed_g, hsm, sc

        if levelwise:
            # feature-sharded frontier histograms + vmapped all_gather
            # argmax per leaf — the level-wise grower composes with the
            # feature-parallel learner like the leaf-wise ones do
            def fp_frontier(binned, g3, leaf_id, L_level):
                lo = lax.axis_index("feature") * F_loc
                block = lax.dynamic_slice(binned, (lo, 0), (F_loc, N))
                h = hist_frontier(block, g3, leaf_id, L_level, Bh,
                                  method=method, precision=precision,
                                  interpret=pallas_interpret)
                full = jnp.zeros((L_level, F_pad, Bh, 3), jnp.float32)
                return lax.dynamic_update_slice(full, h, (0, lo, 0, 0))

            grow = make_levelwise_grower(
                hist_frontier_fn=fp_frontier, split_fn=split_fn,
                cegb_coupled=coupled_fp, **fp_kwargs)
        elif use_wave:
            grow = make_wave_grower(
                hist_wave_fn=hist_wave_fp,
                hist_wave_quant_fn=(hist_wave_quant_fp if use_int8sr
                                    else None),
                split_fn=split_fn,
                fused_round_fn=fused_fp,
                wave_size=wave_size, **fp_kwargs)
        else:
            grow = make_leafwise_grower(
                hist_fn=hist_fn, split_fn=split_fn, cegb_coupled=coupled_fp,
                hist_pool_mb=config.histogram_pool_size,
                num_features=F_pad, **fp_kwargs)
        sharded = shard_map(
            grow,
            mesh=mesh,
            in_specs=(P(None, None), P(None, None), P(), P(), P()),
            out_specs=(
                jax.tree_util.tree_map(lambda _: P(), TreeArrays(
                    *([0] * len(TreeArrays._fields)))),
                P(),
                P(),
            ),
            check_vma=False,
        )

        def grow_fn(binned, g3, base_mask, key, cegb_used):
            maskp = jnp.pad(base_mask, (0, pad_f))
            return sharded(binned, g3, maskp, key,
                           jnp.pad(cegb_used, (0, pad_f)))

        return obs_xla.instrument_jit(
            grow_fn, ("grow.fused_round" if fused_fp is not None
                      else f"grow.{learner}")), binned_dev, N

    log_fatal(f"Unknown tree_learner: {learner}")
