"""Distributed tree learners over a jax.sharding Mesh.

TPU-native re-design of the reference's parallel tree learners and network
stack:

* ``tree_learner=data``  — DataParallelTreeLearner
  (reference: src/treelearner/data_parallel_tree_learner.cpp): rows are
  sharded over the ``data`` mesh axis; each device builds local histograms
  and a ``lax.psum`` replaces the ReduceScatter+allgather of histogram
  blocks (``FindBestSplits`` :155-173, ``HistogramSumReducer`` bin.h:44-57).
  The root grad/hess Allreduce (:126-151) becomes ``psum`` of the g3 totals.
  Split selection runs replicated on every device — deterministic, so no
  ``SyncUpGlobalBestSplit`` message exchange is needed at all.
* ``tree_learner=feature`` — FeatureParallelTreeLearner
  (reference: src/treelearner/feature_parallel_tree_learner.cpp): every
  device holds all rows (data replicated) but builds histograms and searches
  splits only for its feature shard; the winning split is chosen by an
  ``all_gather`` of packed SplitInfo + argmax — the analog of
  ``SyncUpGlobalBestSplit``'s Allreduce-max over serialized SplitInfo pairs
  (parallel_tree_learner.h:190-213).
* ``tree_learner=voting`` — reduces to ``data`` for now (PV-Tree top-k
  voting compression is a comm optimization over slow links; over ICI the
  plain psum is already cheap). A warning is logged.

The socket/MPI ``Network``/``Linkers`` machinery of the reference
(src/network/) has no equivalent here by design: XLA emits the collectives
over ICI/DCN. Multi-host scaling uses ``jax.distributed.initialize`` +
a process-spanning Mesh with the same code path.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..models.grower import make_leafwise_grower
from ..models.tree import TreeArrays
from ..ops.histogram import default_hist_method, hist_one_leaf
from ..ops.split import FeatureMeta, SplitParams, SplitResult, find_best_split
from ..utils.log import log_fatal, log_info, log_warning

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore


def _make_mesh(num_shards: int, axis: str) -> Mesh:
    devices = jax.devices()
    n = num_shards if num_shards > 0 else len(devices)
    if n > len(devices):
        log_fatal(f"num_shards={n} exceeds available devices ({len(devices)})")
    return Mesh(np.array(devices[:n]), (axis,))


def _pack_split(res: SplitResult) -> jnp.ndarray:
    """SplitInfo wire format for the cross-shard argmax (reference:
    SplitInfo::CopyTo, split_info.hpp — fixed-size serialization). The
    categorical bitset words ride along bit-exactly via a f32 bitcast."""
    bits_f32 = lax.bitcast_convert_type(res.cat_bitset, jnp.float32)
    return jnp.concatenate([
        jnp.stack([res.gain, res.feature.astype(jnp.float32),
                   res.threshold_bin.astype(jnp.float32),
                   res.default_left.astype(jnp.float32),
                   res.is_cat.astype(jnp.float32)]),
        res.left_sum, res.right_sum, bits_f32,
    ])


def _unpack_split(v: jnp.ndarray) -> SplitResult:
    return SplitResult(
        gain=v[0],
        feature=v[1].astype(jnp.int32),
        threshold_bin=v[2].astype(jnp.int32),
        default_left=v[3] > 0.5,
        left_sum=v[5:8],
        right_sum=v[8:11],
        is_cat=v[4] > 0.5,
        cat_bitset=lax.bitcast_convert_type(v[11:], jnp.uint32),
    )


def _warn_unimplemented(config: Config) -> None:
    """Loudly reject accepted-but-unimplemented parameters instead of
    silently ignoring them (the reference either enforces or rejects)."""
    checks = [
        ("cegb_tradeoff", config.cegb_tradeoff != 1.0),
        ("cegb_penalty_split", config.cegb_penalty_split != 0.0),
        ("cegb_penalty_feature_lazy", bool(config.cegb_penalty_feature_lazy)),
        ("cegb_penalty_feature_coupled",
         bool(config.cegb_penalty_feature_coupled)),
    ]
    for name, is_set in checks:
        if is_set:
            log_warning(
                f"{name} is set but cost-effective gradient boosting is not "
                "implemented in this build — the parameter has NO effect")


def build_trainer(
    config: Config,
    binned_np: np.ndarray,           # (F, N) uint8/int16 host array
    meta: FeatureMeta,
    params: SplitParams,
    num_bins: int,
) -> Tuple[Callable, jax.Array, int]:
    """Return ``(grow_fn, binned_device, num_data)`` for the configured
    tree_learner.  ``grow_fn(binned_device, g3, base_mask, key)`` has the
    serial grower's signature; ``binned_device`` is already placed/padded
    for the chosen topology."""
    learner = config.tree_learner
    method = default_hist_method(config.hist_method, binned_np.dtype)
    precision = config.hist_dtype
    F, N = binned_np.shape
    B = num_bins

    from ..models.grower import make_levelwise_grower
    from ..ops.histogram import hist_frontier

    levelwise = config.tree_growth == "levelwise"

    def local_hist(binned, g3, leaf_id, target):
        return hist_one_leaf(binned, g3, leaf_id, target, B,
                             method=method, precision=precision)

    def local_frontier(binned, g3, leaf_id, L_level):
        return hist_frontier(binned, g3, leaf_id, L_level, B,
                             method=method, precision=precision)

    if config.monotone_constraints and \
            config.monotone_constraints_method not in ("basic", ""):
        log_warning(
            f"monotone_constraints_method="
            f"{config.monotone_constraints_method} is not implemented; "
            "using 'basic' (reference BasicLeafConstraints semantics)")
    _warn_unimplemented(config)

    common = dict(
        num_leaves=config.num_leaves,
        num_bins=B,
        meta=meta,
        params=params,
        max_depth=config.max_depth,
        feature_fraction_bynode=config.feature_fraction_bynode,
        monotone_penalty=config.monotone_penalty,
    )

    if learner in ("serial", ""):
        if levelwise:
            grow = make_levelwise_grower(hist_frontier_fn=local_frontier, **common)
        else:
            grow = make_leafwise_grower(hist_fn=local_hist, **common)
        return jax.jit(grow), jnp.asarray(binned_np), N

    if learner == "voting":
        log_warning(
            "tree_learner=voting: PV-Tree voting is a communication "
            "compression for slow links; over ICI the data-parallel psum is "
            "already optimal — using tree_learner=data"
        )
        learner = "data"

    if learner == "data":
        mesh = _make_mesh(config.num_shards, "data")
        ndev = mesh.devices.size
        N_pad = ((N + ndev - 1) // ndev) * ndev
        binned_p = np.zeros((F, N_pad), dtype=binned_np.dtype)
        binned_p[:, :N] = binned_np
        binned_dev = jax.device_put(
            jnp.asarray(binned_p), NamedSharding(mesh, P(None, "data"))
        )
        log_info(f"Data-parallel training over {ndev} devices "
                 f"({N_pad // ndev} rows/device)")

        def hist_fn(binned, g3, leaf_id, target):
            # local histogram + Allreduce — the reference's
            # ReduceScatter(HistogramSumReducer) + implicit allgather
            return lax.psum(local_hist(binned, g3, leaf_id, target), "data")

        def sums_fn(g3):
            return lax.psum(g3.sum(axis=0), "data")

        if levelwise:
            def frontier_fn(binned, g3, leaf_id, L_level):
                return lax.psum(
                    local_frontier(binned, g3, leaf_id, L_level), "data")

            grow = make_levelwise_grower(
                hist_frontier_fn=frontier_fn, sums_fn=sums_fn, **common)
        else:
            grow = make_leafwise_grower(hist_fn=hist_fn, sums_fn=sums_fn, **common)
        sharded = shard_map(
            grow,
            mesh=mesh,
            in_specs=(P(None, "data"), P("data", None), P(), P()),
            out_specs=(
                jax.tree_util.tree_map(lambda _: P(), TreeArrays(
                    *([0] * len(TreeArrays._fields)))),
                P("data"),
                P(),
            ),
            check_vma=False,
        )

        @jax.jit
        def grow_fn(binned, g3, base_mask, key):
            pad = N_pad - N
            g3p = jnp.pad(g3, ((0, pad), (0, 0)))
            tree, leaf_id, root = sharded(binned, g3p, base_mask, key)
            return tree, leaf_id[:N], root

        return grow_fn, binned_dev, N

    if learner == "feature":
        if levelwise:
            log_warning("tree_growth=levelwise is not yet available with "
                        "tree_learner=feature; using leafwise")
        mesh = _make_mesh(config.num_shards, "feature")
        ndev = mesh.devices.size
        F_pad = ((F + ndev - 1) // ndev) * ndev
        F_loc = F_pad // ndev
        binned_p = np.zeros((F_pad, N), dtype=binned_np.dtype)
        binned_p[:F] = binned_np
        # every device holds ALL rows and ALL features (reference feature-
        # parallel replicates the data); only histogram build + split search
        # are feature-sharded
        binned_dev = jax.device_put(
            jnp.asarray(binned_p), NamedSharding(mesh, P(None, None))
        )
        pad_f = F_pad - F
        meta_p = FeatureMeta(
            num_bins=jnp.pad(meta.num_bins, (0, pad_f), constant_values=1),
            missing_type=jnp.pad(meta.missing_type, (0, pad_f)),
            nan_bin=jnp.pad(meta.nan_bin, (0, pad_f), constant_values=-1),
            zero_bin=jnp.pad(meta.zero_bin, (0, pad_f)),
            is_categorical=jnp.pad(meta.is_categorical, (0, pad_f)),
            usable=jnp.pad(meta.usable, (0, pad_f)),
            monotone_type=jnp.pad(meta.monotone_type, (0, pad_f)),
        )
        log_info(f"Feature-parallel training over {ndev} devices "
                 f"({F_loc} features/device)")

        def hist_fn(binned, g3, leaf_id, target):
            # build histograms only for this device's feature block, placed
            # at the right offset of a full-width (zero elsewhere) array
            lo = lax.axis_index("feature") * F_loc
            block = lax.dynamic_slice(binned, (lo, 0), (F_loc, N))
            h = hist_one_leaf(block, g3, leaf_id, target, B,
                              method=method, precision=precision)
            full = jnp.zeros((F_pad, B, 3), jnp.float32)
            return lax.dynamic_update_slice(full, h, (lo, 0, 0))

        def split_fn(hist, parent, mask, key, uid, constraint, depth):
            # search only this device's features, then Allreduce-max over
            # packed SplitInfo (reference SyncUpGlobalBestSplit)
            lo = lax.axis_index("feature") * F_loc
            in_shard = (
                lax.broadcasted_iota(jnp.int32, (F_pad, 1), 0)[:, 0] >= lo
            ) & (
                lax.broadcasted_iota(jnp.int32, (F_pad, 1), 0)[:, 0] < lo + F_loc
            )
            local = find_best_split(hist, parent, meta_p, mask & in_shard,
                                    params, constraint, depth,
                                    config.monotone_penalty)
            packed = _pack_split(local)
            allp = lax.all_gather(packed, "feature")        # (ndev, 10)
            best = jnp.argmax(allp[:, 0])
            return _unpack_split(allp[best])

        grow = make_leafwise_grower(
            hist_fn=hist_fn, split_fn=split_fn,
            num_leaves=config.num_leaves, num_bins=B, meta=meta_p,
            params=params, max_depth=config.max_depth,
            feature_fraction_bynode=config.feature_fraction_bynode,
            monotone_penalty=config.monotone_penalty,
        )
        sharded = shard_map(
            grow,
            mesh=mesh,
            in_specs=(P(None, None), P(None, None), P(), P()),
            out_specs=(
                jax.tree_util.tree_map(lambda _: P(), TreeArrays(
                    *([0] * len(TreeArrays._fields)))),
                P(),
                P(),
            ),
            check_vma=False,
        )

        @jax.jit
        def grow_fn(binned, g3, base_mask, key):
            maskp = jnp.pad(base_mask, (0, pad_f))
            return sharded(binned, g3, maskp, key)

        return grow_fn, binned_dev, N

    log_fatal(f"Unknown tree_learner: {learner}")
