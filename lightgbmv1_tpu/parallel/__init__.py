"""Distributed training over jax.sharding meshes (ICI/DCN collectives).

TPU-native replacement for the reference ``src/network`` stack (SURVEY.md §5
"Distributed communication backend"): the socket/MPI Linkers and hand-rolled
Bruck/recursive-halving collectives become ``jax.lax.psum`` /
``psum_scatter`` / ``all_gather`` inside ``shard_map`` over a device mesh.
"""
