"""Elastic multi-process training recovery — leases, peer-loss
detection, deterministic re-bootstrap.

The reference's data-parallel protocol simply HANGS when a machine
drops out mid-training: every ``Network::Allreduce`` blocks on the
dead socket until the operator notices (PAPERS.md §data-parallel; the
socket linker has no liveness story at all).  jax.distributed inherits
the same failure shape — a lost process leaves the survivors blocked
inside a collective forever.  This module adds the three pieces that
turn a hang into a bounded-window recovery:

* **file leases** (:class:`LeaseBoard`) — every worker atomically
  rewrites its ``lease_rank<r>.json`` on a heartbeat period; a peer
  whose lease goes stale past ``lease_timeout_s`` is declared dead.
  Leases are files, not sockets, because the coordinator-side liveness
  surface must survive exactly the failure being detected (a dead
  worker can't FIN its socket cleanly out of ``os._exit``).
* **peer-loss abort** (:class:`HeartbeatMonitor`) — a daemon thread per
  worker beats its own lease and watches the others.  On a stale peer
  it publishes a ``fleet.peer_lost`` event, exports the process's obs
  artifacts (best effort), and ``os._exit(EXIT_PEER_LOST)`` — the ONLY
  honest way out, since the main thread is wedged inside a collective
  the dead peer will never join.
* **deterministic re-bootstrap** (:class:`ElasticCoordinator`) — a
  parent process spawns the N workers (the subprocess harness the
  multihost tests pioneered), watches for any death, reaps the rest,
  and respawns the fleet on a FRESH coordinator port.  Respawned
  workers auto-resume from the newest intact PR-6 checkpoint bundle
  (``cli._find_resume_point``), so the recovered run reproduces the
  uninterrupted run's model text **byte-identically** — recovery is a
  pure recompute of the iterations since the last bundle, never an
  approximation (tools/chaos.py ``trainer_worker_kill``).

Fault seam: workers fire ``peer_dead`` (utils/faults.py) at every
iteration boundary with site ``rank<r>:iter<i>``, so a chaos plan kills
a specific rank at a specific iteration deterministically.  The
coordinator arms the plan for the FIRST generation only — the respawn
models a replaced node, not a haunted one.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils import fileio
from ..utils.log import log_info, log_warning

EXIT_PEER_LOST = 96     # a survivor that aborted on a stale peer lease
LEASE_PREFIX = "lease_rank"


class PeerLostError(RuntimeError):
    """A peer worker's lease went stale (its process is gone or
    wedged); the run must re-bootstrap from the last bundle."""


# ---------------------------------------------------------------------------
# leases
# ---------------------------------------------------------------------------


class LeaseBoard:
    """Per-rank lease files under one shared directory.

    A lease carries ``{rank, pid, beat, iteration, t_wall}`` and is
    rewritten atomically (tmp+fsync+rename) each heartbeat, so a reader
    never sees a torn lease — a lease is either the previous beat or
    the current one.  Staleness is judged on wall clock (the workers
    share a host or a fleet with sane NTP; the timeout is seconds, not
    milliseconds)."""

    def __init__(self, leases_dir: str, rank: int, world: int,
                 timeout_s: float = 3.0):
        self.dir = str(leases_dir)
        self.rank = int(rank)
        self.world = int(world)
        self.timeout_s = float(timeout_s)
        self.beats = 0
        self._t_start = time.time()
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, rank: int) -> str:
        return os.path.join(self.dir, f"{LEASE_PREFIX}{rank}.json")

    def beat(self, iteration: int = -1) -> None:
        self.beats += 1
        payload = {"rank": self.rank, "pid": os.getpid(),
                   "beat": self.beats, "iteration": int(iteration),
                   "t_wall": time.time()}
        fileio.atomic_write_bytes(self._path(self.rank),
                                  json.dumps(payload).encode("utf-8"),
                                  site="lease")

    def read(self, rank: int) -> Optional[dict]:
        try:
            with open(self._path(rank)) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def stale_peers(self, now: Optional[float] = None) -> List[int]:
        """Ranks whose lease is older than ``timeout_s`` (or absent
        after an initial grace of one timeout from board start — a peer
        that never managed a first beat is just as dead)."""
        now = time.time() if now is None else now
        dead = []
        for r in range(self.world):
            if r == self.rank:
                continue
            lease = self.read(r)
            if lease is None:
                if now - self._t_start > self.timeout_s:
                    dead.append(r)
            elif now - float(lease.get("t_wall", 0.0)) > self.timeout_s:
                dead.append(r)
        return dead

    def wait_stale(self, extra_wait_s: Optional[float] = None) -> List[int]:
        """Block up to ``extra_wait_s`` (default 2x the lease timeout)
        for ANY peer lease to go stale; returns the dead ranks (empty =
        every peer stayed fresh).  The survivor's verdict call: a
        collective that failed under it is a peer loss when this
        returns dead ranks, a genuine crash otherwise."""
        deadline = time.monotonic() + (2.0 * self.timeout_s
                                       if extra_wait_s is None
                                       else float(extra_wait_s))
        while True:
            dead = self.stale_peers()
            if dead or time.monotonic() >= deadline:
                return dead
            time.sleep(min(self.timeout_s / 4.0, 0.25))

    def fresh_ranks(self, now: Optional[float] = None) -> List[int]:
        """Ranks with a currently-fresh lease (the coordinator's
        recovery probe: re-bootstrap is DONE when every rank beats)."""
        now = time.time() if now is None else now
        out = []
        for r in range(self.world):
            lease = self.read(r)
            if lease is not None and \
                    now - float(lease.get("t_wall", 0.0)) <= self.timeout_s:
                out.append(r)
        return out


class HeartbeatMonitor:
    """Daemon thread: beat own lease, watch peers, abort on loss.

    The beat signals *process liveness*, deliberately not training
    progress: a worker blocked in a collective is alive and must keep
    its lease while the protocol decides who actually died.  Detection
    latency is bounded by ``timeout_s + period`` (period defaults to a
    quarter of the timeout)."""

    def __init__(self, board: LeaseBoard, *,
                 period_s: Optional[float] = None,
                 obs_export_dir: str = "",
                 on_peer_lost=None):
        self.board = board
        self.period_s = (max(board.timeout_s / 4.0, 0.05)
                         if period_s is None else float(period_s))
        self.obs_export_dir = str(obs_export_dir or "")
        self.on_peer_lost = on_peer_lost
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="elastic-heartbeat",
                                        daemon=True)
        self.lost: List[int] = []

    def start(self) -> "HeartbeatMonitor":
        self.board.beat()
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.board.beat()
                dead = self.board.stale_peers()
            except OSError:
                # leases dir torn down under us: the coordinator reaps
                # the board after the fleet finishes, and this daemon
                # thread may still be mid-beat — that is shutdown, not a
                # crash (must not surface as an unhandled_thread_exception
                # forensic bundle)
                return
            if dead:
                self.lost = dead
                self._abort(dead)
                return

    def _abort(self, dead: List[int]) -> None:
        from ..obs import events as obs_events

        obs_events.publish(
            "fleet.peer_lost",
            f"rank(s) {dead} lease stale past "
            f"{self.board.timeout_s:.1f}s — aborting for re-bootstrap",
            severity="error", dead_ranks=list(dead),
            rank=self.board.rank,
            lease_timeout_s=self.board.timeout_s)
        log_warning(f"elastic: rank {self.board.rank} lost peer(s) "
                    f"{dead}; exiting {EXIT_PEER_LOST} for re-bootstrap")
        if self.obs_export_dir:
            # the survivor's last will: its span/metrics/event artifacts
            # join the fleet-merged trace even though the process dies
            # with a wedged main thread (best effort, never blocking the
            # exit on an export failure)
            try:
                from ..obs import agg as obs_agg

                obs_agg.export_process_artifacts(self.obs_export_dir)
            except Exception:   # noqa: BLE001
                pass
        if self.on_peer_lost is not None:
            self.on_peer_lost(dead)
            return
        # the main thread is (typically) wedged inside a collective the
        # dead peer will never join — a clean unwind does not exist
        os._exit(EXIT_PEER_LOST)


# ---------------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------------


@dataclass
class ElasticConfig:
    """Knobs of one elastic run (mirrored by the ``elastic_*`` names in
    config.py for CLI visibility; defaults match)."""

    world: int = 2                   # worker processes
    devices_per_proc: int = 2        # virtual CPU devices per worker
    lease_timeout_s: float = 3.0     # staleness bound (detection window)
    max_restarts: int = 2            # re-bootstraps before giving up
    restart_backoff_s: float = 0.25  # jittered exponential base
    worker_timeout_s: float = 300.0  # hard per-generation wall bound
    grace_s: float = 0.0             # wait for survivors to self-abort
                                     # (0 = 3 lease timeouts)
    shrink_on_loss: bool = False     # partial-fleet loss: respawn the
                                     # SURVIVORS as a smaller world
                                     # instead of replacing the dead
                                     # rank (pod semantics — a lost
                                     # host stays lost; shard ranges
                                     # and the mesh re-derive from the
                                     # new (rank, world))

    def __post_init__(self):
        self.world = max(int(self.world), 1)
        self.devices_per_proc = max(int(self.devices_per_proc), 1)
        self.lease_timeout_s = max(float(self.lease_timeout_s), 0.2)
        self.max_restarts = max(int(self.max_restarts), 0)
        self.restart_backoff_s = max(float(self.restart_backoff_s), 0.0)
        if self.grace_s <= 0:
            self.grace_s = 3.0 * self.lease_timeout_s

    @classmethod
    def from_config(cls, config, **over) -> "ElasticConfig":
        """Map the global Config's ``elastic_*`` knobs (the CLI-visible
        form, BASELINE.md "Fault-tolerant fleet") onto an ElasticConfig;
        ``over`` wins for harness-specific fields (world, device
        count)."""
        kw = dict(lease_timeout_s=config.elastic_lease_timeout_s,
                  max_restarts=config.elastic_max_restarts)
        kw.update(over)
        return cls(**kw)


@dataclass
class ElasticResult:
    ok: bool
    restarts: int
    generations: List[List[int]] = field(default_factory=list)
    worlds: List[int] = field(default_factory=list)
    recovery_s: Optional[float] = None
    peer_lost_exits: int = 0
    outputs: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"ok": self.ok, "restarts": self.restarts,
                "generations": self.generations,
                "worlds": self.worlds,
                "recovery_s": self.recovery_s,
                "peer_lost_exits": self.peer_lost_exits}


class ElasticCoordinator:
    """Spawn/watch/re-bootstrap loop over the elastic worker module.

    ``worker_args`` is the ``key=value`` argv passed through to
    ``python -m lightgbmv1_tpu.parallel.elastic_worker`` (data path,
    iteration count, snapshot freq, model output — see that module);
    the coordinator owns rank/port/world/lease wiring.  ``fault_env``
    (e.g. a ``peer_dead`` kill plan in ``LGBMV1_FAULTS``) is applied to
    the FIRST generation only."""

    def __init__(self, workdir: str, worker_args: Dict[str, object],
                 config: Optional[ElasticConfig] = None,
                 fault_env: Optional[Dict[str, str]] = None,
                 env: Optional[Dict[str, str]] = None):
        self.workdir = str(workdir)
        self.worker_args = dict(worker_args)
        self.config = config or ElasticConfig()
        self.fault_env = dict(fault_env or {})
        self.base_env = dict(env) if env is not None else dict(os.environ)
        os.makedirs(self.workdir, exist_ok=True)

    # -- spawn one generation -------------------------------------------
    def _spawn(self, generation: int, port: int,
               world: Optional[int] = None) -> List[subprocess.Popen]:
        cfg = self.config
        world = cfg.world if world is None else int(world)
        procs = []
        for rank in range(world):
            env = dict(self.base_env)
            env["PYTHONPATH"] = (
                os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
                + os.pathsep + env.get("PYTHONPATH", ""))
            env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                                f"{cfg.devices_per_proc}")
            env.setdefault("JAX_PLATFORMS", "cpu")
            env.setdefault("LGBMV1_OBS_ROLE", f"trainer-r{rank}")
            if generation == 0 and self.fault_env:
                env.update(self.fault_env)
            else:
                env.pop("LGBMV1_FAULTS", None)
            args = [sys.executable, "-m",
                    "lightgbmv1_tpu.parallel.elastic_worker",
                    f"rank={rank}", f"world={world}", f"port={port}",
                    f"leases_dir={os.path.join(self.workdir, 'leases')}",
                    f"lease_timeout_s={cfg.lease_timeout_s}",
                    f"generation={generation}"]
            args += [f"{k}={v}" for k, v in self.worker_args.items()]
            procs.append(subprocess.Popen(
                args, env=env, cwd=self.workdir,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        return procs

    @staticmethod
    def _reap(procs: List[subprocess.Popen], grace_s: float) -> None:
        """SIGTERM the stragglers, escalate to SIGKILL after a grace —
        a survivor wedged inside a gloo collective may not honor TERM."""
        deadline = time.monotonic() + grace_s
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        for p in procs:
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()

    def _clear_leases(self) -> None:
        leases = os.path.join(self.workdir, "leases")
        try:
            for name in os.listdir(leases):
                if name.startswith(LEASE_PREFIX):
                    os.remove(os.path.join(leases, name))
        except OSError:
            pass

    # -- the recovery loop ----------------------------------------------
    def run(self) -> ElasticResult:
        from .cluster import find_free_port

        cfg = self.config
        result = ElasticResult(ok=False, restarts=0)
        t_detect: Optional[float] = None
        world = cfg.world
        for generation in range(cfg.max_restarts + 1):
            self._clear_leases()
            port = find_free_port()
            log_info(f"elastic: generation {generation} starting "
                     f"({world} workers, coordinator :{port})")
            procs = self._spawn(generation, port, world)
            result.worlds.append(world)
            if t_detect is not None and result.recovery_s is None:
                # recovery window closes when every respawned rank has a
                # fresh lease — the fleet is re-bootstrapped and training
                board = LeaseBoard(os.path.join(self.workdir, "leases"),
                                   rank=-1, world=world,
                                   timeout_s=cfg.lease_timeout_s)
                probe_deadline = time.monotonic() + cfg.worker_timeout_s
                while time.monotonic() < probe_deadline:
                    if len(board.fresh_ranks()) == world:
                        result.recovery_s = round(
                            time.monotonic() - t_detect, 3)
                        break
                    if any(p.poll() is not None for p in procs):
                        break
                    time.sleep(0.05)
            deadline = time.monotonic() + cfg.worker_timeout_s
            rcs: List[Optional[int]] = [None] * world
            first_death: Optional[float] = None
            while time.monotonic() < deadline:
                for i, p in enumerate(procs):
                    if rcs[i] is None and p.poll() is not None:
                        rcs[i] = p.returncode
                        if p.returncode != 0 and first_death is None:
                            first_death = time.monotonic()
                done = [rc is not None for rc in rcs]
                if all(done):
                    break
                if first_death is not None and \
                        time.monotonic() - first_death > cfg.grace_s:
                    # survivors got their lease window to self-abort
                    # (EXIT_PEER_LOST); whoever is left gets reaped
                    break
                time.sleep(0.05)
            self._reap(procs, grace_s=2.0)
            outs = []
            for i, p in enumerate(procs):
                try:
                    out = p.stdout.read() if p.stdout else ""
                except (OSError, ValueError):
                    out = ""
                outs.append(out)
                if rcs[i] is None:
                    rcs[i] = p.returncode
            result.outputs = outs
            result.generations.append([int(rc) for rc in rcs])
            result.peer_lost_exits += sum(
                1 for rc in rcs if rc == EXIT_PEER_LOST)
            if all(rc == 0 for rc in rcs):
                result.ok = True
                return result
            if generation >= cfg.max_restarts:
                log_warning(f"elastic: generation {generation} failed "
                            f"(exits {rcs}) and max_restarts reached")
                return result
            if t_detect is None:
                t_detect = (first_death if first_death is not None
                            else time.monotonic())
            result.restarts += 1
            if cfg.shrink_on_loss:
                # partial-fleet loss (ISSUE 16): ranks that died HARD
                # (not the EXIT_PEER_LOST self-aborts — those survivors
                # are respawnable) are lost hosts; the next generation
                # runs the smaller world, and every worker re-derives
                # its shard range and mesh from the new (rank, world)
                # positive exits only: negative rcs are the coordinator's
                # own reap of wedged-but-alive survivors, not lost hosts
                hard_dead = sum(1 for rc in rcs
                                if rc not in (0, EXIT_PEER_LOST) and rc > 0)
                if 0 < hard_dead < world:
                    world -= hard_dead
                    log_warning(f"elastic: {hard_dead} worker(s) died "
                                f"hard; shrinking the fleet to {world} "
                                "survivors for the next generation")
            jitter = random.Random(1_000_003 * generation).random()
            delay = cfg.restart_backoff_s * (2 ** generation) \
                * (1.0 + jitter)
            log_warning(f"elastic: generation {generation} lost worker(s) "
                        f"(exits {rcs}); re-bootstrapping in {delay:.2f}s "
                        "from the newest checkpoint bundle")
            time.sleep(delay)
        return result
