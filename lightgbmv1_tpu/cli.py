"""Command-line application.

TPU-native equivalent of the reference CLI
(reference: ``src/main.cpp:11-42`` → ``src/application/application.cpp`` —
parameter loading :49-82, LoadData :84-162, InitTrain :164-199, Train :201,
Predict :213 → ``src/application/predictor.hpp:29-160``; model conversion
``ModelToIfElse``, src/boosting/gbdt_model_text.cpp:122-304).

Usage matches the reference:

    python -m lightgbmv1_tpu config=train.conf [key=value ...]

Tasks: ``train`` (default), ``predict`` / ``prediction``, ``refit``,
``convert_model``, ``save_binary`` (parse -> bin -> write the sharded
block cache, from which ``train`` streams out-of-core; reference CLI
parity for Application task save_binary), and ``serve`` (the online
serving subsystem,
``serve/``: deadline-aware micro-batching over the device inference
engine behind a stdlib HTTP endpoint — no reference equivalent; the
reference stops at the batch file->file Predictor).  The reference's
example configs (``/root/reference/examples/*/train.conf``) run
unmodified.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import List, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import Config
from .io.parser import load_data_file
from .utils.log import log_fatal, log_info, log_warning
from .utils.timer import global_timer


def _config_to_params(config: Config) -> dict:
    """Round-trip a Config into the params-dict form the Booster takes."""
    return dataclasses.asdict(config)


def _load_dataset(config: Config, path: str,
                  reference: Optional[Dataset] = None,
                  init_score_file: str = "") -> Dataset:
    from .data.block_cache import is_block_cache
    from .io.dataset import BinnedDataset

    if is_block_cache(path):
        # sharded block cache (task=save_binary output): streams during
        # training — no re-parse, no re-bin, bounded device working set
        return Dataset(path, params=_config_to_params(config),
                       reference=reference)
    if BinnedDataset.is_binary_file(path):
        return Dataset(path, params=_config_to_params(config),
                       reference=reference)
    if config.two_round and reference is None:
        # streaming two-pass path (reference two_round=true); Dataset's
        # file-path constructor routes to io.parser.load_two_round
        cat2 = "auto"
        if config.categorical_feature:
            cat2 = [int(x) for x in
                    str(config.categorical_feature).replace(",", " ").split()]
        return Dataset(path, params=_config_to_params(config),
                       reference=reference, categorical_feature=cat2)
    df = load_data_file(
        path,
        has_header=config.header,
        label_column=config.label_column,
        weight_column=config.weight_column,
        group_column=config.group_column,
        ignore_column=config.ignore_column,
        num_threads=config.num_threads,
        init_score_file=init_score_file,
    )
    cat = "auto"
    if config.categorical_feature:
        cat = [int(x) for x in
               str(config.categorical_feature).replace(",", " ").split()]
    return Dataset(
        df.X, label=df.label, weight=df.weight, group=df.group,
        params=_config_to_params(config), reference=reference,
        feature_name=df.feature_names or "auto",
        categorical_feature=cat,
    )


def _iter_artifacts(output_model: str):
    """``[(iteration, kind, path)]`` of on-disk resume artifacts:
    ``kind`` is ``"ckpt"`` (full trainer-state bundle, bit-exact resume)
    or ``"snapshot"`` (model text, approximate continued training)."""
    import glob
    import re

    out = []
    for kind, tag in (("ckpt", ".ckpt_iter_"),
                      ("snapshot", ".snapshot_iter_")):
        for p in glob.glob(glob.escape(output_model) + tag + "*"):
            m = re.search(r"_iter_(\d+)$", p)
            if m:
                out.append((int(m.group(1)), kind, p))
    return out


def _find_resume_point(output_model: str):
    """Newest VALID resume artifact as ``(kind, path, done_iters,
    bundle)``; ``(None, None, 0, None)`` when nothing intact exists.

    ANY intact checkpoint bundle is preferred over ANY model-text
    snapshot — even one at a higher iteration: a bundle resumes
    bit-exactly, so iterations "lost" to a torn newer file are recomputed
    IDENTICALLY (pure compute cost), while a model-text resume is
    approximate forever.  Every candidate is VALIDATED before it is
    chosen — a torn or corrupted newest file (kill mid-write under the
    legacy non-atomic writer, bit rot, a partial copy) makes the scan
    fall back to the previous intact artifact instead of crashing or
    silently mistraining the resumed run."""
    arts = _iter_artifacts(output_model)
    # all bundles (newest first), then all snapshots (newest first)
    arts.sort(key=lambda t: (t[1] == "ckpt", t[0]), reverse=True)
    for it, kind, path in arts:
        if kind == "ckpt":
            try:
                from .io.checkpoint import load_checkpoint

                bundle = load_checkpoint(path)
                return kind, path, int(bundle["manifest"]["iteration"]), \
                    bundle
            except Exception as e:  # noqa: BLE001 — fall back, loudly
                log_warning(f"Ignoring invalid checkpoint {path} "
                            f"({type(e).__name__}: {e}); falling back")
        else:
            try:
                from .io.model_text import model_from_string
                from .utils import fileio

                with fileio.open_file(path) as fh:
                    model_from_string(fh.read())   # validate_host_tree
                return kind, path, it, None
            except Exception as e:  # noqa: BLE001
                log_warning(f"Ignoring invalid snapshot {path} "
                            f"({type(e).__name__}: {e}); falling back")
    return None, None, 0, None


def _prune_snapshots(output_model: str, keep: int) -> None:
    """Bound the on-disk footprint: keep the newest ``keep`` artifacts of
    EACH kind (>= 2, so a torn newest always has an intact predecessor)."""
    by_kind = {"ckpt": [], "snapshot": []}
    for it, kind, path in _iter_artifacts(output_model):
        by_kind[kind].append((it, path))
    for arts in by_kind.values():
        arts.sort(reverse=True)
        for _, path in arts[max(keep, 2):]:
            try:
                os.remove(path)
            except OSError:  # pragma: no cover — already gone is fine
                pass


def _arm_profiler(config: Config):
    """Arm the ``profile_dir`` jax.profiler device capture for this task
    window and return an EXPORT-ONCE finisher — safe to call from every
    exit path (clean completion, the dying-run handler, finally blocks):
    only the first call stops the trace and writes the wall-clock anchor
    sidecar (obs/xla.py) that lets tools/obs_aggregate.py merge the
    device lane onto the host span timeline.  The pre-ISSUE-12 inline
    start/stop was train-only and could leak an armed profiler when the
    run died between arm and the stop path."""
    if not config.profile_dir:
        return lambda: None
    from .obs import xla as obs_xla

    session = obs_xla.start_profiler(config.profile_dir)

    def finish():
        if obs_xla.stop_profiler(session):
            log_info(f"Wrote device trace to {config.profile_dir} "
                     "(merge the lane with tools/obs_aggregate.py "
                     f"--profile-dir {config.profile_dir})")
    return finish


def run_train(config: Config) -> Booster:
    """reference: Application::InitTrain + Train, application.cpp:164-211."""
    if not config.data:
        log_fatal("No training data: set data=<file>")
    t0 = time.time()
    train_set = _load_dataset(config, config.data,
                              init_score_file=config.initscore_filename)
    if config.save_binary:
        # reference: is_save_binary_file → SaveBinaryFile(data + ".bin")
        train_set.save_binary(config.data + ".bin")
    init_model = config.input_model or None
    done_iters = 0
    resume_bundle = None
    if init_model is None and config.snapshot_freq > 0 \
            and not os.path.exists(config.output_model):
        # crash recovery: resume from the newest VALIDATED artifact
        # automatically — but ONLY when the final model is absent (i.e.
        # the previous run crashed); a completed run's leftover snapshots
        # never hijack a fresh training run.  Checkpoint bundles resume
        # BIT-EXACTLY (full trainer state, io/checkpoint.py); model-text
        # snapshots remain as the approximate fallback.
        kind, snap, done_iters, resume_bundle = _find_resume_point(
            config.output_model)
        if kind == "ckpt":
            log_info(f"Resuming bit-exactly from checkpoint {snap} "
                     f"({done_iters} iterations already trained)")
        elif kind == "snapshot":
            log_info(f"Resuming from snapshot {snap} ({done_iters} "
                     "iterations already trained)")
            init_model = snap
        else:
            done_iters = 0
    booster = Booster(params=_config_to_params(config), train_set=train_set,
                      init_model=init_model)
    valid_names: List[str] = []
    for i, vpath in enumerate(config.valid):
        name = os.path.basename(vpath)
        # per-valid-set init score files (reference: valid_data_initscores)
        vinit = (config.valid_data_initscores[i]
                 if i < len(config.valid_data_initscores) else "")
        booster.add_valid(_load_dataset(config, vpath, reference=train_set,
                                        init_score_file=vinit),
                          name)
        valid_names.append(name)
    if resume_bundle is not None:
        # after add_valid: the valid score caches are part of the bundle
        booster.resume_from_checkpoint(resume_bundle)
    log_info(f"Finished loading data in {time.time() - t0:.6f} seconds")

    n_iter = max(config.num_iterations - done_iters, 0)
    t0 = time.time()
    tracing = False
    if config.obs_trace or config.trace_out:
        # host-side span tracer (obs/trace.py); composes with the jax
        # profiler knob below — profile_dir captures the DEVICE trace,
        # trace_out the HOST span timeline (documented precedence: both
        # write their own artifact; neither disables the other)
        from .obs import trace as obs_trace

        obs_trace.arm(ring_events=config.obs_ring_events)
        tracing = True
    finish_profile = _arm_profiler(config)

    def _finish_trace():
        # export + disarm exactly once — on clean completion (after the
        # final model save, so its materialization span is captured) or
        # on the way out of a dying run (partial trace beats none)
        nonlocal tracing
        if not tracing:
            return
        tracing = False
        from .obs import trace as obs_trace

        if config.trace_out:
            doc = obs_trace.export_chrome(config.trace_out)
            log_info(f"Wrote host span trace to {config.trace_out} "
                     f"({len(doc['traceEvents'])} events, "
                     f"{doc['otherData']['dropped_events']} dropped; "
                     "open at https://ui.perfetto.dev)")
        obs_trace.disarm()

    try:
        for i in range(n_iter):
            finished = booster.update()
            if config.metric_freq > 0 and (i + 1) % config.metric_freq == 0:
                # reference: OutputMetric prints the training metric only
                # under is_provide_training_metric (gbdt.cpp:413-434)
                if config.is_provide_training_metric:
                    for data_name, metric, value, _ in booster.eval_train():
                        log_info(f"Iteration:{i + 1}, {data_name} {metric} "
                                 f": {value:g}")
                for data_name, metric, value, _ in booster.eval_valid():
                    log_info(f"Iteration:{i + 1}, {data_name} {metric} "
                             f": {value:g}")
            log_info(f"{time.time() - t0:.6f} seconds elapsed, "
                     f"finished iteration {i + 1}")
            # snapshots (reference: GBDT::Train, gbdt.cpp:258-262) — both
            # artifacts are written atomically (tmp+fsync+rename): a kill
            # at ANY instant leaves only intact files on disk, and the
            # checkpoint bundle makes the next run's auto-resume
            # bit-exact instead of predict-reseeded
            total_i = done_iters + i + 1
            if config.snapshot_freq > 0 and total_i % config.snapshot_freq == 0:
                snap = f"{config.output_model}.snapshot_iter_{total_i}"
                booster.save_model(snap)
                ckpt = f"{config.output_model}.ckpt_iter_{total_i}"
                booster.save_checkpoint(ckpt)
                log_info(f"Saved snapshot to {snap} (+ checkpoint bundle)")
                _prune_snapshots(config.output_model,
                                 keep=config.snapshot_keep)
                from .utils import faults

                # chaos seam: a scripted kill lands exactly here — after
                # the Nth snapshot is durable, before the next iteration
                faults.fire("snapshot", site=str(total_i))
            if finished:
                break
    except BaseException as e:
        # dying run: the armed flight recorder writes its bundle HERE,
        # while the trainer state that explains the death still exists
        # (the injected-kill and fatal paths dump at their own seams;
        # the once-per-arming latch keeps it to one bundle either way)
        from .obs import dump as obs_dump

        obs_dump.dump("train_crash", exc=e)
        _finish_trace()
        finish_profile()    # export-once: a dying run still gets its
        raise               # partial device trace + anchor sidecar
    try:
        if config.output_model:
            # still inside the traced region: the final model save
            # (host-tree materialization + model-text write) is part of
            # the run's timeline
            booster.save_model(config.output_model)
    finally:
        _finish_trace()
        finish_profile()
    log_info("Finished training")
    return booster


def run_save_binary(config: Config) -> str:
    """``task=save_binary`` (reference CLI parity: Application task
    save_binary → Dataset::SaveBinaryFile): parse → bin → write the
    SHARDED block cache, from which ``task=train`` (auto-detected) or
    ``stream_enable`` trains out-of-core without re-parsing.  Output
    directory: ``stream_cache_dir`` or ``<data>.blocks``."""
    if not config.data:
        log_fatal("No data to convert: set data=<file>")
    out = config.stream_cache_dir or (config.data + ".blocks")
    t0 = time.time()
    train_set = _load_dataset(config, config.data,
                              init_score_file=config.initscore_filename)
    train_set.save_block_cache(out, block_rows=config.stream_block_rows)
    log_info(f"Finished save_binary in {time.time() - t0:.3f}s: "
             f"train with data={out}")
    return out


def run_predict(config: Config) -> None:
    """reference: Application::Predict → Predictor, predictor.hpp:29-160.

    The file->file window decomposes into parse / predict / write; with a
    device ``predict_method`` the predict leg streams through the batched
    inference engine (models/predict.py) — prebinned serving codes and
    double-buffered host->device chunks, so H2D of chunk i+1 overlaps the
    walk of chunk i.  Component times are logged so the split matches
    bench.py's measure_predict fields."""
    if not config.input_model:
        log_fatal("No model file: set input_model=<file>")
    if not config.data:
        log_fatal("No prediction data: set data=<file>")
    # the Config rides into Booster.params so predict_method /
    # predict_prebin / bucket knobs reach the predict routing
    booster = Booster(params=_config_to_params(config),
                      model_file=config.input_model)
    log_info("Finished initializing prediction, total used "
             f"{booster.current_iteration()} iterations")
    # profile_dir now covers the predict window too (it was train-only):
    # the device walk + H2D of the batched inference engine is exactly
    # what a serving-perf capture needs to see
    finish_profile = _arm_profiler(config)
    t0 = time.time()
    try:
        # honor the same loader options as training (header/label/ignore)
        df = load_data_file(
            config.data,
            has_header=config.header,
            label_column=config.label_column,
            weight_column=config.weight_column,
            group_column=config.group_column,
            ignore_column=config.ignore_column,
            is_predict=True,
        )
        X = df.X
        if X.shape[1] == booster.num_feature() + 1:
            X = X[:, 1:]   # prediction files may still carry the label col
        t_parse = time.time()
        out = booster.predict(
            X,
            raw_score=config.predict_raw_score,
            pred_leaf=config.predict_leaf_index,
            pred_contrib=config.predict_contrib,
            start_iteration=config.start_iteration_predict,
            num_iteration=(config.num_iteration_predict
                           if config.num_iteration_predict > 0 else None),
            pred_early_stop=config.pred_early_stop,
            pred_early_stop_freq=config.pred_early_stop_freq,
            pred_early_stop_margin=config.pred_early_stop_margin,
            predict_disable_shape_check=config.predict_disable_shape_check,
        )
        t_pred = time.time()
        out = np.asarray(out)
        if out.ndim == 1:
            out = out[:, None]
        fmt = "%d" if config.predict_leaf_index else "%.18g"
        np.savetxt(config.output_result, out, fmt=fmt, delimiter="\t")
        t1 = time.time()
    finally:
        finish_profile()    # export-once: no leaked armed profiler on a
        # failed parse/predict/write — the partial capture still lands
    log_info(f"Prediction window: parse {t_parse - t0:.3f}s, predict "
             f"{t_pred - t_parse:.3f}s ({config.predict_method}), write "
             f"{t1 - t_pred:.3f}s ({X.shape[0]} rows)")
    log_info("Finished prediction")


def run_serve(config: Config):
    """Online serving (serve/ subsystem): load ``input_model``, publish it
    into a warm :class:`~lightgbmv1_tpu.serve.Server`, and listen on the
    stdlib HTTP front-end.  ``serve_duration_s>0`` bounds the run (CI /
    driver smoke); 0 serves until interrupted.  Returns the
    ``(server, http)`` pair so tests can drive it in-process.

    ``serve_replicas > 1`` stands up the fault-tolerant fleet instead:
    N replica Servers (serve/fleet.py, coordinated two-phase publish)
    behind the self-healing router (serve/router.py — health-check
    ejection, retry-onto-another-replica, optional hedging), served
    through the SAME HTTP front-end; the returned "server" is the
    Router."""
    import time as _time

    from .serve import ServeHTTP
    from .serve.server import build_server

    if not config.input_model:
        log_fatal("No model file: set input_model=<file>")
    tracing = False
    if config.obs_trace or config.trace_out:
        # same knob as task=train: arm the span tracer for the serving
        # window; trace_out (when set) gets the Chrome JSON at shutdown
        from .obs import trace as obs_trace

        obs_trace.arm(ring_events=config.obs_ring_events)
        tracing = True
    # profile_dir covers the serving window too (it was train-only): the
    # micro-batched device walks of live traffic are the capture target
    finish_profile = _arm_profiler(config)
    try:
        return _run_serve_armed(config, finish_profile, tracing)
    except BaseException:
        # a failed model load / fleet build / port bind must not leak an
        # armed profiler (export-once: no-op when shutdown already ran)
        finish_profile()
        raise


def _run_serve_armed(config: Config, finish_profile, tracing: bool):
    import time as _time

    from .serve import ServeHTTP
    from .serve.server import build_server

    booster = Booster(params=_config_to_params(config),
                      model_file=config.input_model)
    fleet = None
    placement = None
    if config.serve_replicas > 1:
        from .serve import (Fleet, Router, RouterConfig, SLOConfig,
                            serve_config_from)

        fleet = Fleet(booster, n_replicas=config.serve_replicas,
                      config=serve_config_from(config))
        server = Router(fleet, RouterConfig(
            health_period_ms=config.router_health_period_ms,
            eject_after=config.router_eject_after,
            readmit_after=config.router_readmit_after,
            retry_max=config.router_retry_max,
            hedge_ms=config.router_hedge_ms,
            deadline_ms=config.router_deadline_ms,
            slo=SLOConfig(
                availability_target=config.serve_slo_availability_target,
                latency_ms=config.serve_slo_latency_ms,
                latency_target=config.serve_slo_latency_target,
                fast_window_s=config.serve_slo_fast_window_s,
                slow_window_s=config.serve_slo_slow_window_s,
            )))
        log_info(f"serve: fleet of {config.serve_replicas} replicas "
                 f"({fleet.version()}) behind the router")
    else:
        server = build_server(booster, config)
    if config.tenant_manifest:
        # multi-tenant serving (serve/tenants.py): one named lineage per
        # manifest entry, each seeded with the input model (re-publish
        # per tenant over the registry from then on); shared-shape
        # tenants serve through one compiled executable
        from .serve import PlacementConfig, PlacementController, \
            TenantRegistry

        backend = fleet if fleet is not None else server
        tenreg = TenantRegistry(backend)
        specs = tenreg.add_manifest(config.tenant_manifest)
        for spec in specs:
            tenreg.publish(spec.name, booster)
        log_info(f"serve: {len(specs)} tenant(s) published "
                 f"({', '.join(s.name for s in specs)})")
        if fleet is not None and config.placement_replicas_per_tenant:
            placement = PlacementController(fleet, server, PlacementConfig(
                replicas_per_tenant=config.placement_replicas_per_tenant,
                burn_threshold=config.placement_burn_threshold,
                occupancy_frac=config.placement_occupancy_frac,
                cooldown_s=config.placement_cooldown_s))
            placement.assign()
    http = ServeHTTP(server, port=config.serve_http_port).start()
    log_info(f"serve: HTTP listening on 127.0.0.1:{http.port} "
             "(POST /predict, GET /metrics, GET /healthz)")
    try:
        deadline = (_time.monotonic() + config.serve_duration_s
                    if config.serve_duration_s > 0 else None)
        while deadline is None or _time.monotonic() < deadline:
            step = 3600.0 if placement is None else 1.0
            if deadline is not None:
                step = min(step, max(deadline - _time.monotonic(), 0.0))
            _time.sleep(step)
            if placement is not None:
                placement.step()
    except KeyboardInterrupt:
        log_info("serve: interrupted")
    finally:
        import json as _json

        http.shutdown()
        snap = server.metrics_snapshot()
        obs_dir = config.obs_dir or os.environ.get("LGBMV1_OBS_DIR", "")
        if obs_dir:
            # per-process artifacts for tools/obs_aggregate.py — with
            # THIS replica's registry, so the merged snapshot carries
            # its serve counters next to the loadgen's client view
            from .obs import agg as obs_agg

            obs_agg.export_process_artifacts(
                obs_dir, registry=server.metrics.registry)
            log_info(f"serve: wrote obs artifacts to {obs_dir}")
        server.close()
        if fleet is not None:
            fleet.close()
        finish_profile()
        if tracing:
            from .obs import trace as obs_trace

            if config.trace_out:
                doc = obs_trace.export_chrome(config.trace_out)
                log_info(f"serve: wrote span trace to {config.trace_out} "
                         f"({len(doc['traceEvents'])} events)")
            obs_trace.disarm()
        log_info("serve: final metrics " + _json.dumps(snap))
    return server, http


def run_refit(config: Config) -> None:
    """reference: Application::Run task=refit (application.h) —
    re-estimate the leaf values of input_model on new data."""
    if not config.input_model:
        log_fatal("No model file: set input_model=<file>")
    booster = Booster(model_file=config.input_model)
    df = load_data_file(config.data, has_header=config.header,
                        label_column=config.label_column)
    refitted = booster.refit(df.X, df.label,
                             decay_rate=config.refit_decay_rate)
    refitted.save_model(config.output_model)
    log_info(f"Finished refit; model saved to {config.output_model}")


def run_convert_model(config: Config) -> None:
    """reference: GBDT::SaveModelToIfElse, gbdt_model_text.cpp:122-304 —
    compile the model into standalone C++ if-else code."""
    from .io.model_codegen import model_to_cpp

    if not config.input_model:
        log_fatal("No model file: set input_model=<file>")
    if config.convert_model_language not in ("", "cpp"):
        log_fatal(f"convert_model_language="
                  f"{config.convert_model_language} is not supported; "
                  "only 'cpp' code generation is available")
    booster = Booster(model_file=config.input_model)
    code = model_to_cpp(booster._loaded)
    out = config.convert_model or "gbdt_prediction.cpp"
    from .utils import fileio

    with fileio.open_file(out, "w") as fh:
        fh.write(code)
    log_info(f"Converted model to C++ code at {out}")


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        return 1
    config = Config.from_cli(argv)
    # phase timing (reference: USE_TIMETAG global_timer, common.h:1054-1138;
    # scopes live in gbdt.py/cli.py; report printed at exit)
    global_timer.enabled = config.verbosity >= 1
    # forensics & fleet identity (obs/): stamp who this process is,
    # size the always-on event ring, and arm the crash-dump flight
    # recorder when a crash dir is configured (knob or env — the env
    # form reaches subprocess runs the chaos driver kills)
    from .obs import events as obs_events

    obs_events.set_identity(role=config.task)
    if config.obs_event_ring != obs_events.DEFAULT_RING_EVENTS:
        obs_events.configure(config.obs_event_ring)
    crash_dir = config.crash_dir or os.environ.get("LGBMV1_CRASH_DIR", "")
    if crash_dir:
        from .obs import dump as obs_dump

        obs_dump.arm(crash_dir, config=_config_to_params(config))
    if config.num_machines > 1 or config.machines:
        # reference: Application::InitTrain -> Network::Init
        # (application.cpp:167); here the cluster bring-up is jax.distributed
        from .parallel.cluster import init_cluster

        init_cluster(config)
    task = config.task
    if task == "train":
        run_train(config)
    elif task == "save_binary":
        run_save_binary(config)
    elif task in ("predict", "prediction", "test"):
        run_predict(config)
    elif task == "serve":
        run_serve(config)
    elif task == "refit":
        run_refit(config)
    elif task == "convert_model":
        run_convert_model(config)
    else:
        log_fatal(f"Unknown task: {task}")
    obs_dir = config.obs_dir or os.environ.get("LGBMV1_OBS_DIR", "")
    if obs_dir and task != "serve":   # serve exports its own (with the
        # replica's registry) inside run_serve's shutdown path
        from .obs import agg as obs_agg

        paths = obs_agg.export_process_artifacts(obs_dir)
        log_info(f"Wrote obs artifacts to {obs_dir} "
                 f"({', '.join(sorted(paths))}; merge with "
                 "tools/obs_aggregate.py)")
    if global_timer.enabled and global_timer.totals:
        log_info(global_timer.report())
    return 0


if __name__ == "__main__":
    sys.exit(main())
