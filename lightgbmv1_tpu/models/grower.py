"""Leaf-wise (best-first) tree growth, fully on device.

TPU-native re-design of the reference SerialTreeLearner
(``SerialTreeLearner::Train`` src/treelearner/serial_tree_learner.cpp:152-202,
``FindBestSplits`` :316, ``SplitInner`` :541-659) and DataPartition
(src/treelearner/data_partition.hpp:101-120).

Design mapping (SURVEY.md §7):

* The reference's permuted row-index partition becomes a per-row ``leaf_id``
  array; ``DataPartition::Split``'s parallel scatter becomes a vectorized
  ``where`` over all rows.
* The histogram pool with parent-reuse + the smaller/larger-leaf subtraction
  trick (``BeforeFindBestSplit`` serial_tree_learner.cpp:274-314,
  ``FeatureHistogram::Subtract`` feature_histogram.hpp:79) is kept exactly:
  one histogram pass over the smaller child per split, larger child =
  parent - smaller (a pure vector op).
* The whole per-tree loop is a ``lax.fori_loop`` of ``num_leaves - 1`` steps
  under one ``jit``; a latched ``done`` flag reproduces the reference's
  early stop when no split has positive gain
  (serial_tree_learner.cpp:192-195).
* Distribution is injected through ``hist_fn`` (see parallel/): the
  data-parallel learner wraps it in a psum over the row mesh axis — the
  analog of DataParallelTreeLearner's ReduceScatter
  (data_parallel_tree_learner.cpp:155-173) — while this module stays
  topology-agnostic.
"""

from __future__ import annotations

import functools
import math
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.binning import MISSING_NAN, MISSING_ZERO
from ..ops.split import (
    NO_CONSTRAINT,
    FeatureMeta,
    SplitParams,
    find_best_split,
    leaf_output,
    smooth_output,
)
from .tree import TreeArrays

# Level-wise frontier chunk cap — the analog of the wave grower's 128-slot
# wave_size cap: the level-wise partition + smaller-child label passes are
# (Ld, N) broadcast-compares over the whole frontier, and a wide level
# (Ld up to num_leaves/2) would materialize multi-GB intermediates at
# bench N (128 x 1M int32 is already 512 MB).  Frontier slices are
# processed in groups of at most this many splits — disjoint row
# ownership makes the chunked int32 accumulation bit-identical to the
# single-pass sum (tests/test_partition_grower.py pins this).  Lowered by
# tests to exercise the chunked branches.
_LEVEL_CHUNK = 128


class GrowerState(NamedTuple):
    leaf_id: jax.Array        # (N,) int32
    hist_pool: jax.Array      # (L, F, B, 3)
    leaf_sums: jax.Array      # (L, 3)
    leaf_depth: jax.Array     # (L,) int32
    best_gain: jax.Array      # (L,)
    best_feat: jax.Array      # (L,) int32
    best_bin: jax.Array       # (L,) int32
    best_dl: jax.Array        # (L,) bool
    best_left: jax.Array      # (L, 3)
    best_right: jax.Array     # (L, 3)
    best_iscat: jax.Array     # (L,) bool
    best_bitset: jax.Array    # (L, W) uint32
    leaf_constr: jax.Array    # (L, 2) — per-leaf [min, max] output bound
                              # (reference BasicLeafConstraints entries_)
    leaf_out: jax.Array       # (L,) — current leaf output values (smoothing)
    leaf_used: jax.Array      # (L, F) bool — branch features per leaf
                              # (reference Tree::branch_features)
    cegb_used: jax.Array      # (F,) bool — model-level used features (CEGB)
    cegb_marks: jax.Array     # (N, F) bool — rows already charged for a
                              # feature (cegb_penalty_feature_lazy;
                              # (1, 1) dummy when lazy costs are off)
    order: jax.Array          # (N+CAPMAX,) int32 — rows grouped by leaf
                              # (reference DataPartition indices_; ghost
                              # entries hold N). dummy (1,) when masked mode
    leaf_begin: jax.Array     # (L,) int32 — segment begin per leaf
    leaf_phys: jax.Array      # (L,) int32 — physical rows per leaf
    forced_leaf: jax.Array    # (S, 2) int32 — realized [left, right] leaf ids
                              # per applied forced step (-1 = not applied);
                              # dummy (1, 2) when no forced splits
    tree: TreeArrays
    leaf_is_left: jax.Array   # (L,) bool
    num_leaves: jax.Array     # () int32
    done: jax.Array           # () bool


def forced_split_stats(hf, parent_sum, ffeat, fbin, fdl, meta, params):
    """Left/right sums + ACTUAL gain of a forced split, from the leaf's
    histogram of the forced feature (the reference computes the real
    SplitInfo for forced thresholds, serial_tree_learner.cpp:500-520).
    Shared by the sequential and level-wise growers so the NaN
    default-direction accounting and the relative-gain convention cannot
    drift apart."""
    from ..ops.split import leaf_gain

    cumf = jnp.cumsum(hf, axis=0)                    # (B, 3)
    has_nan = meta.missing_type[ffeat] == MISSING_NAN
    has_zero = meta.missing_type[ffeat] == MISSING_ZERO
    # the missing mass (NaN bin or zero-as-missing bin) rides with the
    # default direction, independent of its position vs the threshold
    miss_bin = jnp.where(has_nan, jnp.maximum(meta.nan_bin[ffeat], 0),
                         meta.zero_bin[ffeat])
    miss_c = hf[miss_bin] * jnp.where(has_nan | has_zero, 1.0, 0.0)
    in_cum = (has_nan | has_zero) & (miss_bin <= fbin)
    flsum = cumf[fbin] + miss_c * (
        jnp.asarray(fdl).astype(jnp.float32) - in_cum.astype(jnp.float32))
    frsum = parent_sum - flsum
    fgain = (leaf_gain(flsum[0], flsum[1], params)
             + leaf_gain(frsum[0], frsum[1], params)
             - leaf_gain(parent_sum[0], parent_sum[1], params)
             - params.min_gain_to_split)
    return flsum, frsum, fgain


def allowed_features_for(groups, used):
    """reference ColSampler::GetByNode: branch features + union of
    interaction-constraint groups containing ALL branch features
    (src/treelearner/col_sampler.hpp:92-112).  ``groups`` is the (G, F)
    bool constraint matrix or None; ``used`` the leaf's (F,) branch-feature
    mask.  Shared by the sequential, level-wise and wave growers."""
    if groups is None:
        return jnp.ones_like(used)
    fits = jnp.all(groups | ~used[None, :], axis=1)       # (G,)
    return used | jnp.any(groups & fits[:, None], axis=0)


def _node_feature_mask(key, uid, base_mask, fraction: float):
    """Per-node column sampling (reference: ColSampler bynode,
    src/treelearner/col_sampler.hpp:20)."""
    if fraction >= 1.0:
        return base_mask
    F = base_mask.shape[0]
    scores = jax.random.uniform(jax.random.fold_in(key, uid), (F,))
    scores = jnp.where(base_mask, scores, jnp.inf)
    n_allowed = jnp.sum(base_mask)
    k = jnp.maximum(1, jnp.ceil(fraction * n_allowed)).astype(jnp.int32)
    thresh = jnp.sort(scores)[jnp.maximum(k - 1, 0)]
    return base_mask & (scores <= thresh)


def make_leafwise_grower(
    *,
    num_leaves: int,
    num_bins: int,
    meta: FeatureMeta,
    params: SplitParams,
    max_depth: int = -1,
    feature_fraction_bynode: float = 1.0,
    monotone_penalty: float = 0.0,
    interaction_groups=None,
    forced_splits=None,
    cegb_coupled=None,
    cegb_lazy=None,
    partition: bool = False,
    hist_fn: Callable = None,
    split_fn: Callable = None,
    sums_fn: Callable = None,
    bins_of_fn: Callable = None,
    num_features: int = 0,
    hist_pool_mb: float = -1.0,
):
    """Build the jittable ``grow(binned, g3, base_mask, key)`` function.

    ``partition=True`` selects the DataPartition-based fast path (reference:
    src/treelearner/data_partition.hpp — rows kept grouped by leaf in an
    index array): each split only touches its parent's segment and the
    smaller child's histogram is built over COMPACTED rows, so per-split
    cost is O(segment) instead of O(num_data).  Dynamic segment sizes are
    bucketed into a few static capacities dispatched with ``lax.switch``.

    ``forced_splits``: optional (S, 5) int array [parent_step, side, feature,
    bin, default_left] applied as the first S steps in BFS order
    (parse_forced_splits format; parent_step = -1 is the root, side selects
    the parent step's realized left/right child leaf — reference:
    SerialTreeLearner::ForceSplits, serial_tree_learner.cpp:427-539).

    ``hist_fn(binned, g3, leaf_id, target_leaf) -> (F, B, 3)`` — histogram of
    one leaf's rows (globally summed in distributed mode).
    ``split_fn(hist, parent_sum, feature_mask, key, uid, constraint, depth,
    parent_output) -> SplitResult`` — defaults to the local vectorized
    search; the feature-parallel learner substitutes a sharded search +
    cross-shard argmax.  ``constraint`` is the leaf's monotone [min, max]
    output bound; ``parent_output`` the leaf's current value (path
    smoothing).
    ``sums_fn(g3) -> (3,)`` — root grad/hess/count totals (psum over the row
    mesh axis in data-parallel mode; the analog of the reference's root
    sum Allreduce, data_parallel_tree_learner.cpp:126-151).
    ``interaction_groups``: optional (G, F) bool matrix of interaction
    constraints (reference ColSampler::GetByNode, col_sampler.hpp:92-112).
    """
    L = num_leaves
    L1 = max(L - 1, 1)
    use_mc = bool(np.asarray(meta.monotone_type).any())
    groups = (jnp.asarray(interaction_groups)
              if interaction_groups is not None else None)
    S_forced = 0 if forced_splits is None else min(len(forced_splits), L - 1)
    if S_forced:
        # (S, 5) [parent_step, side, feature, bin, dl] — leaf ids resolved at
        # runtime from the realized forced_leaf table (see GrowerState)
        f_parent = jnp.asarray(forced_splits[:S_forced, 0], jnp.int32)
        f_side = jnp.asarray(forced_splits[:S_forced, 1], jnp.int32)
        f_feat = jnp.asarray(forced_splits[:S_forced, 2], jnp.int32)
        f_bin = jnp.asarray(forced_splits[:S_forced, 3], jnp.int32)
        f_dl = jnp.asarray(forced_splits[:S_forced, 4] != 0)

    use_cegb = ((params.cegb_penalty_split > 0) or (cegb_coupled is not None)
                or (cegb_lazy is not None))
    coupled = (jnp.asarray(cegb_coupled, jnp.float32)
               if cegb_coupled is not None else None)
    lazy = (jnp.asarray(cegb_lazy, jnp.float32)
            if cegb_lazy is not None else None)
    if lazy is not None and partition:
        raise ValueError("cegb_penalty_feature_lazy requires the masked "
                         "leaf-wise grower (per-row leaf ids)")

    def cegb_penalty_vec(parent_cnt, used_model, unmarked_cnt=None):
        """reference: CostEfficientGradientBoosting::DetlaGain —
        tradeoff*(penalty_split*n_leaf + coupled_penalty[unused features]
        + lazy_penalty[f]*#unmarked-rows-in-leaf
        (CalculateOndemandCosts, cost_effective_gradient_boosting.hpp:125))."""
        if not use_cegb:
            return None
        pen = jnp.full(meta.num_bins.shape[0],
                       params.cegb_tradeoff * params.cegb_penalty_split
                       * parent_cnt, jnp.float32)
        if coupled is not None:
            pen = pen + params.cegb_tradeoff * coupled * (
                ~used_model).astype(jnp.float32)
        if lazy is not None and unmarked_cnt is not None:
            pen = pen + params.cegb_tradeoff * lazy * unmarked_cnt
        return pen

    if split_fn is None:
        def split_fn(hist, parent, mask, key, uid, constraint, depth,
                     parent_output, cegb_pen=None):
            rk = jax.random.fold_in(key, uid + 1_000_003 + params.extra_seed) \
                if params.extra_trees else None
            return find_best_split(hist, parent, meta, mask, params,
                                   constraint, depth, monotone_penalty,
                                   parent_output, rk, cegb_pen)

    def allowed_features(used):
        return allowed_features_for(groups, used)

    if sums_fn is None:
        def sums_fn(g3):
            # ordered scatter fold into one slot, NOT jnp.sum: scatter-add
            # applies the row additions sequentially in row order, which
            # the out-of-core row-block trainer CONTINUES across blocks
            # bit-exactly (ops/histogram.sums_accum) — jnp.sum's internal
            # reduction tree is shape-dependent and not streamable.  Same
            # mechanism as the histogram pass itself; value differs from
            # jnp.sum only in the last ulp.
            return jnp.zeros((1, 3), jnp.float32).at[
                jnp.zeros(g3.shape[0], jnp.int32)].add(g3)[0]

    if bins_of_fn is None:
        def bins_of_fn(binned, feat):
            return binned[feat]

    # ---- histogram pool sizing (reference: HistogramPool LRU bounded by
    # histogram_pool_size MB, feature_histogram.hpp:1061-1290).  The pool
    # holds one (F, B, 3) f32 histogram per leaf to enable the subtraction
    # trick; when it would exceed the cap (histogram_pool_size > 0) or the
    # 512 MB auto bound (histogram_pool_size < 0), switch to pool-free mode:
    # both children's histograms are built directly (2 passes per split,
    # the reference's no-cache behavior) and HBM stays O(F·B) regardless of
    # num_leaves.  Forced splits read parent histograms after the fact and
    # therefore keep the pool.
    F_pool = num_features if num_features else len(np.asarray(meta.num_bins))
    pool_bytes = float(L) * F_pool * num_bins * 3 * 4
    cap_bytes = (hist_pool_mb * (1 << 20) if hist_pool_mb > 0
                 else 512.0 * (1 << 20))
    use_pool = S_forced > 0 or pool_bytes <= cap_bytes
    if not use_pool:
        from ..utils.log import log_info

        log_info(
            f"Histogram pool would need {pool_bytes / (1 << 20):.0f} MB "
            f"(> {cap_bytes / (1 << 20):.0f} MB cap); using pool-free "
            "growth (children histograms rebuilt per split)")

    def clamp_out(sums, constr, parent_out=0.0):
        out = leaf_output(sums[0], sums[1], params)
        if params.path_smooth > 0:
            out = smooth_output(out, sums[2], parent_out, params)
        if not use_mc:
            return out
        return jnp.clip(out, constr[0], constr[1])

    def apply_decision(binned, leaf_id, leaf, new_leaf, feat, thr, dl,
                       is_cat, bitset):
        with jax.named_scope("lgbm.partition"):
            bins_f = bins_of_fn(binned, feat)       # (N,) original bins
            is_na = ((meta.missing_type[feat] == MISSING_NAN)
                     & (bins_f == meta.nan_bin[feat])) | (
                (meta.missing_type[feat] == MISSING_ZERO)
                & (bins_f == meta.zero_bin[feat]))
            go_left = jnp.where(is_na, dl, bins_f <= thr)
            bi = bins_f.astype(jnp.int32)
            word = bitset[bi >> 5]
            in_set = ((word >> (bi.astype(jnp.uint32) & 31)) & 1) == 1
            go_left = jnp.where(is_cat, in_set, go_left)
            return jnp.where((leaf_id == leaf) & (~go_left), new_leaf,
                             leaf_id)

    def grow(binned, g3, base_mask, key, cegb_used=None):
        N = binned.shape[1]
        F = base_mask.shape[0]    # ORIGINAL features (binned may be the
                                  # narrower EFB bundle matrix)
        B = num_bins
        marks_in = None
        if isinstance(cegb_used, (tuple, list)):
            cegb_used, marks_in = cegb_used
        if cegb_used is None:
            cegb_used = jnp.zeros(F, bool)
        if lazy is not None:
            marks0 = (marks_in if marks_in is not None
                      else jnp.zeros((N, F), bool))
        else:
            marks0 = jnp.zeros((1, 1), bool)

        # ---- bucketed static capacities for the partition fast path -----
        if partition:
            caps = []
            c = 2048
            while c < N:
                caps.append(c)
                c = (c * 3) // 2
            caps.append(N)
            capmax = caps[-1]

            def bucket_of(n):
                b = jnp.zeros((), jnp.int32)
                for cc in caps[:-1]:
                    b = b + (n > cc).astype(jnp.int32)
                return b

            def partition_segment(order, s_begin, n_p, feat, thr, dl,
                                  iscat, bitset):
                """Stable two-way partition of one leaf's segment
                (reference DataPartition::Split, data_partition.hpp:101)."""
                bins_row = bins_of_fn(binned, feat)        # (N,) orig bins

                def make_branch(CAP):
                    def br(op):
                        order, s_begin, n_p, thr, dl, iscat, bitset = op
                        seg = lax.dynamic_slice(order, (s_begin,), (CAP,))
                        bseg = jnp.take(bins_row, seg, mode="fill",
                                        fill_value=0)
                        valid = jnp.arange(CAP) < n_p
                        is_na = ((meta.missing_type[feat]
                                  == MISSING_NAN)
                                 & (bseg == meta.nan_bin[feat])) | (
                            (meta.missing_type[feat] == MISSING_ZERO)
                            & (bseg == meta.zero_bin[feat]))
                        gl = jnp.where(is_na, dl, bseg <= thr)
                        bi = bseg.astype(jnp.int32)
                        word = bitset[bi >> 5]
                        in_set = ((word >> (bi.astype(jnp.uint32) & 31))
                                  & 1) == 1
                        gl = jnp.where(iscat, in_set, gl) & valid
                        n_l = gl.sum().astype(jnp.int32)
                        posl = jnp.where(gl, size=CAP, fill_value=CAP)[0]
                        posr = jnp.where((~gl) & valid, size=CAP,
                                         fill_value=CAP)[0]
                        lrows = jnp.take(seg, posl, mode="fill", fill_value=N)
                        rrows = jnp.take(seg, posr, mode="fill", fill_value=N)
                        pos = jnp.arange(CAP)
                        rpick = jnp.take(rrows,
                                         jnp.clip(pos - n_l, 0, CAP - 1))
                        comb = jnp.where(pos < n_l, lrows, rpick)
                        comb = jnp.where(valid, comb, seg)  # ghosts untouched
                        order2 = lax.dynamic_update_slice(order, comb,
                                                          (s_begin,))
                        return order2, n_l
                    return br

                with jax.named_scope("lgbm.partition"):
                    return lax.switch(
                        bucket_of(n_p), [make_branch(cc) for cc in caps],
                        (order, s_begin, n_p, thr, dl, iscat, bitset))

            def hist_compact(order, s_begin, n_s):
                """Histogram of one COMPACTED segment (the smaller child)
                — the reference's ordered-gradient smaller-leaf pass.  The
                slice capacity can exceed the segment, so rows beyond n_s
                (they belong to OTHER leaves) are zero-masked."""
                def make_branch(CAP):
                    def br(op):
                        order, s_begin, n_s = op
                        rows = lax.dynamic_slice(order, (s_begin,), (CAP,))
                        in_seg = jnp.arange(CAP) < n_s
                        bins_sub = jnp.take(binned, rows, axis=1,
                                            mode="fill", fill_value=0)
                        g3_sub = jnp.take(g3, rows, axis=0, mode="fill",
                                          fill_value=0.0)
                        g3_sub = jnp.where(in_seg[:, None], g3_sub, 0.0)
                        return hist_fn(bins_sub, g3_sub,
                                       jnp.zeros(CAP, jnp.int32),
                                       jnp.asarray(0, jnp.int32))
                    return br

                return lax.switch(
                    bucket_of(n_s), [make_branch(cc) for cc in caps],
                    (order, s_begin, n_s))

            order0 = jnp.concatenate([
                jnp.arange(N, dtype=jnp.int32),
                jnp.full(capmax, N, jnp.int32)])
            leaf_begin0 = jnp.zeros(L, jnp.int32)
            leaf_phys0 = jnp.zeros(L, jnp.int32).at[0].set(N)
        else:
            order0 = jnp.zeros(1, jnp.int32)
            leaf_begin0 = jnp.zeros(L, jnp.int32)
            leaf_phys0 = jnp.zeros(L, jnp.int32)

        leaf_id = jnp.zeros(N, jnp.int32)
        hist0 = hist_fn(binned, g3, leaf_id, jnp.asarray(0, jnp.int32))
        root_sum = sums_fn(g3)
        mask0 = _node_feature_mask(key, 0, base_mask, feature_fraction_bynode)
        no_constr = jnp.asarray(NO_CONSTRAINT, jnp.float32)
        used0 = jnp.zeros(F, bool)
        mask0 = mask0 & allowed_features(used0)
        out0 = leaf_output(root_sum[0], root_sum[1], params)
        if params.path_smooth > 0:
            out0 = smooth_output(out0, root_sum[2], 0.0, params)
        unmk0 = ((~marks0).sum(axis=0).astype(jnp.float32)
                 if lazy is not None else None)
        res0 = split_fn(hist0, root_sum, mask0, key, 0, no_constr, 0, out0,
                        cegb_penalty_vec(root_sum[2], cegb_used, unmk0))

        from ..models.tree import empty_tree

        W = res0.cat_bitset.shape[0]
        st = GrowerState(
            leaf_id=leaf_id,
            hist_pool=(jnp.zeros((L,) + hist0.shape,
                                 jnp.float32).at[0].set(hist0)
                       if use_pool else jnp.zeros((1, 1, 1, 3), jnp.float32)),
            leaf_sums=jnp.zeros((L, 3), jnp.float32).at[0].set(root_sum),
            leaf_depth=jnp.zeros(L, jnp.int32),
            best_gain=jnp.full(L, -jnp.inf, jnp.float32).at[0].set(res0.gain),
            best_feat=jnp.zeros(L, jnp.int32).at[0].set(res0.feature),
            best_bin=jnp.zeros(L, jnp.int32).at[0].set(res0.threshold_bin),
            best_dl=jnp.zeros(L, bool).at[0].set(res0.default_left),
            best_left=jnp.zeros((L, 3), jnp.float32).at[0].set(res0.left_sum),
            best_right=jnp.zeros((L, 3), jnp.float32).at[0].set(res0.right_sum),
            best_iscat=jnp.zeros(L, bool).at[0].set(res0.is_cat),
            best_bitset=jnp.zeros((L, W), jnp.uint32).at[0].set(res0.cat_bitset),
            leaf_constr=jnp.tile(jnp.asarray(NO_CONSTRAINT, jnp.float32), (L, 1)),
            leaf_out=jnp.zeros(L, jnp.float32).at[0].set(out0),
            leaf_used=jnp.zeros((L, F), bool),
            cegb_used=cegb_used,
            cegb_marks=marks0,
            order=order0,
            leaf_begin=leaf_begin0,
            leaf_phys=leaf_phys0,
            forced_leaf=jnp.full((max(S_forced, 1), 2), -1, jnp.int32),
            tree=empty_tree(L, W),
            leaf_is_left=jnp.zeros(L, bool),
            num_leaves=jnp.asarray(1, jnp.int32),
            done=jnp.asarray(L <= 1),
        )

        def body(s, st: GrowerState) -> GrowerState:
            leaf = jnp.argmax(st.best_gain).astype(jnp.int32)
            gain = st.best_gain[leaf]
            is_forced = jnp.asarray(False)
            if S_forced:
                # forced splits occupy the first S steps (reference
                # ForceSplits BFS, serial_tree_learner.cpp:427-539); a forced
                # split that would create an empty child is skipped, and any
                # step whose parent step was skipped is skipped too (the
                # realized forced_leaf entry stays -1)
                sidx = jnp.minimum(s, S_forced - 1)
                maybe = s < S_forced
                pstep = f_parent[sidx]
                fleaf_raw = jnp.where(
                    pstep < 0, 0,
                    st.forced_leaf[jnp.maximum(pstep, 0), f_side[sidx]])
                parent_ok = (pstep < 0) | (fleaf_raw >= 0)
                fleaf = jnp.maximum(fleaf_raw, 0)
                ffeat = f_feat[sidx]
                fthr, fdl = f_bin[sidx], f_dl[sidx]
                flsum, frsum, forced_gain = forced_split_stats(
                    st.hist_pool[fleaf, ffeat], st.leaf_sums[fleaf],
                    ffeat, fthr, fdl, meta, params)
                ok_f = maybe & parent_ok & (flsum[2] > 0) & (frsum[2] > 0)
                is_forced = ok_f
                leaf = jnp.where(ok_f, fleaf, leaf)
                gain = jnp.where(ok_f, forced_gain, gain)
            active = (~st.done) & ((gain > 0) | is_forced)

            def do_split(st: GrowerState) -> GrowerState:
                nl = st.num_leaves                    # new (right-child) leaf index
                node = nl - 1                         # internal node index
                feat = st.best_feat[leaf]
                thr = st.best_bin[leaf]
                dl = st.best_dl[leaf]
                lsum = st.best_left[leaf]
                rsum = st.best_right[leaf]
                iscat = st.best_iscat[leaf]
                bitset = st.best_bitset[leaf]
                if S_forced:
                    sidx2 = jnp.minimum(s, S_forced - 1)
                    feat = jnp.where(is_forced, f_feat[sidx2], feat)
                    thr = jnp.where(is_forced, f_bin[sidx2], thr)
                    dl = jnp.where(is_forced, f_dl[sidx2], dl)
                    lsum = jnp.where(is_forced, flsum, lsum)
                    rsum = jnp.where(is_forced, frsum, rsum)
                    iscat = iscat & (~is_forced)
                    bitset = jnp.where(is_forced,
                                       jnp.zeros_like(bitset), bitset)
                    # record the REALIZED child leaf ids of this forced step
                    # (left child keeps the parent's leaf id, right child is
                    # the new leaf) so descendant forced steps resolve
                    # against actual leaf numbering
                    forced_next = st.forced_leaf.at[sidx2].set(
                        jnp.where(is_forced, jnp.stack([leaf, nl]),
                                  st.forced_leaf[sidx2]))
                else:
                    forced_next = st.forced_leaf
                parent_sum = st.leaf_sums[leaf]

                if partition:
                    s_begin = st.leaf_begin[leaf]
                    n_p = st.leaf_phys[leaf]
                    order2, n_l_phys = partition_segment(
                        st.order, s_begin, n_p, feat, thr, dl, iscat, bitset)
                    leaf_id = st.leaf_id      # reconstructed once at the end
                else:
                    order2, n_l_phys = st.order, jnp.asarray(0, jnp.int32)
                    leaf_id = apply_decision(binned, st.leaf_id, leaf, nl,
                                             feat, thr, dl, iscat, bitset)

                # monotone constraint propagation (reference:
                # BasicLeafConstraints::Update, monotone_constraints.hpp:99-117)
                pconstr = st.leaf_constr[leaf]
                pout = st.leaf_out[leaf]
                out_l = clamp_out(lsum, pconstr, pout)
                out_r = clamp_out(rsum, pconstr, pout)
                if use_mc:
                    mono = meta.monotone_type[feat]
                    mid = 0.5 * (out_l + out_r)
                    upd = (~iscat) & (mono != 0)
                    new_max_l = jnp.where(upd & (mono > 0),
                                          jnp.minimum(pconstr[1], mid), pconstr[1])
                    new_min_l = jnp.where(upd & (mono < 0),
                                          jnp.maximum(pconstr[0], mid), pconstr[0])
                    new_max_r = jnp.where(upd & (mono < 0),
                                          jnp.minimum(pconstr[1], mid), pconstr[1])
                    new_min_r = jnp.where(upd & (mono > 0),
                                          jnp.maximum(pconstr[0], mid), pconstr[0])
                    constr_l = jnp.stack([new_min_l, new_max_l])
                    constr_r = jnp.stack([new_min_r, new_max_r])
                else:
                    constr_l = constr_r = pconstr

                # histogram-subtraction trick: one pass over the smaller child
                if partition:
                    n_r_phys = n_p - n_l_phys
                    smaller_is_left = n_l_phys <= n_r_phys
                    sm_begin = jnp.where(smaller_is_left, s_begin,
                                         s_begin + n_l_phys)
                    sm_n = jnp.minimum(n_l_phys, n_r_phys)
                    h_small = hist_compact(order2, sm_begin, sm_n)
                else:
                    smaller_is_left = lsum[2] <= rsum[2]
                    smaller = jnp.where(smaller_is_left, leaf, nl)
                    h_small = hist_fn(binned, g3, leaf_id, smaller)
                if use_pool:
                    h_parent = st.hist_pool[leaf]
                    h_left = jnp.where(smaller_is_left, h_small,
                                       h_parent - h_small)
                    h_right = h_parent - h_left
                    pool = st.hist_pool.at[leaf].set(h_left).at[nl].set(h_right)
                else:
                    # pool-free: build the larger child directly too
                    if partition:
                        lg_begin = jnp.where(smaller_is_left,
                                             s_begin + sm_n, s_begin)
                        h_large = hist_compact(order2, lg_begin, n_p - sm_n)
                    else:
                        larger = jnp.where(smaller_is_left, nl, leaf)
                        h_large = hist_fn(binned, g3, leaf_id, larger)
                    h_left = jnp.where(smaller_is_left, h_small, h_large)
                    h_right = jnp.where(smaller_is_left, h_large, h_small)
                    pool = st.hist_pool

                d = st.leaf_depth[leaf] + 1
                depth_ok = (max_depth <= 0) | (d < max_depth)

                used_child = st.leaf_used[leaf].at[feat].set(True)
                allow_child = allowed_features(used_child)
                mask_l = _node_feature_mask(
                    key, 2 * s + 1, base_mask, feature_fraction_bynode
                ) & allow_child
                mask_r = _node_feature_mask(
                    key, 2 * s + 2, base_mask, feature_fraction_bynode
                ) & allow_child
                cegb_next = st.cegb_used.at[feat].set(True) \
                    if use_cegb else st.cegb_used
                if lazy is not None:
                    # mark the split leaf's rows for the split feature
                    # (UpdateLeafBestSplits, cegb hpp:110-121), THEN price
                    # the children's candidates by their unmarked rows
                    in_parent = st.leaf_id == leaf
                    marks_next = st.cegb_marks | (
                        in_parent[:, None]
                        & jax.nn.one_hot(feat, F, dtype=bool))
                    notm = (~marks_next).astype(jnp.float32)
                    unmk_l = (leaf_id == leaf).astype(jnp.float32) @ notm
                    unmk_r = (leaf_id == nl).astype(jnp.float32) @ notm
                else:
                    marks_next = st.cegb_marks
                    unmk_l = unmk_r = None
                res_l = split_fn(h_left, lsum, mask_l, key, 2 * s + 1,
                                 constr_l, d, out_l,
                                 cegb_penalty_vec(lsum[2], cegb_next, unmk_l))
                res_r = split_fn(h_right, rsum, mask_r, key, 2 * s + 2,
                                 constr_r, d, out_r,
                                 cegb_penalty_vec(rsum[2], cegb_next, unmk_r))
                gain_l = jnp.where(depth_ok, res_l.gain, -jnp.inf)
                gain_r = jnp.where(depth_ok, res_r.gain, -jnp.inf)

                t = st.tree
                # re-wire the parent pointer that pointed at ~leaf
                p = t.leaf_parent[leaf]
                p_safe = jnp.maximum(p, 0)
                was_left = st.leaf_is_left[leaf]
                lc = t.left_child.at[p_safe].set(
                    jnp.where((p >= 0) & was_left, node, t.left_child[p_safe])
                )
                rc = t.right_child.at[p_safe].set(
                    jnp.where((p >= 0) & (~was_left), node, t.right_child[p_safe])
                )
                lc = lc.at[node].set(-(leaf + 1))
                rc = rc.at[node].set(-(nl + 1))

                tree = t._replace(
                    num_leaves=nl + 1,
                    split_feature=t.split_feature.at[node].set(feat),
                    threshold_bin=t.threshold_bin.at[node].set(thr),
                    default_left=t.default_left.at[node].set(dl),
                    is_cat=t.is_cat.at[node].set(iscat),
                    cat_bitset=t.cat_bitset.at[node].set(bitset),
                    missing_type=t.missing_type.at[node].set(meta.missing_type[feat]),
                    left_child=lc,
                    right_child=rc,
                    split_gain=t.split_gain.at[node].set(gain),
                    internal_value=t.internal_value.at[node].set(pout),
                    internal_weight=t.internal_weight.at[node].set(parent_sum[1]),
                    internal_count=t.internal_count.at[node].set(parent_sum[2]),
                    leaf_value=t.leaf_value.at[leaf].set(out_l).at[nl].set(out_r),
                    leaf_weight=t.leaf_weight.at[leaf].set(lsum[1]).at[nl].set(rsum[1]),
                    leaf_count=t.leaf_count.at[leaf].set(lsum[2]).at[nl].set(rsum[2]),
                    leaf_parent=t.leaf_parent.at[leaf].set(node).at[nl].set(node),
                )

                return GrowerState(
                    leaf_id=leaf_id,
                    hist_pool=pool,
                    leaf_sums=st.leaf_sums.at[leaf].set(lsum).at[nl].set(rsum),
                    leaf_depth=st.leaf_depth.at[leaf].set(d).at[nl].set(d),
                    best_gain=st.best_gain.at[leaf].set(gain_l).at[nl].set(gain_r),
                    best_feat=st.best_feat.at[leaf].set(res_l.feature).at[nl].set(res_r.feature),
                    best_bin=st.best_bin.at[leaf]
                    .set(res_l.threshold_bin)
                    .at[nl]
                    .set(res_r.threshold_bin),
                    best_dl=st.best_dl.at[leaf].set(res_l.default_left).at[nl].set(res_r.default_left),
                    best_left=st.best_left.at[leaf].set(res_l.left_sum).at[nl].set(res_r.left_sum),
                    best_right=st.best_right.at[leaf].set(res_l.right_sum).at[nl].set(res_r.right_sum),
                    best_iscat=st.best_iscat.at[leaf].set(res_l.is_cat).at[nl].set(res_r.is_cat),
                    best_bitset=st.best_bitset.at[leaf].set(res_l.cat_bitset).at[nl].set(res_r.cat_bitset),
                    leaf_constr=st.leaf_constr.at[leaf].set(constr_l).at[nl].set(constr_r),
                    leaf_out=st.leaf_out.at[leaf].set(out_l).at[nl].set(out_r),
                    leaf_used=st.leaf_used.at[leaf].set(used_child)
                    .at[nl].set(used_child),
                    cegb_used=cegb_next,
                    cegb_marks=marks_next,
                    order=order2,
                    leaf_begin=st.leaf_begin.at[nl].set(
                        st.leaf_begin[leaf] + n_l_phys) if partition
                    else st.leaf_begin,
                    leaf_phys=st.leaf_phys.at[leaf].set(n_l_phys)
                    .at[nl].set(st.leaf_phys[leaf] - n_l_phys) if partition
                    else st.leaf_phys,
                    forced_leaf=forced_next,
                    tree=tree,
                    leaf_is_left=st.leaf_is_left.at[leaf].set(True).at[nl].set(False),
                    num_leaves=nl + 1,
                    done=st.done,
                )

            def no_split(st: GrowerState) -> GrowerState:
                return st._replace(done=jnp.asarray(True))

            return lax.cond(active, do_split, no_split, st)

        st = lax.fori_loop(0, L - 1, body, st) if L > 1 else st
        if partition and L > 1:
            # reconstruct the per-row leaf assignment from the partition
            # (one pass; the loop never touched the O(N) leaf_id array):
            # sort active segments by begin, find each position's segment by
            # searchsorted, then scatter through the row order.
            beg_eff = jnp.where(st.leaf_phys > 0, st.leaf_begin,
                                N + 1 + jnp.arange(L))
            leaf_order = jnp.argsort(beg_eff)
            sorted_begin = beg_eff[leaf_order]
            pos = jnp.arange(N)
            ordinal = jnp.clip(
                jnp.searchsorted(sorted_begin, pos, side="right") - 1, 0, L - 1)
            pos_leaf = leaf_order[ordinal].astype(jnp.int32)
            rows = st.order[:N]
            leaf_id_final = jnp.zeros(N, jnp.int32).at[rows].set(
                pos_leaf, mode="drop", unique_indices=True)
            return st.tree, leaf_id_final, root_sum
        return st.tree, st.leaf_id, root_sum

    return grow


# ---------------------------------------------------------------------------
# Level-wise (depth-wise) grower — the batched fast path
# ---------------------------------------------------------------------------


def make_levelwise_grower(
    *,
    num_leaves: int,
    num_bins: int,
    meta: FeatureMeta,
    params: SplitParams,
    max_depth: int = -1,
    feature_fraction_bynode: float = 1.0,
    monotone_penalty: float = 0.0,
    interaction_groups=None,
    cegb_coupled=None,
    forced_splits=None,
    hist_frontier_fn: Callable = None,
    split_fn: Callable = None,
    sums_fn: Callable = None,
    bins_of_fn: Callable = None,
):
    """Depth-wise tree growth with the whole frontier batched per level.

    ``forced_splits``: optional (S, 6) int array [parent_step, side,
    feature, bin, default_left, depth] in BFS order (parse_forced_splits).
    A forced step applies at its BFS depth's level: the targeted frontier
    leaf splits on the forced (feature, bin) instead of its best split,
    bypassing the gain test and the per-level budget ranking (reference:
    SerialTreeLearner::ForceSplits, serial_tree_learner.cpp:427-539 —
    forced splits occupy the top of the tree in both growth orders).

    Rationale: an exact leaf-wise step histograms ONE leaf, which on the MXU
    is a 3-row matmul (3/128 utilization).  Batching all `2^d` leaves of a
    level multiplies the matmul row count by the frontier size, which is what
    makes GBDT training MXU-bound instead of latency-bound.  Semantics match
    xgboost_hist's depthwise policy — the configuration the reference
    benchmarks itself against (docs/Experiments.rst:110-135) — with the
    ``num_leaves`` budget enforced by per-level gain ranking.

    ``hist_frontier_fn(binned, g3, leaf_id, L_level) -> (L_level, F, B, 3)``
    computes histograms for every leaf in one pass (psum-wrapped when
    data-parallel).
    """
    import math as _math

    from ..ops.split import find_best_split_batch

    L = num_leaves
    L1 = max(L - 1, 1)
    levels = _math.ceil(_math.log2(max(L, 2)))
    if max_depth > 0:
        levels = min(levels, max_depth)
    use_mc = bool(np.asarray(meta.monotone_type).any())
    groups_lw = (jnp.asarray(interaction_groups)
                 if interaction_groups is not None else None)

    S_forced = 0 if forced_splits is None else min(len(forced_splits), L - 1)
    steps_at_depth = {}
    if S_forced:
        fs_np = np.asarray(forced_splits)[:S_forced]
        if max_depth <= 0:
            # forced chains deeper than ceil(log2(L)) extend the level loop
            levels = max(levels, min(int(fs_np[:, 5].max()) + 1, L - 1))
        for s in range(S_forced):
            d = int(fs_np[s, 5])
            if d < levels:
                steps_at_depth.setdefault(d, []).append(s)

    use_cegb_lw = (params.cegb_penalty_split > 0) or (cegb_coupled is not None)
    coupled_lw = (jnp.asarray(cegb_coupled, jnp.float32)
                  if cegb_coupled is not None else None)

    def cegb_penalty_batch(parent_cnt, used_model):
        if not use_cegb_lw:
            return None
        F = meta.num_bins.shape[0]
        pen = (params.cegb_tradeoff * params.cegb_penalty_split
               * parent_cnt[:, None]) * jnp.ones((1, F), jnp.float32)
        if coupled_lw is not None:
            pen = pen + params.cegb_tradeoff * coupled_lw[None, :] * (
                ~used_model)[None, :].astype(jnp.float32)
        return pen

    if split_fn is None:
        def split_fn(hist, parent, mask, key, uid, constraint, depth,
                     parent_output, cegb_pen=None):
            rk = jax.random.fold_in(key, uid + 1_000_003 + params.extra_seed) \
                if params.extra_trees else None
            return find_best_split(hist, parent, meta, mask, params,
                                   constraint, depth, monotone_penalty,
                                   parent_output, rk, cegb_pen)

    if sums_fn is None:
        def sums_fn(g3):
            return g3.sum(axis=0)

    if bins_of_fn is None:
        def bins_of_fn(binned, feat):
            return binned[feat]

    use_cat_lw = bool(np.asarray(meta.is_categorical).any())

    def allowed_features_batch(used):
        if groups_lw is None:
            return jnp.ones_like(used)
        return jax.vmap(lambda u: allowed_features_for(groups_lw, u))(used)

    def clamp_out_batch(sums, constr, parent_out=None):
        out = jax.vmap(lambda s: leaf_output(s[0], s[1], params))(sums)
        if params.path_smooth > 0 and parent_out is not None:
            out = smooth_output(out, sums[:, 2], parent_out, params)
        if not use_mc:
            return out
        return jnp.clip(out, constr[:, 0], constr[:, 1])

    def grow(binned, g3, base_mask, key, cegb_used=None):
        N = binned.shape[1]
        F = base_mask.shape[0]    # ORIGINAL features (EFB: binned narrower)
        if cegb_used is None:
            cegb_used = jnp.zeros(F, bool)
        from .tree import empty_tree

        leaf_id = jnp.zeros(N, jnp.int32)
        root_sum = sums_fn(g3)
        W = -(-num_bins // 32)
        tree = empty_tree(L, W)
        leaf_sums = jnp.zeros((L, 3), jnp.float32).at[0].set(root_sum)
        leaf_constr = jnp.tile(jnp.asarray(NO_CONSTRAINT, jnp.float32), (L, 1))
        out_root = leaf_output(root_sum[0], root_sum[1], params)
        if params.path_smooth > 0:
            out_root = smooth_output(out_root, root_sum[2], 0.0, params)
        leaf_out = jnp.zeros(L, jnp.float32).at[0].set(out_root)
        leaf_used = jnp.zeros((L, F), bool)
        leaf_active = jnp.zeros(L, bool).at[0].set(True)
        leaf_is_left = jnp.zeros(L, bool)
        num_leaves_cur = jnp.asarray(1, jnp.int32)
        num_nodes_cur = jnp.asarray(0, jnp.int32)
        forced_leaf = jnp.full((max(S_forced, 1), 2), -1, jnp.int32)

        # smaller-sibling + subtraction across levels (the reference's
        # smaller-leaf trick): level d rebuilds only the SMALLER child of
        # each level-(d-1) split; the sibling comes from the parent's stored
        # histogram by subtraction, and unsplit leaves keep theirs.  Halves
        # the per-level histogram pass.  Disabled when the carried state
        # would exceed 512 MB (wide-F configs).
        prev = None          # (hist, split_mask, new_leaf, sm_left)
        for d in range(levels):
            Ld = min(1 << d, L)
            if prev is None:
                hist = hist_frontier_fn(binned, g3, leaf_id, Ld)  # (Ld,F,B,3)
                use_sub_lw = (L * int(np.prod(hist.shape[1:])) * 4
                              ) <= 512 * (1 << 20)
            else:
                p_hist, p_mask, p_new, p_sml = prev
                Lp = p_hist.shape[0]
                # label rows of each split's smaller child with the PARENT
                # slot; everything else is dead (slot Lp, sliced away).
                # (Lp, N) broadcast-compare, NOT a per-row table gather —
                # 1M-row gathers measure 8-12 ms on this device vs ~3 ms
                # for a whole compare pass (tools/microbench_gather.py)
                sm_id = jnp.where(p_sml, jnp.arange(Lp, dtype=jnp.int32),
                                  p_new)
                sm_leaf = jnp.where(p_mask, sm_id, L + 1)       # (Lp,)
                # chunked (<=_LEVEL_CHUNK, N) broadcast-compare: each row
                # is owned by at most ONE frontier slot, so the chunked
                # int32 accumulation is bit-identical to one (Lp, N) pass
                acc = jnp.zeros(N, jnp.int32)
                for c0 in range(0, Lp, _LEVEL_CHUNK):
                    c1 = min(c0 + _LEVEL_CHUNK, Lp)
                    mine_c = sm_leaf[c0:c1, None] == leaf_id[None, :]
                    acc = acc + jnp.sum(jnp.where(
                        mine_c,
                        jnp.arange(c0, c1, dtype=jnp.int32)[:, None] - Lp,
                        0), axis=0)
                label = acc + Lp
                h_small = hist_frontier_fn(binned, g3, label, Lp + 1)[:Lp]
                smL = p_sml[:, None, None, None]
                h_left = jnp.where(smL, h_small, p_hist - h_small)
                h_right = p_hist - h_left
                hist = jnp.zeros((Ld,) + h_left.shape[1:], jnp.float32)
                hist = hist.at[:Lp].set(
                    jnp.where(p_mask[:, None, None, None], h_left,
                              p_hist))
                hist = hist.at[jnp.where(p_mask, p_new, Ld + 1)].set(
                    h_right, mode="drop")
            if feature_fraction_bynode < 1.0:
                masks = jnp.stack([
                    _node_feature_mask(key, d * (2 * L) + i, base_mask,
                                       feature_fraction_bynode)
                    for i in range(Ld)
                ])
            else:
                masks = jnp.broadcast_to(base_mask, (Ld, F))
            masks = masks & allowed_features_batch(leaf_used[:Ld])
            cegb_pen = cegb_penalty_batch(leaf_sums[:Ld, 2], cegb_used)
            # one uid per LEAF (not per level) so extra_trees draws distinct
            # random thresholds for each node, like the leaf-wise 2s+1/2s+2
            # numbering; shares the level-d feature-mask uid base
            uids = d * (2 * L) + jnp.arange(Ld, dtype=jnp.int32)
            if cegb_pen is None:
                res = jax.vmap(
                    lambda h, p, m, c, po, u: split_fn(h, p, m, key, u, c, d, po)
                )(hist, leaf_sums[:Ld], masks, leaf_constr[:Ld], leaf_out[:Ld],
                  uids)
            else:
                res = jax.vmap(
                    lambda h, p, m, c, po, u, cp: split_fn(
                        h, p, m, key, u, c, d, po, cp)
                )(hist, leaf_sums[:Ld], masks, leaf_constr[:Ld],
                  leaf_out[:Ld], uids, cegb_pen)

            # ---- forced splits for this level (BFS depth == d) ------------
            forced_now = jnp.zeros(Ld, bool)
            forced_steps_d = steps_at_depth.get(d, [])
            forced_resolved = {}          # s -> (tleaf, ok) for recording
            for s in forced_steps_d:
                pstep, side = int(fs_np[s, 0]), int(fs_np[s, 1])
                ffeat, fbin = int(fs_np[s, 2]), int(fs_np[s, 3])
                fdl = bool(fs_np[s, 4])
                traw = (jnp.asarray(0, jnp.int32) if pstep < 0
                        else forced_leaf[pstep, side])
                ok_p = (traw >= 0) & (traw < Ld)
                tleaf = jnp.clip(traw, 0, Ld - 1)
                flsum, frsum, fgain = forced_split_stats(
                    hist[tleaf, ffeat], leaf_sums[tleaf], ffeat, fbin, fdl,
                    meta, params)
                ok = ok_p & leaf_active[tleaf] & (flsum[2] > 0) & \
                    (frsum[2] > 0)
                forced_resolved[s] = (tleaf, ok)
                sel = jax.nn.one_hot(tleaf, Ld, dtype=bool) & ok
                res = res._replace(
                    gain=jnp.where(sel, fgain, res.gain),
                    feature=jnp.where(sel, ffeat, res.feature),
                    threshold_bin=jnp.where(sel, fbin, res.threshold_bin),
                    default_left=jnp.where(sel, fdl, res.default_left),
                    is_cat=jnp.where(sel, False, res.is_cat),
                    left_sum=jnp.where(sel[:, None], flsum[None, :],
                                       res.left_sum),
                    right_sum=jnp.where(sel[:, None], frsum[None, :],
                                        res.right_sum),
                )
                forced_now = forced_now | sel

            gains = jnp.where(leaf_active[:Ld], res.gain, -jnp.inf)
            rank_gains = jnp.where(forced_now, jnp.inf, gains)
            want = rank_gains > 0
            # budget: rank wanted splits by gain, keep the top (L - current);
            # forced splits rank first (reference applies them regardless of
            # the gain test)
            order = jnp.argsort(-jnp.where(want, rank_gains, -jnp.inf))
            rank = jnp.zeros(Ld, jnp.int32).at[order].set(
                jnp.arange(Ld, dtype=jnp.int32))
            budget = L - num_leaves_cur
            split_mask = want & (rank < budget)

            split_order = jnp.cumsum(split_mask.astype(jnp.int32)) - 1
            node_idx = num_nodes_cur + split_order          # (Ld,)
            new_leaf = num_leaves_cur + split_order
            for s in forced_steps_d:
                # record the REALIZED children of applied forced steps so
                # deeper forced steps resolve against actual leaf ids
                # (left child keeps the leaf slot, right child is new_leaf)
                tleaf, ok = forced_resolved[s]
                applied = ok & split_mask[tleaf]
                forced_leaf = forced_leaf.at[s].set(jnp.where(
                    applied, jnp.stack([tleaf, new_leaf[tleaf]]),
                    forced_leaf[s]))

            # partition update: (Ld, N) broadcast-compare over the level's
            # split leaves (the same formulation as the wave grower's
            # round_pass — per-row table gathers measure 8-12 ms per 1M
            # rows on this device vs ~3 ms for the whole compare pass,
            # tools/microbench_gather.py; this was ~2/3 of the level-wise
            # iteration before round 5), processed in frontier chunks of
            # at most _LEVEL_CHUNK splits so wide levels never
            # materialize the full (Ld, N) intermediates (the wave
            # grower's 128-slot cap, applied to the level frontier).
            # Disjoint row ownership keeps the chunked accumulation
            # bit-identical to the single pass.
            feat_k = res.feature                             # (Ld,)
            leafk = jnp.where(split_mask,
                              jnp.arange(Ld, dtype=jnp.int32), L)
            delta = jnp.zeros(N, jnp.int32)
            for c0 in range(0, Ld, _LEVEL_CHUNK):
                c1 = min(c0 + _LEVEL_CHUNK, Ld)
                fk = feat_k[c0:c1]
                bk = jax.vmap(lambda f: bins_of_fn(binned, f))(fk) \
                    .astype(jnp.int32)                       # (<=C, N)
                mt_k = meta.missing_type[fk][:, None]
                na_k = ((mt_k == MISSING_NAN)
                        & (bk == meta.nan_bin[fk][:, None])) | (
                    (mt_k == MISSING_ZERO)
                    & (bk == meta.zero_bin[fk][:, None]))
                glk = jnp.where(na_k, res.default_left[c0:c1, None],
                                bk <= res.threshold_bin[c0:c1, None])
                if use_cat_lw:  # categorical: bin-space bitset membership
                    word = jnp.zeros(bk.shape, jnp.uint32)
                    for wv in range(W):
                        word = jnp.where(
                            (bk >> 5) == wv,
                            res.cat_bitset[c0:c1, wv][:, None], word)
                    in_set = ((word >> (bk.astype(jnp.uint32) & 31))
                              & 1) == 1
                    glk = jnp.where(res.is_cat[c0:c1, None], in_set, glk)
                mine = leafk[c0:c1, None] == leaf_id[None, :]
                go_r = mine & (~glk)
                delta = delta + jnp.sum(
                    jnp.where(go_r, new_leaf[c0:c1, None]
                              - leaf_id[None, :], 0), axis=0)
            leaf_id = leaf_id + delta

            # tree array updates (scatter with out-of-bounds drop for masked)
            nd = jnp.where(split_mask, node_idx, L1 + 1)
            nl = jnp.where(split_mask, new_leaf, L + 1)
            ld_idx = jnp.where(split_mask, jnp.arange(Ld), L + 1)
            pconstr = leaf_constr[:Ld]
            parent_out = leaf_out[:Ld]
            left_out = clamp_out_batch(res.left_sum, pconstr, parent_out)
            right_out = clamp_out_batch(res.right_sum, pconstr, parent_out)
            if use_mc:
                # BasicLeafConstraints::Update, vectorized over the level
                mono = meta.monotone_type[res.feature]
                mid = 0.5 * (left_out + right_out)
                upd = (~res.is_cat) & (mono != 0)
                max_l = jnp.where(upd & (mono > 0),
                                  jnp.minimum(pconstr[:, 1], mid), pconstr[:, 1])
                min_l = jnp.where(upd & (mono < 0),
                                  jnp.maximum(pconstr[:, 0], mid), pconstr[:, 0])
                max_r = jnp.where(upd & (mono < 0),
                                  jnp.minimum(pconstr[:, 1], mid), pconstr[:, 1])
                min_r = jnp.where(upd & (mono > 0),
                                  jnp.maximum(pconstr[:, 0], mid), pconstr[:, 0])
                constr_l = jnp.stack([min_l, max_l], axis=1)
                constr_r = jnp.stack([min_r, max_r], axis=1)
            else:
                constr_l = constr_r = pconstr

            t = tree
            # re-wire parents of the split leaves
            p = t.leaf_parent[jnp.minimum(ld_idx, L - 1)]
            fix_l = jnp.where(split_mask & (p >= 0) & leaf_is_left[jnp.minimum(ld_idx, L - 1)],
                              jnp.maximum(p, 0), L1 + 1)
            fix_r = jnp.where(split_mask & (p >= 0) & (~leaf_is_left[jnp.minimum(ld_idx, L - 1)]),
                              jnp.maximum(p, 0), L1 + 1)
            lc = t.left_child.at[fix_l].set(nd, mode="drop")
            rc = t.right_child.at[fix_r].set(nd, mode="drop")
            lc = lc.at[nd].set(-(ld_idx + 1), mode="drop")
            rc = rc.at[nd].set(-(nl + 1), mode="drop")
            tree = t._replace(
                num_leaves=num_leaves_cur + split_mask.sum(),
                split_feature=t.split_feature.at[nd].set(res.feature, mode="drop"),
                threshold_bin=t.threshold_bin.at[nd].set(res.threshold_bin, mode="drop"),
                default_left=t.default_left.at[nd].set(res.default_left, mode="drop"),
                is_cat=t.is_cat.at[nd].set(res.is_cat, mode="drop"),
                cat_bitset=t.cat_bitset.at[nd].set(res.cat_bitset, mode="drop"),
                missing_type=t.missing_type.at[nd].set(
                    meta.missing_type[res.feature], mode="drop"),
                left_child=lc,
                right_child=rc,
                split_gain=t.split_gain.at[nd].set(res.gain, mode="drop"),
                internal_value=t.internal_value.at[nd].set(parent_out, mode="drop"),
                internal_weight=t.internal_weight.at[nd].set(
                    leaf_sums[:Ld, 1], mode="drop"),
                internal_count=t.internal_count.at[nd].set(
                    leaf_sums[:Ld, 2], mode="drop"),
                leaf_value=t.leaf_value.at[ld_idx].set(left_out, mode="drop")
                .at[nl].set(right_out, mode="drop"),
                leaf_weight=t.leaf_weight.at[ld_idx].set(res.left_sum[:, 1], mode="drop")
                .at[nl].set(res.right_sum[:, 1], mode="drop"),
                leaf_count=t.leaf_count.at[ld_idx].set(res.left_sum[:, 2], mode="drop")
                .at[nl].set(res.right_sum[:, 2], mode="drop"),
                leaf_parent=t.leaf_parent.at[ld_idx].set(nd, mode="drop")
                .at[nl].set(nd, mode="drop"),
            )
            leaf_sums = leaf_sums.at[ld_idx].set(res.left_sum, mode="drop") \
                .at[nl].set(res.right_sum, mode="drop")
            leaf_constr = leaf_constr.at[ld_idx].set(constr_l, mode="drop") \
                .at[nl].set(constr_r, mode="drop")
            leaf_out = leaf_out.at[ld_idx].set(left_out, mode="drop") \
                .at[nl].set(right_out, mode="drop")
            if use_cegb_lw:
                cegb_used = cegb_used | jnp.any(
                    jax.nn.one_hot(res.feature, F, dtype=bool)
                    & split_mask[:, None], axis=0)
            used_child = leaf_used[:Ld] | jax.nn.one_hot(
                res.feature, F, dtype=bool)
            leaf_used = leaf_used.at[ld_idx].set(used_child, mode="drop") \
                .at[nl].set(used_child, mode="drop")
            leaf_is_left = leaf_is_left.at[ld_idx].set(True, mode="drop") \
                .at[nl].set(False, mode="drop")
            leaf_active = (leaf_active & jnp.pad(split_mask, (0, L - Ld))
                           if Ld < L else leaf_active & split_mask)
            leaf_active = leaf_active.at[nl].set(True, mode="drop")
            num_leaves_cur = num_leaves_cur + split_mask.sum()
            num_nodes_cur = num_nodes_cur + split_mask.sum()
            if d + 1 < levels and use_sub_lw:
                prev = (hist, split_mask,
                        jnp.where(split_mask, new_leaf, L + 1),
                        res.left_sum[:, 2] <= res.right_sum[:, 2])
            else:
                prev = None

        return tree, leaf_id, root_sum

    return grow
