from .gbdt import DART, GBDT, GOSS, RF, create_boosting
from .grower import make_leafwise_grower
from .tree import HostTree, TreeArrays, empty_tree

__all__ = [
    "DART",
    "GBDT",
    "GOSS",
    "RF",
    "create_boosting",
    "make_leafwise_grower",
    "HostTree",
    "TreeArrays",
    "empty_tree",
]
