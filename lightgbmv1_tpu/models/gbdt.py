"""GBDT boosting loop and variants (DART / GOSS / RF).

TPU-native re-design of the reference boosting layer
(reference: ``src/boosting/gbdt.cpp`` — ``TrainOneIter`` :337-419,
``BoostFromAverage`` :312-335, ``Bagging`` :209-243, ``UpdateScore``
:458-478, ``RollbackOneIter`` :421-437; variants ``dart.hpp:23-170``,
``goss.hpp:25-150``, ``rf.hpp:25``; score caching ``score_updater.hpp``).

Host/device split (SURVEY.md §3.3 note): the per-iteration loop stays on the
host (one compiled tree-build per tree, like the reference's Python-side
loop); everything inside an iteration — gradients, histograms, split search,
partition, score update — runs on device under jit.

Bagging is mask-based: excluded rows get zero grad/hess/count in the
histogram channels (equivalent to the reference's index-subset bagging for
every training statistic), and out-of-bag rows still receive score updates
because the partition covers all rows (the reference updates out-of-bag
scores explicitly, gbdt.cpp:458-478).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import BinnedDataset
from ..metrics import Metric, create_metrics
from ..objectives import ObjectiveFunction, create_objective
from ..obs import trace as obs_trace
from ..obs import xla as obs_xla
from ..ops.split import SplitParams, make_feature_meta
from ..utils.log import log_fatal, log_info, log_warning
from ..utils.timer import global_timer
from .grower import make_leafwise_grower
from .tree import (HostTree, TreeArrays, leaf_lookup,
                   tree_predict_binned, tree_used_features)


class FiniteGuardError(RuntimeError):
    """``finite_guard=raise``: non-finite training state (NaN/Inf
    gradients propagated into the score cache) detected at an iteration
    boundary — the poisoned iteration is the LAST one, so a caller can
    roll back or resume from the previous checkpoint instead of shipping
    silently corrupted trees."""


def _np_weighted_quantile_sorted(v, w, q):
    cw = np.cumsum(w)
    if cw[-1] <= 0:
        return 0.0
    idx = int(np.searchsorted(cw, q * cw[-1], side="left"))
    return float(v[min(idx, len(v) - 1)])


class _ScoreUpdater:
    """Cached raw scores for one dataset (reference: score_updater.hpp:21-130)."""

    def __init__(self, num_data: int, num_class: int, init: np.ndarray):
        self.score = jnp.asarray(
            np.broadcast_to(init, (num_data, num_class)).copy(), jnp.float32
        )

    def add_leaf_values(self, leaf_values: jax.Array, leaf_id: jax.Array, k: int):
        self.score = self.score.at[:, k].add(
            leaf_lookup(leaf_values, leaf_id))

    def add_pred(self, pred: jax.Array, k: int):
        self.score = self.score.at[:, k].add(pred)


class GBDT:
    """Gradient Boosting Decision Tree driver (reference: class GBDT, gbdt.h:34)."""

    # out-of-core row-block training (models/gbdt_stream.py sets True):
    # the binned matrix is NEVER uploaded whole — blocks stream per pass
    _is_streaming = False

    def __init__(
        self,
        config: Config,
        train_set: BinnedDataset,
        objective: Optional[ObjectiveFunction] = None,
        metrics: Optional[List[Metric]] = None,
        init_raw_scores: Optional[np.ndarray] = None,
    ):
        # init_raw_scores: (num_data, num_class) raw predictions of a loaded
        # model — continued training resumes boosting from them (reference:
        # continued training via input_model, application.cpp:90-93 predicts
        # the old model to seed the score cache)
        self._init_raw_scores = init_raw_scores
        self.config = config
        self.train_set = train_set
        self.num_data = train_set.num_data
        self.num_class = config.num_tree_per_iteration
        self.objective = objective if objective is not None else create_objective(config)
        if self.objective is not None:
            self.objective.init(train_set.metadata, self.num_data)
        self.train_metrics = metrics if metrics is not None else create_metrics(config)
        for m in self.train_metrics:
            m.init(train_set.metadata, self.num_data)

        # device-resident training data (the EFB bundle matrix when
        # bundling applied — trees and meta always speak ORIGINAL features)
        self._bundle = None
        if not self._is_streaming and train_set.bundle_layout is not None:
            from ..io.bundle import BundleArrays

            incompatible = (config.tree_learner in ("voting", "feature")
                            or bool(config.forcedsplits_filename))
            if incompatible and train_set.binned is None:
                log_fatal("tree_learner=voting/feature and forced splits do "
                          "not support EFB-bundled sparse datasets; load "
                          "dense data or drop the incompatible option")
            if incompatible:
                log_warning("EFB disabled (tree_learner=voting/feature and "
                            "forced splits run on unbundled features)")
                train_set.bundled = None
                train_set.bundle_layout = None
            else:
                self._bundle = BundleArrays(train_set.bundle_layout,
                                            train_set.zero_bins,
                                            train_set.num_bins)
        # 4-bit packing (reference DenseBin<..,IS_4BIT>, dense_bin.hpp:52):
        # two bins per byte when every feature fits 4 bits — halves the
        # binned matrix in HBM and the hist pass's dominant read stream,
        # including the fused wave round/loop (in-VMEM nibble unpack).
        # Layout resolution + once-per-build logging:
        # parallel/trainer.select_bin_layout (config.bin_layout).
        self._packed = False
        if self._is_streaming:
            # the row bulk never lands on device whole: blocks stream per
            # histogram pass (models/grower_stream.py); EFB / 4-bit
            # packing are resident-trainer representations (the block
            # cache stores packed SHARDS separately, data/block_cache.py)
            self._host_matrix = None
        else:
            self._host_matrix = train_set.train_matrix
            from ..parallel.trainer import select_bin_layout

            layout = select_bin_layout(
                config, num_total_bin=train_set.num_total_bin,
                bin_dtype=self._host_matrix.dtype,
                bundled=self._bundle is not None)
            if layout == "packed4":
                from ..ops.hist_pallas import pack4bit

                self._packed = True
                self._host_matrix = pack4bit(self._host_matrix)
        if self._is_streaming:
            self.binned = None
        elif getattr(train_set, "is_row_sharded", False):
            # process-sharded training data: the global device array is
            # assembled from per-process shards by the trainer
            # (parallel/dist_data.py make_process_sharded)
            if config.tree_learner != "data":
                log_fatal("process-sharded datasets require "
                          "tree_learner=data")
            self.binned = None
        else:
            self.binned = jnp.asarray(self._host_matrix)
        self.meta = make_feature_meta(train_set, config.monotone_constraints,
                                      config.feature_contri)
        rv = getattr(train_set, "row_valid", None)
        self._row_valid = (jnp.asarray(rv, jnp.float32)
                           if rv is not None else None)
        self.num_bins = train_set.padded_bin
        self.split_params = SplitParams(
            lambda_l1=config.lambda_l1,
            lambda_l2=config.lambda_l2,
            min_data_in_leaf=float(config.min_data_in_leaf),
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            min_gain_to_split=config.min_gain_to_split,
            max_delta_step=config.max_delta_step,
            cat_l2=config.cat_l2,
            cat_smooth=config.cat_smooth,
            max_cat_threshold=int(config.max_cat_threshold),
            max_cat_to_onehot=int(config.max_cat_to_onehot),
            min_data_per_group=float(config.min_data_per_group),
            path_smooth=float(config.path_smooth),
            extra_trees=bool(config.extra_trees),
            extra_seed=int(config.extra_seed),
            cegb_tradeoff=float(config.cegb_tradeoff),
            cegb_penalty_split=float(config.cegb_penalty_split),
        )

        self._build_trainer()

        # initial scores (reference: BoostFromAverage gbdt.cpp:312-335)
        self._init_scores = np.zeros(self.num_class, dtype=np.float64)
        meta_init = train_set.metadata.init_score
        if init_raw_scores is not None:
            base = np.asarray(init_raw_scores, dtype=np.float64).reshape(
                self.num_data, self.num_class)
            self._train_scores = self._new_score_store(
                self.num_data, self.num_class, base)
            self._used_init_score = True
        elif meta_init is not None:
            init = np.asarray(meta_init, dtype=np.float64).reshape(self.num_data, -1)
            base = np.zeros((self.num_data, self.num_class))
            base[:, : init.shape[1]] = init
            self._train_scores = self._new_score_store(
                self.num_data, self.num_class, base)
            self._used_init_score = True
        else:
            if self.objective is not None:
                for k in range(self.num_class):
                    self._init_scores[k] = self.objective.boost_from_score(k)
                if any(self._init_scores):
                    log_info(
                        "Start training from score "
                        + " ".join(f"{s:.6f}" for s in self._init_scores)
                    )
            self._train_scores = self._new_score_store(
                self.num_data, self.num_class, self._init_scores[None, :]
            )
            self._used_init_score = False

        self.models: List[Optional[HostTree]] = []  # flat: iter-major, class-minor
        self._device_trees: List[TreeArrays] = []
        self._model_shrink: List[float] = []
        self._model_bias: List[float] = []
        # Host trees are materialized lazily (one batched device_get at the
        # end) unless the objective renews leaf outputs on the host — keeps
        # the per-iteration loop free of device->host syncs, which dominate
        # wall-clock when the device is reached through a network tunnel.
        self._needs_host_tree = (
            self.objective is not None and self.objective.renew_percentile is not None
        )
        self.iter = 0
        self._valid_sets: List[BinnedDataset] = []
        self._valid_names: List[str] = []
        self._valid_binned: List[jax.Array] = []
        self._valid_scores: List[_ScoreUpdater] = []
        self._valid_metrics: List[List[Metric]] = []
        self._prev_state = None
        # CEGB model-level used-feature mask (reference
        # is_feature_used_in_split_, persists across trees) and, for
        # cegb_penalty_feature_lazy, the per-row feature marks (reference
        # feature_used_in_data_ bitset) — both persist across iterations
        self._cegb_lazy_active = (
            bool(config.cegb_penalty_feature_lazy)
            and config.tree_learner in ("serial", "")
            and config.tree_growth != "levelwise")
        self._cegb_enabled = (config.cegb_penalty_split > 0
                              or bool(config.cegb_penalty_feature_coupled)
                              or self._cegb_lazy_active)
        self._cegb_used = jnp.zeros(train_set.num_features, bool)
        if self._cegb_lazy_active:
            self._cegb_used = (
                self._cegb_used,
                jnp.zeros((self.num_data, train_set.num_features), bool))
        self._rng_key = jax.random.PRNGKey(config.seed)
        self._bag_mask: Optional[jax.Array] = None
        self._feat_rng = np.random.RandomState(config.feature_fraction_seed)
        # fault injection (utils/faults.py): an armed grad_poison fault is
        # baked in at trace time as a traced iteration==N select, so it
        # fires exactly once even inside a scanned multi-iteration dispatch
        from ..utils import faults as _faults

        self._poison_iter = _faults.grad_poison_iteration()
        self._finite_warned = False
        # score-cache buffer donation through the fused step
        # (donate_argnums): the iteration's score update runs in place —
        # no second (N, K) buffer per cache, no defensive copy at the
        # dispatch boundary.  XLA:CPU ignores donation (and warns), so
        # the knob arms only off-CPU; tests probe the lowered HLO's
        # aliasing directly (tests/test_wave_pipeline.py).
        self._donate = bool(config.donate_buffers) and \
            jax.default_backend() != "cpu"

    # ------------------------------------------------------------------
    def _new_score_store(self, num_data, num_class, init):
        """Train-score cache factory — the streaming trainer overrides
        this with a host-backed store (block-sharded per-row state)."""
        return _ScoreUpdater(num_data, num_class, init)

    # ------------------------------------------------------------------
    @property
    def iter(self) -> int:
        return self._iter

    @iter.setter
    def iter(self, v: int) -> None:
        # every ensemble mutation (tree append, rollback truncation, DART
        # drop-rescale of EXISTING trees) happens inside an update/rollback
        # flow that moves ``iter``; the monotone version counter is the
        # native-predictor cache invalidation key (with the tree count) —
        # object identity of host trees is not stable (they may be
        # re-materialized per call) and CPython id() can alias after GC
        self._iter = v
        self.model_version = getattr(self, "model_version", -1) + 1

    # ------------------------------------------------------------------
    def _build_trainer(self):
        from ..parallel.trainer import build_trainer

        self._grow, self._grow_binned, _ = build_trainer(
            self.config,
            self._host_matrix,
            self.meta,
            self.split_params,
            self.num_bins,
            bin_mappers=self.train_set.bin_mappers,
            bundle=self._bundle,
            bundle_num_bins=(self.train_set.padded_bundle_bin
                             if self._bundle is not None else None),
            row_sharded=getattr(self.train_set, "is_row_sharded", False),
            packed=self._packed,
        )
        if self.binned is None:
            self.binned = self._grow_binned
        self._step = None  # fused per-iteration step, built lazily

    # ------------------------------------------------------------------
    # Fused iteration: gradients -> sampling -> K tree builds -> score
    # updates, all under ONE jit so an iteration is a single device
    # dispatch.  Essential when the device sits behind a network tunnel and
    # on TPU generally (SURVEY.md §3.3: one compiled step per iteration).
    # ------------------------------------------------------------------
    def _supports_fused_step(self) -> bool:
        return (
            self.objective is not None
            and self.objective.renew_percentile is None
            and not self._needs_host_tree
        )

    def _bag_fraction_mask(self, key, iteration):
        """Traceable bagging mask (see _bagging_mask for semantics)."""
        cfg = self.config
        use_pos_neg = (
            cfg.objective == "binary"
            and (cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0)
        )
        if cfg.bagging_freq <= 0 or (cfg.bagging_fraction >= 1.0 and not use_pos_neg):
            return None
        kk = jax.random.fold_in(
            jax.random.PRNGKey(cfg.bagging_seed),
            iteration // max(cfg.bagging_freq, 1),
        )
        if use_pos_neg:
            label = self.objective.label
            pos = jax.random.bernoulli(kk, cfg.pos_bagging_fraction, (self.num_data,))
            neg = jax.random.bernoulli(
                jax.random.fold_in(kk, 1), cfg.neg_bagging_fraction, (self.num_data,)
            )
            mask = jnp.where(label > 0, pos, neg)
        else:
            mask = jax.random.bernoulli(kk, cfg.bagging_fraction, (self.num_data,))
        return mask.astype(jnp.float32)

    def _build_step(self):
        cfg = self.config
        K = self.num_class
        rate = cfg.learning_rate if not isinstance(self, RF) else 1.0

        def step(binned, valid_binned, train_score, valid_scores, iteration,
                 feat_masks, cegb_used):
            # binned/valid_binned ride as arguments (NOT closure constants):
            # closed-over process-spanning global arrays cannot be baked into
            # the jaxpr on multi-host meshes
            s = train_score[:, 0] if K == 1 else train_score
            grad, hess = self._objective_grads(s, iteration)
            if grad.ndim == 1:
                grad, hess = grad[:, None], hess[:, None]
            bag = self._bag_fraction_mask(None, iteration)
            trees = []
            leaf_ids = []
            train_preds = []
            valid_preds = [[] for _ in valid_binned]
            grow_valids = getattr(self._grow, "_supports_valids", False)
            for k in range(K):
                g3 = self._sample_g3(grad[:, k], hess[:, k], bag, iteration)
                key = jax.random.fold_in(self._rng_key, iteration * K + k)
                if grow_valids and valid_binned:
                    # the wave grower routes valid rows through each
                    # round's splits: valid predictions become a
                    # leaf_value gather (no per-tree device walk)
                    tree_dev, leaf_id, _, vlids = self._grow(
                        binned, g3, feat_masks[k], key, cegb_used,
                        valids=tuple(valid_binned))
                else:
                    tree_dev, leaf_id, _ = self._grow(
                        binned, g3, feat_masks[k], key, cegb_used
                    )
                    vlids = None
                if self._cegb_enabled:
                    cegb_used = self._update_cegb_state(
                        cegb_used, tree_dev, leaf_id)
                shrunk = tree_dev._replace(leaf_value=tree_dev.leaf_value * rate)
                train_preds.append(leaf_lookup(shrunk.leaf_value, leaf_id))
                for vi, vb in enumerate(valid_binned):
                    if vlids is not None:
                        # native gather, NOT leaf_lookup: this path is
                        # pinned bit-exact against the tree walk
                        # (test_valid_row_routing_matches_tree_walk), and
                        # valid sets are small enough that the gather tax
                        # does not matter
                        valid_preds[vi].append(shrunk.leaf_value[vlids[vi]])
                    else:
                        valid_preds[vi].append(tree_predict_binned(
                            shrunk, vb, self.meta.nan_bin,
                            self.meta.missing_type, self._bundle,
                            self._packed, zero_bins=self.meta.zero_bin))
                trees.append(shrunk)
                leaf_ids.append(leaf_id)
            # Deferred score bookkeeping: every class's leaf values land in
            # ONE (N, K) elementwise add per score cache instead of K
            # column-slice updates — this step's gradients were computed
            # BEFORE the class loop, so deferral is bit-identical (score
            # columns are independent elements receiving the same single
            # add).  Together with the leaf_lookup formulation this keeps
            # the whole gradient -> g3 -> score-update chain a handful of
            # row-streaming ops inside the same fused dispatch as the
            # trees' round-0 histogram passes (tools/phase_attrib.py
            # itemizes the cost under grad_g3_ms / score_update_ms).
            train_score = train_score + jnp.stack(train_preds, axis=1)
            if valid_binned:
                valid_scores = tuple(
                    vs + jnp.stack(vp, axis=1)
                    for vs, vp in zip(valid_scores, valid_preds))
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)
            return (train_score, valid_scores, stacked, jnp.stack(leaf_ids),
                    cegb_used)

        self._step_fn = step
        # args 2/3 are the train/valid score caches — the buffers the
        # fused step updates in place under donation.  The labeled
        # lower/compile wrapper (obs/xla.py) makes every compilation of
        # the fused step an observed event (compile_ms, retrace count,
        # cost/memory analysis) without touching its semantics.
        return obs_xla.instrument_jit(
            step, "train.step",
            donate_argnums=(2, 3) if self._donate else ())

    def _objective_grads(self, s, iteration=None):
        if getattr(self.objective, "is_stochastic", False):
            grad, hess = self.objective.get_gradients(s, iteration=iteration)
        else:
            grad, hess = self.objective.get_gradients(s)
        return self._guard_grads(grad, hess, iteration)

    def _guard_grads(self, grad, hess, iteration):
        """Finite-guard + fault-injection seam on the grad/hess pass.

        ``finite_guard=clamp`` zeroes non-finite grad/hess entries inside
        the traced step (a poisoned row behaves like a bagged-out row:
        zero weight in every histogram channel), so one bad pass cannot
        corrupt a tree.  ``warn``/``raise`` detect the propagated damage
        host-side at the iteration boundary (check_finite_boundary).
        The injected poison hits a deterministic ~8% row slice — enough
        to corrupt every histogram, small enough that clamp-mode training
        continues meaningfully on the surviving rows."""
        if self._poison_iter is not None and iteration is not None:
            n = grad.shape[0]
            rows = (jnp.arange(n, dtype=jnp.int32) % 13) == 0
            bad = rows if grad.ndim == 1 else rows[:, None]
            firing = jnp.asarray(iteration, jnp.int32) == jnp.int32(
                self._poison_iter)
            poison = jnp.where(bad & firing, jnp.float32(jnp.nan),
                               jnp.float32(0.0))
            grad = grad + poison
            hess = hess + poison
        if self.config.finite_guard == "clamp":
            finite = jnp.isfinite(grad) & jnp.isfinite(hess)
            grad = jnp.where(finite, grad, 0.0)
            hess = jnp.where(finite, hess, 0.0)
        return grad, hess

    def check_finite_boundary(self) -> None:
        """Iteration-boundary finite check (``finite_guard=warn|raise``).

        Two detectors, both one scalar device read:

        1. the train score cache — catches NaN/Inf that PROPAGATED into
           the model (diverged training, poisoned leaf values);
        2. a re-run of the just-finished gradient pass on the saved
           pre-update scores (``_prev_state`` — the rollback snapshot
           taken before the iteration) — catches a poisoned pass even
           when the grower ABSORBED it (NaN gains compare false, the
           iteration silently trains a zero no-op tree: the quiet
           mistraining this guard exists to surface).

        Called by Booster.update() after each iteration; train_iters()
        checks at scanned-block boundaries (detector 1 only is exact
        there).  Cost: one extra gradient pass per iteration, only when
        the guard is armed."""
        mode = self.config.finite_guard
        if mode not in ("warn", "raise"):
            return
        bad = not bool(np.isfinite(np.asarray(
            jax.device_get(jnp.sum(self._train_scores.score)))))
        if not bad and self.objective is not None \
                and self._prev_state is not None and self.iter > 0:
            score = self._prev_state[0]
            s = score[:, 0] if self.num_class == 1 else score
            g, h = self._objective_grads(s, iteration=int(self.iter - 1))
            tot = jax.device_get(jnp.sum(g) + jnp.sum(h))
            bad = not bool(np.isfinite(np.asarray(tot)))
        if not bad:
            return
        msg = (f"non-finite gradient/score state at iteration {self.iter} "
               f"boundary (finite_guard={mode}): the last iteration's "
               "trees are suspect — roll back or resume from the "
               "previous checkpoint")
        from ..obs import dump, events

        events.publish("guard.finite_guard", msg,
                       severity="error" if mode == "raise" else "warning",
                       mode=mode, iteration=int(self.iter))
        if mode == "raise":
            # a tripped finite guard is a crash-grade moment: the armed
            # flight recorder dumps the state that explains WHICH
            # iteration poisoned the scores before the raise unwinds it
            dump.dump("finite_guard", error=msg)
            raise FiniteGuardError(msg)
        if not self._finite_warned:
            self._finite_warned = True
            log_warning(msg)

    # ------------------------------------------------------------------
    def train_iters(self, n: int) -> None:
        """Run ``n`` boosting iterations in a SINGLE device dispatch via
        ``lax.scan`` over the fused step — the 'scan over trees on device'
        option (SURVEY.md §3.3).  Amortizes host->device dispatch latency,
        which dominates when the chip sits behind a network tunnel."""
        if n <= 0:
            return
        if not self._supports_fused_step():
            for _ in range(n):
                if self.train_one_iter(check_stop=False):
                    break
            return
        if self._step is None:
            self._step = self._build_step()
        if getattr(self, "_scan", None) is None:
            step_fn = self._step_fn

            def scan_fn(binned, valid_binned, train_score, valid_scores,
                        start_iter, feat_masks_all, cegb_used):
                def body(carry, fm):
                    ts, vs, it, cu = carry
                    ts, vs, stacked, _, cu = step_fn(binned, valid_binned,
                                                     ts, vs, it, fm, cu)
                    return (ts, vs, it + 1, cu), stacked

                (ts, vs, _, cu), trees = jax.lax.scan(
                    body, (train_score, valid_scores, start_iter, cegb_used),
                    feat_masks_all
                )
                return ts, vs, trees, cu

            self._scan = obs_xla.instrument_jit(
                scan_fn, "train.scan",
                donate_argnums=(2, 3) if self._donate else ())

        K = self.num_class
        feat_masks = jnp.asarray(np.stack([
            np.stack([self._tree_feature_mask() for _ in range(K)])
            for _ in range(n)
        ]))
        vscores = tuple(vs.score for vs in self._valid_scores)
        self._save_rollback_state()
        t0_ns = obs_trace.now_ns()
        with global_timer.section("GBDT::TrainIters(dispatch)"):
            new_train, new_valid, trees, self._cegb_used = self._scan(
                self._grow_binned, tuple(self._valid_binned),
                self._train_scores.score, vscores,
                jnp.asarray(self.iter, jnp.int32), feat_masks,
                self._cegb_used,
            )
        self._train_scores.score = new_train
        for vs, s in zip(self._valid_scores, new_valid):
            vs.score = s
        if obs_trace.enabled():
            # the scanned block is ONE device dispatch — the host cannot
            # see iteration boundaries inside it, so the trace carries
            # one block span (args say how many iterations it amortized)
            obs_trace.add_span(
                "train.iterations", t0_ns, obs_trace.now_ns() - t0_ns,
                cat="train", args={"n": n, "start_iter": int(self.iter)})
        for i in range(n):
            for k in range(K):
                self._device_trees.append(
                    jax.tree_util.tree_map(lambda a: a[i, k], trees)
                )
                self.models.append(None)
                self._model_shrink.append(
                    self.config.learning_rate if not isinstance(self, RF) else 1.0
                )
                self._model_bias.append(self._tree_bias(k))
            self.iter += 1
        self.check_finite_boundary()

    def _fused_train_one_iter(self) -> None:
        if self._step is None:
            self._step = self._build_step()
        feat_masks = jnp.asarray(
            np.stack([self._tree_feature_mask() for _ in range(self.num_class)])
        )
        vscores = tuple(vs.score for vs in self._valid_scores)
        with global_timer.section("GBDT::TrainOneIter(dispatch)"):
            (new_train, new_valid, stacked, leaf_ids,
             self._cegb_used) = self._step(
                self._grow_binned, tuple(self._valid_binned),
                self._train_scores.score, vscores,
                jnp.asarray(self.iter, jnp.int32), feat_masks,
                self._cegb_used,
            )
        self._train_scores.score = new_train
        for vs, s in zip(self._valid_scores, new_valid):
            vs.score = s
        store = getattr(self, "_maybe_store_lids", None)
        if store is not None:
            # DART keeps each tree's training-row leaf assignment so a
            # later drop re-predicts via a cheap (L,)-table gather instead
            # of a per-row tree walk (see DART._fused_dart_iter)
            store(leaf_ids)
        for k in range(self.num_class):
            tree_k = jax.tree_util.tree_map(lambda a: a[k], stacked)
            self._device_trees.append(tree_k)
            self.models.append(None)
            self._model_shrink.append(
                self.config.learning_rate if not isinstance(self, RF) else 1.0
            )
            self._model_bias.append(self._tree_bias(k))

    # ------------------------------------------------------------------
    def add_valid(self, valid_set: BinnedDataset, name: str,
                  init_raw: Optional[np.ndarray] = None) -> None:
        metrics = create_metrics(self.config)
        for m in metrics:
            m.init(valid_set.metadata, valid_set.num_data)
        if init_raw is not None:
            # continued training: valid scores also resume from the loaded
            # model's predictions
            init = np.asarray(init_raw, dtype=np.float64).reshape(
                valid_set.num_data, self.num_class)
        elif valid_set.metadata.init_score is not None:
            init = np.asarray(valid_set.metadata.init_score,
                              dtype=np.float64).reshape(valid_set.num_data, -1)
        else:
            init = self._init_scores[None, :]
        if self.iter > 0:
            log_fatal("Cannot add validation data after training started")
        self._valid_sets.append(valid_set)
        self._valid_names.append(name)
        if self._bundle is not None:
            # valid data must share the training bundle layout (the analog
            # of the reference's shared FeatureGroups for valid sets)
            if (valid_set.bundled is None
                    or valid_set.bundle_layout
                    is not self.train_set.bundle_layout):
                if valid_set.binned is None:
                    log_fatal("validation set was bundled with a different "
                              "EFB layout and has no dense bins to "
                              "re-bundle; construct it with "
                              "reference=<train dataset>")
                from ..io.bundle import apply_bundles_dense

                valid_set.bundled = apply_bundles_dense(
                    valid_set.binned, valid_set.zero_bins,
                    self.train_set.bundle_layout)
                valid_set.bundle_layout = self.train_set.bundle_layout
            self._valid_binned.append(jnp.asarray(valid_set.bundled))
        else:
            # sparse valid sets built against an unbundled reference carry
            # identity bundles: bundle bins == original bins
            vb = (valid_set.binned if valid_set.binned is not None
                  else valid_set.train_matrix)
            if self._packed:
                from ..ops.hist_pallas import pack4bit

                vb = pack4bit(vb)
            self._valid_binned.append(jnp.asarray(vb))
        self._valid_scores.append(
            _ScoreUpdater(valid_set.num_data, self.num_class, init)
        )
        self._valid_metrics.append(metrics)

    # ------------------------------------------------------------------
    def _tree_feature_mask(self) -> np.ndarray:
        """Per-tree column sampling (reference: ColSampler by-tree)."""
        usable = ~self.train_set.is_trivial
        frac = self.config.feature_fraction
        if frac >= 1.0:
            return usable
        idx = np.flatnonzero(usable)
        k = max(1, int(math.ceil(frac * len(idx))))
        chosen = self._feat_rng.choice(idx, size=k, replace=False)
        mask = np.zeros_like(usable)
        mask[chosen] = True
        return mask

    def _bagging_mask(self, iteration: int) -> Optional[jax.Array]:
        """reference: GBDT::Bagging gbdt.cpp:209-243 (+ balanced bagging
        :180-207). Mask-based Bernoulli sampling."""
        cfg = self.config
        use_pos_neg = (
            cfg.objective == "binary"
            and (cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0)
        )
        if cfg.bagging_freq <= 0 or (cfg.bagging_fraction >= 1.0 and not use_pos_neg):
            return None
        if self._bag_mask is not None and iteration % cfg.bagging_freq != 0:
            return self._bag_mask
        key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.bagging_seed), iteration // max(cfg.bagging_freq, 1)
        )
        if use_pos_neg:
            label = self.objective.label
            pos = jax.random.bernoulli(key, cfg.pos_bagging_fraction, (self.num_data,))
            neg = jax.random.bernoulli(
                jax.random.fold_in(key, 1), cfg.neg_bagging_fraction, (self.num_data,)
            )
            mask = jnp.where(label > 0, pos, neg)
        else:
            mask = jax.random.bernoulli(key, cfg.bagging_fraction, (self.num_data,))
        self._bag_mask = mask.astype(jnp.float32)
        return self._bag_mask

    # ------------------------------------------------------------------
    def _gradients(self) -> Tuple[jax.Array, jax.Array]:
        score = self._train_scores.score
        s = score[:, 0] if self.num_class == 1 else score
        grad, hess = self._objective_grads(s, int(self.iter))
        if grad.ndim == 1:
            grad, hess = grad[:, None], hess[:, None]
        return grad, hess

    def _update_cegb_state(self, state, tree_dev, leaf_id):
        """Post-tree CEGB bookkeeping. ``state`` is the (F,) used-feature
        mask, or ((F,), (N, F)) with the per-row lazy marks.  The marks
        update is exact: a row 'used' precisely the features on its final
        leaf's root path (the union over the tree of the reference's
        per-split row marking, cost_effective_gradient_boosting.hpp:110)."""
        if isinstance(state, tuple):
            used, marks = state
            used = used | tree_used_features(tree_dev, used.shape[0])
            from .tree import leaf_path_features

            pf = leaf_path_features(tree_dev, marks.shape[1])
            marks = marks | pf[leaf_id]
            return (used, marks)
        return state | tree_used_features(tree_dev, state.shape[0])

    def _sample_g3(self, grad_k, hess_k, bag, iteration):
        """Assemble the (N, 3) [grad, hess, count] channels with bagging.
        Process-sharded datasets carry phantom pad rows (weight 0): they
        must also have count 0 so min_data_in_leaf gating and count-based
        smoothing see only real rows."""
        if bag is None:
            cnt = jnp.ones_like(grad_k)
        else:
            grad_k, hess_k, cnt = grad_k * bag, hess_k * bag, bag
        if self._row_valid is not None:
            cnt = cnt * self._row_valid
        return jnp.stack([grad_k, hess_k, cnt], axis=1)

    # ------------------------------------------------------------------
    def train_one_iter(
        self,
        custom_grad: Optional[np.ndarray] = None,
        custom_hess: Optional[np.ndarray] = None,
        check_stop: bool = True,
    ) -> bool:
        """Train one boosting iteration (num_class trees).
        Returns True if no tree could be grown (reference returns early-stop
        signal when the best gain is non-positive).  ``check_stop=False``
        skips the device->host sync — the benchmark path."""
        cfg = self.config
        if custom_grad is None and self._supports_fused_step():
            self._save_rollback_state()
            self._fused_train_one_iter()
            self.iter += 1
            if check_stop:
                new = self._device_trees[-self.num_class:]
                stopped = all(int(t.num_leaves) <= 1 for t in new)
                if stopped:
                    log_warning(
                        "Stopped training because there are no more leaves "
                        "that meet the split requirements"
                    )
                return stopped
            return False
        self._save_rollback_state()
        if custom_grad is not None:
            grad = jnp.asarray(np.asarray(custom_grad).reshape(self.num_data, -1), jnp.float32)
            hess = jnp.asarray(np.asarray(custom_hess).reshape(self.num_data, -1), jnp.float32)
        else:
            grad, hess = self._gradients()

        bag = self._bagging_mask(self.iter)
        new_trees = []
        for k in range(self.num_class):
            g3 = self._sample_g3(grad[:, k], hess[:, k], bag, self.iter)
            key = jax.random.fold_in(self._rng_key, self.iter * self.num_class + k)
            base_mask = jnp.asarray(self._tree_feature_mask())
            tree_dev, leaf_id, root_sum = self._grow(
                self._grow_binned, g3, base_mask, key, self._cegb_used)
            if self._cegb_enabled:
                self._cegb_used = self._update_cegb_state(
                    self._cegb_used, tree_dev, leaf_id)
            new_trees.append(self._finish_tree(tree_dev, leaf_id, k))
        self.iter += 1
        stopped = False
        if check_stop:
            stopped = all(int(t.num_leaves) <= 1 for t in new_trees)
            if stopped:
                log_warning(
                    "Stopped training because there are no more leaves that "
                    "meet the split requirements"
                )
        return stopped

    # ------------------------------------------------------------------
    def _finish_tree(self, tree_dev: TreeArrays, leaf_id: jax.Array, k: int,
                     shrinkage: Optional[float] = None) -> TreeArrays:
        """Renew leaf outputs, apply shrinkage, update scores, store model
        (reference: gbdt.cpp:368-380 RenewTreeOutput → Shrinkage → UpdateScore).

        Sync-free unless the objective needs host-side leaf renewal: a
        single-leaf tree has all-zero leaf values, so unconditional score
        updates are correct no-ops and no ``num_leaves`` check is needed."""
        cfg = self.config
        rate = cfg.learning_rate if shrinkage is None else shrinkage
        # init score is embedded into the saved model via AddBias
        # (reference: gbdt.cpp:381-383), NOT into the score caches (those
        # already carry it from _ScoreUpdater init)
        bias = self._tree_bias(k)

        if self._needs_host_tree:
            q = self.objective.renew_percentile if self.objective else None
            if q is not None:
                # ONE batched transfer for everything the renewal reads
                # (tree arrays + per-row leaf ids + this class's scores)
                # instead of three round-trips — at tunnel latency the
                # transfer count dominates the renewal cost
                arrays, lid_np, score_np = jax.device_get(
                    (tree_dev, leaf_id, self._train_scores.score[:, k]))
                host_tree = HostTree(arrays)
            else:
                host_tree = HostTree(jax.device_get(tree_dev))
            self._fill_real_thresholds(host_tree)
            if q is not None and host_tree.num_leaves > 1:
                new_vals = self._renew_leaf_values(host_tree, lid_np, k, q,
                                                   score_np)
                host_tree.set_leaf_values(new_vals)
                tree_dev = tree_dev._replace(
                    leaf_value=tree_dev.leaf_value.at[: host_tree.num_leaves].set(
                        jnp.asarray(new_vals, jnp.float32)
                    )
                )
            host_tree.apply_shrinkage(rate)
            host_tree.add_bias(bias)
            self.models.append(host_tree)
        else:
            self.models.append(None)  # materialized lazily in one batch

        shrunk = tree_dev._replace(leaf_value=tree_dev.leaf_value * rate)
        self._model_shrink.append(rate)
        self._model_bias.append(bias)

        # score updates: train via partition gather, valid via binned predict
        self._train_scores.add_leaf_values(shrunk.leaf_value, leaf_id, k)
        for vb, vs in zip(self._valid_binned, self._valid_scores):
            pred = tree_predict_binned(
                shrunk, vb, self.meta.nan_bin, self.meta.missing_type,
                self._bundle, self._packed, zero_bins=self.meta.zero_bin
            )
            vs.add_pred(pred, k)

        self._device_trees.append(shrunk)
        return shrunk

    # ------------------------------------------------------------------
    def materialize_host_trees(self) -> List[HostTree]:
        """Fetch all not-yet-materialized trees in one batched transfer."""
        idxs = [i for i, m in enumerate(self.models) if m is None]
        if idxs:
            with obs_trace.span("train.materialize_host_trees",
                                cat="train"), \
                    global_timer.section("GBDT::MaterializeHostTrees"):
                fetched = jax.device_get([self._device_trees[i] for i in idxs])
            for i, arrays in zip(idxs, fetched):
                ht = HostTree(arrays)
                # device leaf values already include shrinkage
                ht.shrinkage = self._model_shrink[i]
                self._fill_real_thresholds(ht)
                ht.add_bias(self._model_bias[i])
                self.models[i] = ht
        return self.models

    def _tree_bias(self, k: int) -> float:
        """Constant folded into this tree's saved leaf values.  GBDT: the
        init score goes into the first tree of each class (gbdt.cpp:381)."""
        if self.iter == 0 and not self._used_init_score:
            return float(self._init_scores[k])
        return 0.0

    def _fill_real_thresholds(self, tree: HostTree) -> None:
        mappers = self.train_set.bin_mappers
        for i in range(tree.num_leaves - 1):
            m = mappers[tree.split_feature[i]]
            if tree.is_cat[i]:
                # bin-space bitset -> raw category values (the translation
                # the reference does in Tree::SplitCategorical, tree.cpp:70-86)
                cats = [m.bin_2_categorical[b] for b in tree.cat_bins_of(i)
                        if b < len(m.bin_2_categorical)]
                tree.cat_sets[i] = np.asarray(sorted(cats), dtype=np.int64)
                tree.threshold[i] = 0.0   # rewritten to the cat index on save
            else:
                tree.threshold[i] = m.bin_to_threshold(tree.threshold_bin[i])

    def _renew_leaf_values(self, tree: HostTree, leaf_id, k: int, q: float,
                           score=None):
        """reference: RenewTreeOutput (objective-specific, e.g. L1 median —
        regression_objective.hpp RenewTreeOutput + percentile helpers).
        ``leaf_id``/``score`` arrive as host arrays from the caller's single
        batched device_get."""
        label = np.asarray(self.objective._np_label)
        if score is None:
            score = self._train_scores.score[:, k]
        score = np.asarray(score, dtype=np.float64)
        resid = label - score
        lid = np.asarray(leaf_id)
        w = self.objective.renew_weights()
        out = np.array(tree.leaf_value[: tree.num_leaves])
        for leaf in range(tree.num_leaves):
            rows = lid == leaf
            if not rows.any():
                continue
            r = resid[rows]
            order = np.argsort(r)
            if w is None:
                ww = np.ones(len(r))
            else:
                ww = np.asarray(w)[rows]
            out[leaf] = _np_weighted_quantile_sorted(r[order], ww[order], q)
        return out

    # ------------------------------------------------------------------
    def _save_rollback_state(self):
        score = self._train_scores.score
        valid = [vs.score for vs in self._valid_scores]
        if self._donate:
            # the fused step donates these buffers (in-place update); the
            # rollback / finite-guard snapshot must survive the donation,
            # so it keeps explicit copies — one (N, K) device copy per
            # cache per iteration, noise next to the histogram pass
            score = jnp.copy(score)
            valid = [jnp.copy(v) for v in valid]
        self._prev_state = (score, valid, len(self.models))

    def rollback_one_iter(self):
        """reference: GBDT::RollbackOneIter gbdt.cpp:421-437."""
        if self._prev_state is None:
            return
        score, valid_scores, n_models = self._prev_state
        self._train_scores.score = score
        for vs, s in zip(self._valid_scores, valid_scores):
            vs.score = s
        self.models = self.models[:n_models]
        self._device_trees = self._device_trees[:n_models]
        self._model_shrink = self._model_shrink[:n_models]
        self._model_bias = self._model_bias[:n_models]
        self.iter -= 1
        self._prev_state = None

    # ------------------------------------------------------------------
    # Crash-consistent checkpointing (io/checkpoint.py).  The captured
    # state is everything a resumed trainer needs to continue BIT-EXACTLY
    # where the killed one stopped: the same device tree arrays (bin
    # space — no text roundtrip in the loop), the same f32 score caches,
    # the same host RNG states.  Per-iteration PRNG (bagging, GOSS,
    # extra_trees, tree keys) is fold_in-keyed on the iteration counter
    # and therefore stateless — only the sequentially-consumed
    # RandomStates (feature sampling, DART drops) need saving.
    # ------------------------------------------------------------------
    @staticmethod
    def _host_fetch(arr) -> np.ndarray:
        """Dtype-preserving host fetch of a possibly cross-process
        array (checkpoint capture under multi-process training): an
        addressable or fully-replicated array reads directly; a
        process-spanning sharded one is gathered through a jitted
        identity with replicated out-sharding.  NOTE the gather is a
        COLLECTIVE — under ``jax.process_count() > 1`` every process
        must call ``capture_state`` in lockstep (the elastic worker
        captures on all ranks and writes on rank 0)."""
        if getattr(arr, "is_fully_addressable", True) or \
                getattr(arr, "is_fully_replicated", False):
            return np.asarray(jax.device_get(arr))
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = jax.jit(
            lambda a: a,
            out_shardings=NamedSharding(arr.sharding.mesh, P()))(arr)
        return np.asarray(jax.device_get(rep))

    def capture_state(self):
        """-> (manifest dict, arrays dict) for io.checkpoint.write."""
        from ..io.checkpoint import encode_rng_state
        from .tree import TreeArrays

        arrays: Dict[str, np.ndarray] = {}
        for f in TreeArrays._fields:
            arrays[f"tree_{f}"] = np.stack(
                [self._host_fetch(getattr(t, f))
                 for t in self._device_trees])
        arrays["train_score"] = self._host_fetch(self._train_scores.score)
        for i, vs in enumerate(self._valid_scores):
            arrays[f"valid_score_{i}"] = self._host_fetch(vs.score)
        if isinstance(self._cegb_used, tuple):
            arrays["cegb_used"] = self._host_fetch(self._cegb_used[0])
            arrays["cegb_marks"] = self._host_fetch(self._cegb_used[1])
        else:
            arrays["cegb_used"] = self._host_fetch(self._cegb_used)
        # Row-sharded (padded) layouts record the TRUE row count and the
        # pad mask so a resumed trainer with a DIFFERENT fleet shape (pod
        # shrink: elastic.py shrink_on_loss) can remap per-row state —
        # the padded global count is world-dependent, the real rows are
        # not (contiguous rank shards keep true global row order under
        # the mask on both sides).
        if self._row_valid is not None:
            rv = self._host_fetch(self._row_valid) > 0.5
            arrays["row_valid"] = rv
            num_data_true = int(rv.sum())
        else:
            num_data_true = int(self.num_data)
        manifest = {
            "iteration": int(self.iter),
            "num_trees": len(self.models),
            "num_class": int(self.num_class),
            "num_data": int(self.num_data),
            "num_data_true": num_data_true,
            "n_valid": len(self._valid_scores),
            "boosting": type(self).__name__,
            "objective": self.config.objective,
            "seed": int(self.config.seed),
            "used_init_score": bool(self._used_init_score),
            "init_scores": [float(v) for v in self._init_scores],
            "model_shrink": [float(v) for v in self._model_shrink],
            "model_bias": [float(v) for v in self._model_bias],
            "feat_rng": encode_rng_state(self._feat_rng),
        }
        self._capture_extra(manifest, arrays)
        return manifest, arrays

    def _capture_extra(self, manifest, arrays) -> None:
        """Subclass hook (DART adds drop RNG / weights / leaf ids)."""

    def restore_state(self, manifest, arrays) -> None:
        """Restore a captured state into a FRESH trainer built on the
        same dataset/config (valid sets already attached).  Raises
        :class:`~lightgbmv1_tpu.io.checkpoint.CheckpointError` on any
        shape/identity mismatch rather than resuming wrong."""
        from ..io.checkpoint import CheckpointError, decode_rng_state
        from .tree import TreeArrays

        if self.iter != 0 or self.models:
            raise CheckpointError(
                "restore_state() needs a fresh trainer (training already "
                f"started: iteration {self.iter})")
        # num_data: tolerate a PADDED-count change iff both sides are
        # row-sharded layouts agreeing on the TRUE row count (elastic
        # shrink repartitions the same rows over fewer hosts, so the
        # per-rank pad — and with it the padded global count — moves);
        # everything per-row is then remapped old-mask -> new-mask below.
        remap = False
        mask_old = mask_new = None
        if int(manifest["num_data"]) != self.num_data:
            true_want = manifest.get("num_data_true")
            if (true_want is None or self._row_valid is None
                    or "row_valid" not in arrays):
                raise CheckpointError(
                    "checkpoint/trainer mismatch on num_data: checkpoint "
                    f"has {int(manifest['num_data'])!r}, trainer has "
                    f"{self.num_data!r}")
            mask_new = np.asarray(self._row_valid) > 0.5
            mask_old = np.asarray(arrays["row_valid"]).astype(bool)
            if int(mask_new.sum()) != int(true_want) \
                    or int(mask_old.sum()) != int(true_want):
                raise CheckpointError(
                    "checkpoint/trainer mismatch on num_data_true: "
                    f"checkpoint has {int(true_want or -1)!r} real rows, "
                    f"trainer has {int(mask_new.sum())!r}")
            remap = True

        def _remap_rows(a: np.ndarray) -> np.ndarray:
            """Old padded layout -> new padded layout via the two pad
            masks (real rows keep true global order on both sides); new
            pad rows keep the fresh trainer's value."""
            if not remap:
                return a
            if a.shape[0] != mask_old.shape[0]:
                raise CheckpointError(
                    f"per-row checkpoint array has {a.shape[0]} rows, "
                    f"expected {mask_old.shape[0]} (old padded layout)")
            out = np.zeros((self.num_data,) + a.shape[1:], a.dtype)
            out[mask_new] = a[mask_old]
            return out

        def _remap_score(a: np.ndarray) -> np.ndarray:
            """Like :func:`_remap_rows` but new pad rows keep the fresh
            trainer's (init) score instead of 0 — matching what a
            from-scratch run at the new world shape would hold there."""
            if not remap:
                return a
            if a.shape[0] != mask_old.shape[0]:
                raise CheckpointError(
                    f"train_score checkpoint has {a.shape[0]} rows, "
                    f"expected {mask_old.shape[0]} (old padded layout)")
            out = np.asarray(self._train_scores.score, a.dtype).copy()
            out[mask_new] = a[mask_old]
            return out

        for key, want, got in (
                ("num_class", int(manifest["num_class"]), self.num_class),
                ("boosting", manifest["boosting"], type(self).__name__),
                ("objective", manifest["objective"],
                 self.config.objective),
                ("seed", int(manifest["seed"]), int(self.config.seed)),
                ("n_valid", int(manifest["n_valid"]),
                 len(self._valid_scores))):
            if want != got:
                raise CheckpointError(
                    f"checkpoint/trainer mismatch on {key}: checkpoint "
                    f"has {want!r}, trainer has {got!r}")
        T = int(manifest["num_trees"])
        stacked = {f: arrays[f"tree_{f}"] for f in TreeArrays._fields}
        if any(v.shape[0] != T for v in stacked.values()):
            raise CheckpointError("tree array stack does not match the "
                                  "manifest tree count")
        self._device_trees = [
            TreeArrays(**{f: jnp.asarray(stacked[f][i])
                          for f in TreeArrays._fields})
            for i in range(T)
        ]
        self.models = [None] * T
        self._model_shrink = [float(v) for v in manifest["model_shrink"]]
        self._model_bias = [float(v) for v in manifest["model_bias"]]
        self._train_scores.score = jnp.asarray(
            _remap_score(np.asarray(arrays["train_score"])))
        for i, vs in enumerate(self._valid_scores):
            vs.score = jnp.asarray(arrays[f"valid_score_{i}"])
        if "cegb_marks" in arrays:
            self._cegb_used = (jnp.asarray(arrays["cegb_used"]),
                               jnp.asarray(_remap_rows(
                                   np.asarray(arrays["cegb_marks"]))))
        else:
            self._cegb_used = jnp.asarray(arrays["cegb_used"])
        self._feat_rng.set_state(decode_rng_state(manifest["feat_rng"]))
        self._used_init_score = bool(manifest["used_init_score"])
        self._init_scores = np.asarray(manifest["init_scores"], np.float64)
        self._bag_mask = None
        self._prev_state = None
        self._restore_extra(manifest, arrays)
        self.iter = int(manifest["iteration"])   # last: bumps model_version

    def _restore_extra(self, manifest, arrays) -> None:
        """Subclass hook (DART)."""

    # ------------------------------------------------------------------
    @staticmethod
    def _host_array(arr) -> np.ndarray:
        """Fetch a (possibly cross-process-sharded) score array to host.
        With process-sharded training data the jitted score updates leave
        the scores row-sharded across processes; a jitted identity with a
        replicated out-sharding inserts the all-gather (the analog of the
        reference's score sync for metric evaluation)."""
        if getattr(arr, "is_fully_addressable", True) or \
                getattr(arr, "is_fully_replicated", False):
            return np.asarray(arr, dtype=np.float64)
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = jax.jit(
            lambda a: a,
            out_shardings=NamedSharding(arr.sharding.mesh, P()))(arr)
        return np.asarray(rep, dtype=np.float64)

    def _converted_pred(self, scores: _ScoreUpdater, objective) -> np.ndarray:
        raw = self._host_array(scores.score)
        s = raw[:, 0] if self.num_class == 1 else raw
        if objective is not None:
            s = objective.convert_output(s)
        return np.asarray(s, dtype=np.float64)

    def _raw_pred(self, scores: _ScoreUpdater) -> np.ndarray:
        """Raw margins for ``wants_raw`` metrics (reference: metrics reading
        score_ directly, e.g. AucMuMetric multiclass_metric.hpp:254)."""
        raw = self._host_array(scores.score)
        s = raw[:, 0] if self.num_class == 1 else raw
        return np.asarray(s, dtype=np.float64)

    def _eval_metrics(self, dataset_name, scores, metrics, out):
        pred = raw = None
        for m in metrics:
            if getattr(m, "wants_raw", False):
                if raw is None:
                    raw = self._raw_pred(scores)
                p = raw
            else:
                if pred is None:
                    pred = self._converted_pred(scores, self.objective)
                p = pred
            for name, value, hb in m.eval(p):
                out.append((dataset_name, name, value, hb))

    def eval_train(self):
        with global_timer.section("GBDT::EvalTrain"):
            return self._eval_train_inner()

    def _eval_train_inner(self):
        out = []
        self._eval_metrics("training", self._train_scores,
                           self.train_metrics, out)
        return out

    def eval_valid(self):
        with global_timer.section("GBDT::EvalValid"):
            return self._eval_valid_inner()

    def _eval_valid_inner(self):
        out = []
        for vname, vs, metrics in zip(
            self._valid_names, self._valid_scores, self._valid_metrics
        ):
            self._eval_metrics(vname, vs, metrics, out)
        return out

    # ------------------------------------------------------------------
    def raw_train_scores(self) -> np.ndarray:
        return self._host_array(self._train_scores.score)

    def num_trees(self) -> int:
        return len(self.models)

    @property
    def num_model_per_iteration(self) -> int:
        return self.num_class


# ---------------------------------------------------------------------------
# GOSS (reference: src/boosting/goss.hpp:25-150)
# ---------------------------------------------------------------------------


class GOSS(GBDT):
    """Gradient-based One-Side Sampling: keep the top_rate fraction of rows
    by |grad * hess|, sample other_rate of the rest, amplifying their
    grad/hess by (1 - top_rate) / other_rate."""

    def _sample_g3(self, grad_k, hess_k, bag, iteration):
        cfg = self.config
        n = self.num_data
        top_k = max(1, int(cfg.top_rate * n))
        other_k = max(1, int(cfg.other_rate * n))
        score = jnp.abs(grad_k * hess_k)
        thresh = jnp.sort(score)[-top_k]
        is_top = score >= thresh
        key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.seed + 17), iteration
        )
        rest_prob = other_k / jnp.maximum(n - top_k, 1)
        sampled_rest = (~is_top) & jax.random.bernoulli(key, rest_prob, (n,))
        amp = (1.0 - cfg.top_rate) / cfg.other_rate
        w = jnp.where(is_top, 1.0, jnp.where(sampled_rest, amp, 0.0))
        cnt = (is_top | sampled_rest).astype(jnp.float32)
        if bag is not None:
            w = w * bag
            cnt = cnt * bag
        return jnp.stack([grad_k * w, hess_k * w, cnt], axis=1)


# ---------------------------------------------------------------------------
# DART (reference: src/boosting/dart.hpp:23-170)
# ---------------------------------------------------------------------------


class DART(GBDT):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._drop_rng = np.random.RandomState(self.config.drop_seed)
        # per-tree weights driving the weighted (non-uniform) drop
        # (reference: dart.hpp tree_weight_/sum_weight_, :67-68,103-115)
        self._tree_weight: List[float] = []
        self._sum_weight = 0.0
        self._dart_steps: dict = {}    # (P, use_lids) -> compiled step
        # per-iteration (K, N) leaf assignments of the TRAIN rows: a drop's
        # train-score removal becomes leaf_value[lid] — one small-table
        # gather — instead of a per-row tree walk (which random-gathers the
        # (F, N) matrix per node and dominates DART cost on TPU).  Bounded
        # to ~1 GB of HBM; beyond that drops fall back to tree walks.
        self._train_leaf_ids: List[jax.Array] = []
        L = self.config.num_leaves
        self._lid_dtype = (jnp.uint8 if L <= 256
                           else jnp.uint16 if L <= 65536 else jnp.int32)
        # dynamic ~1 GB budget (config.num_iterations is unreliable here:
        # engine.train moves the round count into num_boost_round); once
        # exhausted — or once any host-path iteration breaks the
        # per-iteration alignment — the list is freed and drops fall back
        # to tree walks for the rest of the run
        self._lid_per_iter_bytes = (self.num_data * self.num_class
                                    * jnp.dtype(self._lid_dtype).itemsize)
        self._lid_budget = 1 << 30
        self._keep_lids = True
        self._lids_aligned = True

    def _maybe_store_lids(self, leaf_ids) -> None:
        if not (self._keep_lids and self._lids_aligned):
            return
        if ((len(self._train_leaf_ids) + 1) * self._lid_per_iter_bytes
                > self._lid_budget):
            self._keep_lids = False
            self._train_leaf_ids.clear()
            return
        self._train_leaf_ids.append(leaf_ids.astype(self._lid_dtype))

    def _drop_lids_usable(self) -> bool:
        return (self._keep_lids and self._lids_aligned
                and len(self._train_leaf_ids)
                == len(self.models) // self.num_class)

    def _capture_extra(self, manifest, arrays) -> None:
        from ..io.checkpoint import encode_rng_state

        manifest["dart"] = {
            "drop_rng": encode_rng_state(self._drop_rng),
            "tree_weight": [float(v) for v in self._tree_weight],
            "sum_weight": float(self._sum_weight),
            "lids_kept": bool(self._drop_lids_usable()),
        }
        if self._drop_lids_usable() and self._train_leaf_ids:
            # the recorded per-iteration (K, N) leaf assignments: restoring
            # them keeps the resumed run on the SAME fused drop path
            # (leaf-table gather) the uninterrupted run compiles, so the
            # two runs execute identical programs — the strongest
            # bit-exactness guarantee, not just value equality
            arrays["dart_lids"] = np.stack(
                [np.asarray(a) for a in jax.device_get(
                    self._train_leaf_ids)])

    def _restore_extra(self, manifest, arrays) -> None:
        from ..io.checkpoint import decode_rng_state

        d = manifest["dart"]
        self._drop_rng.set_state(decode_rng_state(d["drop_rng"]))
        self._tree_weight = [float(v) for v in d["tree_weight"]]
        self._sum_weight = float(d["sum_weight"])
        self._train_leaf_ids.clear()
        if d.get("lids_kept") and "dart_lids" in arrays:
            lids = arrays["dart_lids"]
            self._train_leaf_ids.extend(
                jnp.asarray(lids[i]).astype(self._lid_dtype)
                for i in range(lids.shape[0]))
            self._keep_lids = True
            self._lids_aligned = True
        else:
            # no recorded assignments: drops fall back to tree walks
            # (value-equal; the compiled drop program differs)
            self._keep_lids = False
            self._lids_aligned = False
        self._prev_weights = None

    def _supports_fused_step(self) -> bool:
        # the scanned multi-iteration path cannot host the per-iteration
        # drop selection; DART fuses WITHIN an iteration instead
        return False

    def _select_drops(self) -> List[int]:
        """Host-side drop selection (reference: dart.hpp DroppingTrees
        :96-137 — uniform_drop drops at drop_rate; otherwise each tree's
        probability is weighted by its current normalized weight)."""
        cfg = self.config
        n_trees = len(self.models) // self.num_class
        drop_iters: List[int] = []
        if n_trees > 0 and self._drop_rng.rand() >= cfg.skip_drop:
            dr = cfg.drop_rate
            if not cfg.uniform_drop and self._sum_weight > 0:
                inv_avg = len(self._tree_weight) / self._sum_weight
                if cfg.max_drop > 0:
                    dr = min(dr, cfg.max_drop * inv_avg / self._sum_weight)
                for i in range(n_trees):
                    if self._drop_rng.rand() < dr * self._tree_weight[i] * inv_avg:
                        drop_iters.append(i)
                        if cfg.max_drop > 0 and len(drop_iters) >= cfg.max_drop:
                            break
            else:
                if cfg.max_drop > 0:
                    dr = min(dr, cfg.max_drop / float(n_trees))
                for i in range(n_trees):
                    if self._drop_rng.rand() < dr:
                        drop_iters.append(i)
                        if cfg.max_drop > 0 and len(drop_iters) >= cfg.max_drop:
                            break
        return drop_iters

    def _normalization(self, k_drop: int):
        """(shrink_new, old_factor, w_dec) — reference dart.hpp Normalize
        :158-196 and shrinkage_rate_ :138-146."""
        lr = self.config.learning_rate
        if self.config.xgboost_dart_mode:
            shrink_new = lr if k_drop == 0 else lr / (lr + k_drop)
            return shrink_new, k_drop / (k_drop + lr), 1.0 / (k_drop + lr)
        return (lr / (k_drop + 1.0), k_drop / (k_drop + 1.0),
                1.0 / (k_drop + 1.0))

    def _snapshot_dropped(self, drop_iters: List[int]) -> None:
        """Extend the rollback snapshot with the dropped trees' state (the
        permanent old_factor rescale must be undoable)."""
        self._prev_state = self._prev_state + (
            {
                it * self.num_class + kk: (
                    None if self.models[it * self.num_class + kk] is None
                    else (
                        self.models[it * self.num_class + kk].leaf_value.copy(),
                        self.models[it * self.num_class + kk].internal_value.copy(),
                        self.models[it * self.num_class + kk].shrinkage,
                    ),
                    self._device_trees[it * self.num_class + kk].leaf_value,
                    self._model_shrink[it * self.num_class + kk],
                    self._model_bias[it * self.num_class + kk],
                )
                for it in drop_iters
                for kk in range(self.num_class)
            },
        )

    def _rescale_dropped(self, drop_iters: List[int], old_factor: float,
                         w_dec: float) -> None:
        """Permanent rescale of the dropped trees (reference Normalize
        :158-196).  Works for lazily-materialized trees: the device leaf
        values carry the rescale; _model_shrink/_model_bias metadata scale
        with them."""
        for it in drop_iters:
            for k in range(self.num_class):
                idx = it * self.num_class + k
                if self.models[idx] is not None:
                    self.models[idx].apply_shrinkage(old_factor)
                self._device_trees[idx] = self._device_trees[idx]._replace(
                    leaf_value=self._device_trees[idx].leaf_value * old_factor
                )
                self._model_shrink[idx] *= old_factor
                self._model_bias[idx] *= old_factor
            if not self.config.uniform_drop:
                self._sum_weight -= self._tree_weight[it] * w_dec
                self._tree_weight[it] *= old_factor

    # ------------------------------------------------------------------
    # fused DART iteration: drop removal, gradients, K class trees, drop
    # restore, and every score update in ONE device dispatch (the host
    # keeps only drop selection and bookkeeping).  Semantics identical to
    # the host-loop path below (reference dart.hpp:23-170).
    # ------------------------------------------------------------------
    def _build_dart_step(self, P: int, use_lids: bool):
        K = self.num_class

        def pred_with(tree, b):
            return tree_predict_binned(tree, b, self.meta.nan_bin,
                                       self.meta.missing_type,
                                       self._bundle, self._packed,
                                       zero_bins=self.meta.zero_bin)

        def step(binned, valid_binned, train_score, valid_scores, iteration,
                 feat_masks, cegb_used, drop_stack, drop_weight, shrink_new,
                 drop_lv, drop_lids):
            # drop_weight: (P, K) f32 one-hot rows scaled by the slot's
            # validity (0 rows = padding).  With use_lids the TRAIN removal
            # gathers drop_lv (P, L) bias-carrying leaf tables through the
            # RECORDED leaf assignments drop_lids (P, N) — a small-table
            # gather instead of a per-row tree walk (the walk random-
            # gathers the (F, N) matrix per node and dominated DART cost);
            # drop_stack (full TreeArrays over P slots) is only needed for
            # valid-set removal, where no assignments were recorded.
            if use_lids:
                preds = jax.vmap(leaf_lookup)(drop_lv, drop_lids)  # (P, N)
            else:
                preds = jax.vmap(lambda t: pred_with(t, binned))(drop_stack)
            drop_delta = preds.T @ drop_weight                   # (N, K)
            s_drop = train_score - drop_delta
            v_drops, v_deltas = [], []
            for vb, vscore in zip(valid_binned, valid_scores):
                vp = jax.vmap(lambda t: pred_with(t, vb))(drop_stack)
                vd = vp.T @ drop_weight
                v_deltas.append(vd)
                v_drops.append(vscore - vd)

            s = s_drop[:, 0] if K == 1 else s_drop
            grad, hess = self._objective_grads(s, iteration)
            if grad.ndim == 1:
                grad, hess = grad[:, None], hess[:, None]
            bag = self._bag_fraction_mask(None, iteration)

            trees, leaf_ids = [], []
            for k in range(K):
                g3 = self._sample_g3(grad[:, k], hess[:, k], bag, iteration)
                key = jax.random.fold_in(self._rng_key, iteration * K + k)
                tree_dev, leaf_id, _ = self._grow(binned, g3, feat_masks[k],
                                                  key, cegb_used)
                if self._cegb_enabled:
                    cegb_used = self._update_cegb_state(cegb_used, tree_dev,
                                                        leaf_id)
                shrunk = tree_dev._replace(
                    leaf_value=tree_dev.leaf_value * shrink_new)
                trees.append(shrunk)
                leaf_ids.append(leaf_id)
            return (s_drop, tuple(v_drops), drop_delta, tuple(v_deltas),
                    jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees),
                    jnp.stack(leaf_ids), cegb_used)

        def full(binned, valid_binned, train_score, valid_scores, iteration,
                 feat_masks, cegb_used, drop_stack, drop_weight, shrink_new,
                 old_factor, drop_lv=None, drop_lids=None):
            (s_drop, v_drops, d_delta, v_deltas, stacked, leaf_ids,
             cegb_used) = step(binned, valid_binned, train_score,
                               valid_scores, iteration, feat_masks,
                               cegb_used, drop_stack, drop_weight,
                               shrink_new, drop_lv, drop_lids)
            new_train = s_drop + old_factor * d_delta
            new_valids = [vs + old_factor * vd
                          for vs, vd in zip(v_drops, v_deltas)]
            for k in range(K):
                tree_k = jax.tree_util.tree_map(lambda a: a[k], stacked)
                new_train = new_train.at[:, k].add(
                    leaf_lookup(tree_k.leaf_value, leaf_ids[k]))
                new_valids = [
                    nv.at[:, k].add(pred_with(tree_k, vb))
                    for nv, vb in zip(new_valids, valid_binned)
                ]
            return (new_train, tuple(new_valids), stacked, leaf_ids,
                    cegb_used)

        # same donation contract as the plain fused step: args 2/3 are the
        # score caches, updated in place (rollback snapshots keep copies)
        return obs_xla.instrument_jit(
            full, "train.dart_step",
            donate_argnums=(2, 3) if self._donate else ())

    def _dart_step_for(self, P: int, use_lids: bool):
        key = (P, use_lids)
        if key not in self._dart_steps:
            self._dart_steps[key] = self._build_dart_step(P, use_lids)
        return self._dart_steps[key]

    def _fused_dart_iter(self, drop_iters: List[int]) -> None:
        cfg = self.config
        K = self.num_class
        k_drop = len(drop_iters)
        shrink_new, old_factor, w_dec = self._normalization(k_drop)
        self._snapshot_dropped(drop_iters)

        # padded drop stack: fixed bucket sizes keep the number of compiled
        # step variants tiny (each new P is a full recompile of the fused
        # iteration — the dominant DART cost if P tracked k_drop exactly)
        n_real = k_drop * K
        P = next(b for b in (4, 16, 64, 256, 1024) if b >= n_real) \
            if n_real <= 1024 else n_real
        # leaf-id fast path only while every past iteration recorded its
        # assignments (a host-path iteration, e.g. custom fobj, breaks the
        # alignment — then drops fall back to tree walks)
        use_lids = self._drop_lids_usable()
        need_stack = (not use_lids) or bool(self._valid_binned)
        entries, weights = [], np.zeros((P, K), np.float32)
        lv_tables, lid_rows = [], []
        for j, it in enumerate(drop_iters):
            for k in range(K):
                idx = it * K + k
                t = self._device_trees[idx]
                b = self._model_bias[idx]
                if b:
                    t = t._replace(leaf_value=t.leaf_value + b)
                if need_stack:
                    entries.append(t)
                if use_lids:
                    lv_tables.append(t.leaf_value)
                    lid_rows.append(self._train_leaf_ids[it][k])
                weights[j * K + k, k] = 1.0
        drop_stack = drop_lv = drop_lids = None
        if need_stack:
            while len(entries) < P:
                entries.append(entries[0])    # padding; weight row is 0
            drop_stack = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                                *entries)
        if use_lids:
            while len(lv_tables) < P:
                lv_tables.append(lv_tables[0])
                lid_rows.append(lid_rows[0])
            drop_lv = jnp.stack(lv_tables)
            drop_lids = jnp.stack(lid_rows)

        step = self._dart_step_for(P, use_lids)
        feat_masks = jnp.asarray(
            np.stack([self._tree_feature_mask() for _ in range(K)]))
        vscores = tuple(vs.score for vs in self._valid_scores)
        with global_timer.section("DART::TrainOneIter(dispatch)"):
            (new_train, new_valid, stacked, leaf_ids,
             self._cegb_used) = step(
                self._grow_binned, tuple(self._valid_binned),
                self._train_scores.score, vscores,
                jnp.asarray(self.iter, jnp.int32), feat_masks,
                self._cegb_used, drop_stack, jnp.asarray(weights),
                jnp.float32(shrink_new), jnp.float32(old_factor),
                drop_lv, drop_lids,
            )
        self._train_scores.score = new_train
        for vs, s in zip(self._valid_scores, new_valid):
            vs.score = s
        self._maybe_store_lids(leaf_ids)
        for k in range(K):
            self._device_trees.append(
                jax.tree_util.tree_map(lambda a: a[k], stacked))
            self.models.append(None)
            self._model_shrink.append(shrink_new)
            self._model_bias.append(self._tree_bias(k))

        self._rescale_dropped(drop_iters, old_factor, w_dec)
        if not cfg.uniform_drop:
            self._tree_weight.append(shrink_new)
            self._sum_weight += shrink_new

    def train_one_iter(self, custom_grad=None, custom_hess=None,
                       check_stop: bool = True) -> bool:
        cfg = self.config
        fused_ok = (custom_grad is None and self.objective is not None
                    and self.objective.renew_percentile is None
                    and not self._needs_host_tree)
        if fused_ok:
            self._save_rollback_state()
            self._prev_weights = (list(self._tree_weight), self._sum_weight)
            drop_iters = self._select_drops()
            if not drop_iters:
                # no drop: exactly a plain GBDT iteration at rate lr
                self._fused_train_one_iter()
                if not cfg.uniform_drop:
                    lr = cfg.learning_rate
                    self._tree_weight.append(lr)
                    self._sum_weight += lr
            else:
                self._fused_dart_iter(drop_iters)
            self.iter += 1
            if check_stop:
                new = self._device_trees[-self.num_class:]
                stopped = all(int(t.num_leaves) <= 1 for t in new)
                return stopped
            return False
        return self._host_train_one_iter(custom_grad, custom_hess,
                                         check_stop)

    def _host_train_one_iter(self, custom_grad=None, custom_hess=None,
                             check_stop: bool = True) -> bool:
        cfg = self.config
        # this path records no leaf assignments: the per-iteration list
        # would misalign, so free it and use tree walks from here on
        self._lids_aligned = False
        self._train_leaf_ids.clear()
        self._save_rollback_state()
        self._prev_weights = (list(self._tree_weight), self._sum_weight)
        drop_iters = self._select_drops()
        k_drop = len(drop_iters)

        # remove dropped trees' contribution from scores, caching each
        # prediction so the restore pass below costs no second traversal
        dropped_preds = {}
        if k_drop:
            # rollback must be able to undo the permanent rescaling of
            # dropped trees, so snapshot their values
            self._snapshot_dropped(drop_iters)
            dropped_preds = self._remove_dropped(drop_iters)

        if custom_grad is not None:
            grad = jnp.asarray(np.asarray(custom_grad).reshape(self.num_data, -1), jnp.float32)
            hess = jnp.asarray(np.asarray(custom_hess).reshape(self.num_data, -1), jnp.float32)
        else:
            grad, hess = self._gradients()
        bag = self._bagging_mask(self.iter)

        shrink_new, old_factor, w_dec = self._normalization(k_drop)

        new_trees = []
        for k in range(self.num_class):
            g3 = self._sample_g3(grad[:, k], hess[:, k], bag, self.iter)
            key = jax.random.fold_in(self._rng_key, self.iter * self.num_class + k)
            base_mask = jnp.asarray(self._tree_feature_mask())
            tree_dev, leaf_id, _ = self._grow(
                self._grow_binned, g3, base_mask, key, self._cegb_used)
            if self._cegb_enabled:
                self._cegb_used = self._update_cegb_state(
                    self._cegb_used, tree_dev, leaf_id)
            new_trees.append(
                self._finish_tree(tree_dev, leaf_id, k, shrinkage=shrink_new)
            )
        stopped = all(int(t.num_leaves) <= 1 for t in new_trees)

        # scale dropped trees and restore their (rescaled) contribution —
        # reusing the cached removal predictions, scaled by old_factor
        if k_drop:
            for it in drop_iters:
                for k in range(self.num_class):
                    idx = it * self.num_class + k
                    if self.models[idx] is not None:
                        self.models[idx].apply_shrinkage(old_factor)
                    self._device_trees[idx] = self._device_trees[idx]._replace(
                        leaf_value=self._device_trees[idx].leaf_value * old_factor
                    )
                    # metadata scales with the tree (shrinkage for lazy
                    # materialization, the embedded init score always)
                    self._model_shrink[idx] *= old_factor
                    self._model_bias[idx] *= old_factor
                    pred, vpreds = dropped_preds[idx]
                    self._train_scores.add_pred(old_factor * pred, k)
                    for vs, vp in zip(self._valid_scores, vpreds):
                        vs.add_pred(old_factor * vp, k)
                if not cfg.uniform_drop:
                    # reference Normalize weight rescale (:173-175,:191-194)
                    self._sum_weight -= self._tree_weight[it] * w_dec
                    self._tree_weight[it] *= old_factor

        if not cfg.uniform_drop:
            self._tree_weight.append(shrink_new)
            self._sum_weight += shrink_new
        self.iter += 1
        return stopped

    def _remove_dropped(self, drop_iters: List[int]):
        """Subtract dropped trees from all score caches; return the cached
        per-tree predictions keyed by model index.

        Drops use the **bias-carrying** tree (the embedded init score included)
        exactly like the reference, which drops via the saved model trees
        (dart.hpp DroppingTrees uses models_, whose first tree absorbed the
        init via AddBias) — this keeps score caches and the saved model
        consistent under drop-normalization."""
        preds = {}
        for it in drop_iters:
            for k in range(self.num_class):
                idx = it * self.num_class + k
                tree = self._device_trees[idx]
                b = self._model_bias[idx]
                if b:
                    tree = tree._replace(leaf_value=tree.leaf_value + b)
                pred = tree_predict_binned(
                    tree, self.binned, self.meta.nan_bin,
                    self.meta.missing_type, self._bundle, self._packed,
                    zero_bins=self.meta.zero_bin)
                self._train_scores.add_pred(-pred, k)
                vpreds = []
                for vb, vs in zip(self._valid_binned, self._valid_scores):
                    vp = tree_predict_binned(
                        tree, vb, self.meta.nan_bin,
                        self.meta.missing_type, self._bundle, self._packed,
                        zero_bins=self.meta.zero_bin)
                    vs.add_pred(-vp, k)
                    vpreds.append(vp)
                preds[idx] = (pred, vpreds)
        return preds

    def rollback_one_iter(self):
        if self._prev_state is not None and len(self._prev_state) == 4:
            dropped = self._prev_state[3]
            for idx, (host_snap, dev_vals, shrink, bias) in dropped.items():
                if host_snap is not None and self.models[idx] is not None:
                    lv, iv, sh = host_snap
                    self.models[idx].leaf_value = lv
                    self.models[idx].internal_value = iv
                    self.models[idx].shrinkage = sh
                self._device_trees[idx] = self._device_trees[idx]._replace(
                    leaf_value=dev_vals
                )
                self._model_shrink[idx] = shrink
                self._model_bias[idx] = bias
            self._prev_state = self._prev_state[:3]
        if getattr(self, "_prev_weights", None) is not None:
            self._tree_weight, self._sum_weight = self._prev_weights
            self._prev_weights = None
        super().rollback_one_iter()
        keep = len(self.models) // self.num_class
        del self._train_leaf_ids[keep:]


# ---------------------------------------------------------------------------
# RF (reference: src/boosting/rf.hpp:25 — bagging-required, averaged outputs)
# ---------------------------------------------------------------------------


class RF(GBDT):
    def __init__(self, config, train_set, objective=None, metrics=None,
                 init_raw_scores=None):
        if config.bagging_freq <= 0 or config.bagging_fraction >= 1.0:
            log_fatal("RF mode requires bagging "
                      "(bagging_freq > 0 and bagging_fraction < 1)")
        if train_set.metadata.init_score is not None:
            log_fatal("RF mode does not support init_score (reference rf.hpp:44)")
        if init_raw_scores is not None:
            log_fatal("RF mode does not support continued training")
        super().__init__(config, train_set, objective, metrics)

    def _tree_bias(self, k: int) -> float:
        # reference rf.hpp:136: every tree absorbs the init score, and
        # prediction divides the summed output by the iteration count
        return float(self._init_scores[k])

    _cached_grads = None

    def _gradients(self):
        # gradients always computed at the constant init score — computed
        # once and reused (reference rf.hpp: "only boosting one time")
        if self._cached_grads is None:
            init = jnp.asarray(
                np.broadcast_to(self._init_scores[None, :],
                                (self.num_data, self.num_class)),
                jnp.float32,
            )
            s = init[:, 0] if self.num_class == 1 else init
            grad, hess = self.objective.get_gradients(s)
            if grad.ndim == 1:
                grad, hess = grad[:, None], hess[:, None]
            self._cached_grads = (grad, hess)
        return self._cached_grads

    def _objective_grads(self, s, iteration=None):
        # gradients always evaluated at the constant init score
        init = jnp.asarray(self._init_scores, jnp.float32)
        const = jnp.broadcast_to(init[None, :], (self.num_data, self.num_class))
        sc = const[:, 0] if self.num_class == 1 else const
        if getattr(self.objective, "is_stochastic", False):
            grad, hess = self.objective.get_gradients(sc, iteration=iteration)
        else:
            grad, hess = self.objective.get_gradients(sc)
        return self._guard_grads(grad, hess, iteration)

    def train_one_iter(self, custom_grad=None, custom_hess=None,
                       check_stop: bool = True) -> bool:
        # trees are unshrunk; scores hold the running *sum*, converted to an
        # average at eval time
        if custom_grad is None and self._supports_fused_step():
            return GBDT.train_one_iter(self, check_stop=check_stop)
        cfg = self.config
        self._save_rollback_state()
        grad, hess = (
            self._gradients()
            if custom_grad is None
            else (
                jnp.asarray(np.asarray(custom_grad).reshape(self.num_data, -1), jnp.float32),
                jnp.asarray(np.asarray(custom_hess).reshape(self.num_data, -1), jnp.float32),
            )
        )
        bag = self._bagging_mask(self.iter)
        new_trees = []
        for k in range(self.num_class):
            g3 = self._sample_g3(grad[:, k], hess[:, k], bag, self.iter)
            key = jax.random.fold_in(self._rng_key, self.iter * self.num_class + k)
            base_mask = jnp.asarray(self._tree_feature_mask())
            tree_dev, leaf_id, _ = self._grow(
                self._grow_binned, g3, base_mask, key, self._cegb_used)
            if self._cegb_enabled:
                self._cegb_used = self._update_cegb_state(
                    self._cegb_used, tree_dev, leaf_id)
            new_trees.append(self._finish_tree(tree_dev, leaf_id, k, shrinkage=1.0))
        self.iter += 1
        if custom_grad is None and check_stop:
            return all(int(t.num_leaves) <= 1 for t in new_trees)
        return False

    def _converted_pred(self, scores, objective):
        n_iter = max(self.iter, 1)
        init = jnp.asarray(self._init_scores[None, :], jnp.float32)
        raw = init + (scores.score - init) / n_iter
        s = raw[:, 0] if self.num_class == 1 else raw
        if objective is not None:
            s = objective.convert_output(s)
        return np.asarray(s, dtype=np.float64)

    def _raw_pred(self, scores):
        n_iter = max(self.iter, 1)
        init = jnp.asarray(self._init_scores[None, :], jnp.float32)
        raw = init + (scores.score - init) / n_iter
        s = raw[:, 0] if self.num_class == 1 else raw
        return np.asarray(s, dtype=np.float64)


def create_boosting(config: Config, train_set: BinnedDataset, **kw) -> GBDT:
    """reference: Boosting::CreateBoosting, src/boosting/boosting.cpp:37-44."""
    kind = config.boosting
    if getattr(train_set, "is_streaming", False) or config.stream_enable:
        # out-of-core row-block trainer (models/gbdt_stream.py): a block
        # cache streams from disk; stream_enable=true wraps resident data
        # into the same block path (bounded device working set)
        from .gbdt_stream import create_streaming_boosting

        return create_streaming_boosting(config, train_set, **kw)
    if kind in ("gbdt", "gbrt"):
        return GBDT(config, train_set, **kw)
    if kind == "dart":
        return DART(config, train_set, **kw)
    if kind == "goss":
        return GOSS(config, train_set, **kw)
    if kind in ("rf", "random_forest"):
        return RF(config, train_set, **kw)
    log_fatal(f"Unknown boosting type: {kind}")
