"""Exact TreeSHAP feature contributions.

Implements the polynomial-time exact SHAP value algorithm for decision
trees (Lundberg et al., "Consistent Individualized Feature Attribution for
Tree Ensembles") — the same algorithm behind the reference's
``Tree::PredictContrib`` / ``TreeSHAP`` (include/LightGBM/tree.h:138,
src/io/tree.cpp), replacing the Saabas approximation used in round 1.

The path state mirrors the published algorithm: a list of
(feature_index, zero_fraction, one_fraction, pweight) entries extended at
each internal node and unwound when a feature repeats on the path.
Per-node "cover" weights come from the training row counts stored in the
model (internal_count / leaf_count), exactly like the reference.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .tree import HostTree


class _Path:
    __slots__ = ("d", "z", "o", "w")

    def __init__(self, d, z, o, w):
        self.d = d
        self.z = z
        self.o = o
        self.w = w


def _extend(path: List[_Path], pz: float, po: float, pi: int) -> List[_Path]:
    # copy-on-extend: the recursion shares parent paths between the hot and
    # cold branches (the C++ implementation copies into a fresh buffer per
    # call, tree_shap's unique_path + unique_depth+1 offset)
    path = [_Path(p.d, p.z, p.o, p.w) for p in path] + [
        _Path(pi, pz, po, 1.0 if len(path) == 0 else 0.0)]
    n = len(path) - 1
    for i in range(n - 1, -1, -1):
        path[i + 1].w += po * path[i].w * (i + 1) / (n + 1)
        path[i].w = pz * path[i].w * (n - i) / (n + 1)
    return path


def _unwind(path: List[_Path], i: int) -> List[_Path]:
    n = len(path) - 1
    po, pz = path[i].o, path[i].z
    out = [_Path(p.d, p.z, p.o, p.w) for p in path]
    nxt = out[n].w
    for j in range(n - 1, -1, -1):
        if po != 0:
            tmp = out[j].w
            out[j].w = nxt * (n + 1) / ((j + 1) * po)
            nxt = tmp - out[j].w * pz * (n - j) / (n + 1)
        else:
            out[j].w = out[j].w * (n + 1) / (pz * (n - j))
    for j in range(i, n):
        out[j].d, out[j].z, out[j].o = out[j + 1].d, out[j + 1].z, out[j + 1].o
    out.pop()
    return out


def _unwound_sum(path: List[_Path], i: int) -> float:
    n = len(path) - 1
    po, pz = path[i].o, path[i].z
    total = 0.0
    if po != 0:
        nxt = path[n].w
        for j in range(n - 1, -1, -1):
            tmp = nxt * (n + 1) / ((j + 1) * po)
            total += tmp
            nxt = path[j].w - tmp * pz * (n - j) / (n + 1)
    else:
        for j in range(n - 1, -1, -1):
            total += path[j].w * (n + 1) / (pz * (n - j))
    return total


def _node_count(tree: HostTree, child: int) -> float:
    if child < 0:
        return float(tree.leaf_count[-child - 1])
    return float(tree.internal_count[child])


def tree_expected_value(tree: HostTree) -> float:
    """Count-weighted mean output (reference: Tree::ExpectedValue)."""
    if tree.num_leaves <= 1:
        return float(tree.leaf_value[0]) if tree.num_leaves == 1 else 0.0
    total = tree.leaf_count.sum()
    if total <= 0:
        return 0.0
    return float((tree.leaf_value * tree.leaf_count).sum() / total)


def _tree_shap_row(tree: HostTree, go_left_row: np.ndarray,
                   phi: np.ndarray) -> None:
    """Accumulate one tree's SHAP values for one row into ``phi`` (F+1,).

    ``go_left_row``: precomputed decision per internal node (vectorized
    HostTree._go_left over all nodes at once).  Iterative DFS with an
    explicit stack — path depth can approach num_leaves-1 for leaf-wise
    trees, beyond Python's recursion limit.
    """
    stack = [(0, [], 1.0, 1.0, -1)]
    while stack:
        node, path, pz, po, pi = stack.pop()
        path = _extend(path, pz, po, pi)
        if node < 0:
            v = float(tree.leaf_value[-node - 1])
            for i in range(1, len(path)):
                w = _unwound_sum(path, i)
                phi[path[i].d] += w * (path[i].o - path[i].z) * v
            continue
        if go_left_row[node]:
            hot, cold = int(tree.left_child[node]), int(tree.right_child[node])
        else:
            hot, cold = int(tree.right_child[node]), int(tree.left_child[node])
        f = int(tree.split_feature[node])
        cnt = float(tree.internal_count[node])
        hot_frac = _node_count(tree, hot) / cnt if cnt > 0 else 0.0
        cold_frac = _node_count(tree, cold) / cnt if cnt > 0 else 0.0
        iz, io = 1.0, 1.0
        k = next((i for i in range(1, len(path)) if path[i].d == f), None)
        if k is not None:
            iz, io = path[k].z, path[k].o
            path = _unwind(path, k)
        stack.append((hot, path, iz * hot_frac, io, f))
        stack.append((cold, path, iz * cold_frac, 0.0, f))


def tree_shap(tree: HostTree, X: np.ndarray) -> np.ndarray:
    """(N, F+1) SHAP values for one tree; last column is the expected value
    (the reference appends it per tree too, PredictContrib)."""
    N, F = X.shape
    out = np.zeros((N, F + 1), dtype=np.float64)
    out[:, F] = tree_expected_value(tree)
    if tree.num_leaves <= 1:
        return out
    n_nodes = tree.num_leaves - 1
    # (N, n_nodes) decision matrix via the vectorized host walk
    go_left = np.empty((N, n_nodes), dtype=bool)
    for nd in range(n_nodes):
        f = int(tree.split_feature[nd])
        go_left[:, nd] = tree._go_left(np.full(N, nd, dtype=np.int64),
                                       X[:, f].astype(np.float64))
    for r in range(N):
        _tree_shap_row(tree, go_left[r], out[r])
    return out
