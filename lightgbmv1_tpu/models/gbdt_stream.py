"""Row-block streaming boosting drivers — out-of-core training.

:class:`StreamingGBDT` / :class:`StreamingDART` subclass the resident
drivers (models/gbdt.py) and replace every O(N)-on-device pass with a
block-streamed equivalent:

* the **binned matrix** never lands on device whole — per-split passes
  stream verified cache blocks (models/grower_stream.py);
* **score / gradient / leaf-routing state** lives host-side as (N,·)
  numpy shards sliced per block (the reference keeps exactly this state
  in RAM; rows·features is the HBM-breaking term, not rows alone);
* per-block **gradients** run the real objective on device over sliced
  inputs (elementwise objectives: slice == full, bit-for-bit).

Parity contract (tests/test_stream_train.py): with a fixed block order,
streaming training produces **byte-identical model text** to the
resident trainer at the sequential best-first schedule
(``tree_growth=leafwise_masked`` — the parity configuration) across
binary / multiclass / DART including bagging, feature_fraction,
categorical/NaN and valid sets.  The mechanism is arithmetic-order
preservation, not luck: histogram scatter folds continue the resident
pass's update order, score updates are one-add-per-element on both
sides, and DART's drop matmul keeps the same padded (P, K) shape.

Not streamable (rejected loudly at construction): forced splits, CEGB,
EFB bundle-only data, ranking objectives (per-query state), objectives
with host leaf renewal (L1/quantile/MAPE/Huber), stochastic objectives,
custom ``fobj``, GOSS/RF boosting, parallel tree learners.
"""

from __future__ import annotations

import copy
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data.streaming import DeviceLedger, block_source_for
from ..io.dataset import BinnedDataset
from ..utils.log import log_fatal, log_info, log_warning
from .gbdt import DART, GBDT
from .tree import leaf_lookup, tree_predict_binned


class _HostScoreStore:
    """Host-backed (N, K) f32 score cache — the streaming analog of
    _ScoreUpdater.  Same one-add-per-element update semantics (numpy f32
    adds are the same IEEE ops XLA emits for the resident cache)."""

    def __init__(self, num_data: int, num_class: int, init: np.ndarray):
        self.score = np.broadcast_to(
            init, (num_data, num_class)).astype(np.float32).copy()

    def add_leaf_values(self, leaf_values, leaf_id, k: int):
        self.score[:, k] += np.asarray(leaf_values)[np.asarray(leaf_id)]

    def add_pred(self, pred, k: int):
        self.score[:, k] += np.asarray(pred, np.float32)


class _ObjectiveSlicer:
    """Per-block views of a globally-initialized objective.

    ``init()`` ran ONCE over the full metadata, so global statistics
    (class balance weights, label transforms) are already baked into the
    instance; every (N,)-leading array attribute is then re-homed to host
    memory, and ``sliced(a, b)`` hands back a shallow copy whose arrays
    are the device slices for one block.  Elementwise objectives produce
    bit-identical per-row gradients this way."""

    def __init__(self, obj, num_data: int):
        self._obj = obj
        self._host = {}
        for k, v in list(vars(obj).items()):
            if isinstance(v, (np.ndarray, jax.Array)) \
                    and getattr(v, "ndim", 0) >= 1 \
                    and v.shape[0] == num_data:
                arr = np.asarray(v)
                self._host[k] = arr
                setattr(obj, k, arr)   # frees the device-resident copy

    def sliced(self, a: int, b: int):
        o = copy.copy(self._obj)
        for k, v in self._host.items():
            setattr(o, k, jnp.asarray(v[a:b]))
        return o


def _check_streamable(config: Config, train_set) -> None:
    if config.tree_learner not in ("serial", ""):
        log_fatal(f"streaming training requires tree_learner=serial "
                  f"(got {config.tree_learner}); ROADMAP item 1 composes "
                  "multi-host loading with this path")
    if config.tree_growth == "levelwise":
        log_fatal("streaming training implements the sequential leaf-wise "
                  "schedule; tree_growth=levelwise is resident-only")
    if config.forcedsplits_filename:
        log_fatal("forcedsplits_filename is not supported by the "
                  "streaming trainer")
    if (config.cegb_tradeoff * config.cegb_penalty_split > 0
            or config.cegb_penalty_feature_coupled
            or config.cegb_penalty_feature_lazy):
        log_fatal("CEGB penalties are not supported by the streaming "
                  "trainer (per-row feature marks are O(N*F) state)")
    if train_set.metadata.group is not None:
        log_fatal("ranking objectives (query groups) are not supported by "
                  "the streaming trainer: per-query gradients span blocks")
    if getattr(train_set, "bundle_layout", None) is not None \
            and train_set.binned is None:
        log_fatal("EFB bundle-only (sparse-path) datasets are not "
                  "streamable; load dense data or set enable_bundle=false")


class StreamingGBDT(GBDT):
    """Out-of-core GBDT: device working set O(stream_block_rows · F)."""

    _is_streaming = True

    def __init__(self, config, train_set, objective=None, metrics=None,
                 init_raw_scores=None):
        _check_streamable(config, train_set)
        self._source = block_source_for(train_set, config.stream_block_rows)
        self._ledger = DeviceLedger()
        self._bag_cache = None
        super().__init__(config, train_set, objective, metrics,
                         init_raw_scores)
        # a packed4 cache (block-cache v3) streams packed shards: the
        # prediction walker decodes nibbles (tree_predict_binned packed
        # lane) and add_valid packs valid matrices to match
        self._packed = getattr(self._source, "bin_layout", "u8") \
            == "packed4"
        if self.objective is None:
            log_fatal("streaming training requires a built-in objective "
                      "(custom fobj needs full-matrix raw scores)")
        if self.objective.renew_percentile is not None:
            log_fatal(f"objective {config.objective} renews leaf values "
                      "host-side and is not supported by the streaming "
                      "trainer")
        if getattr(self.objective, "is_stochastic", False):
            log_fatal(f"objective {config.objective} draws per-row "
                      "randomness over the full matrix; not streamable")
        self._slicer = _ObjectiveSlicer(self.objective, self.num_data)
        self._guard_jit = jax.jit(self._stream_guard)
        self._drop_jit = jax.jit(
            lambda preds, w, sc: (preds.T @ w, sc - preds.T @ w))
        self._valid_jit = jax.jit(self._valid_update)
        log_info(
            f"Streaming trainer: {self._source.num_blocks} blocks of "
            f"{getattr(self._source, 'block_rows', 0)} rows "
            f"({self._source.num_rows} rows x {self._source.num_features} "
            "features; device working set bounded per block)")

    # -- plumbing overrides ---------------------------------------------
    @property
    def stream_peak_device_bytes(self) -> int:
        """Ledger peak of streaming-owned device allocations (the
        memory-guard contract's observable; data/streaming.DeviceLedger)."""
        return self._ledger.peak_bytes

    def _new_score_store(self, num_data, num_class, init):
        return _HostScoreStore(num_data, num_class, init)

    def _supports_fused_step(self) -> bool:
        return False

    def _build_trainer(self):
        from ..ops.histogram import default_hist_method
        from ..parallel.trainer import parse_interaction_constraints
        from .grower_stream import StreamGrower

        cfg = self.config
        method = default_hist_method(cfg.hist_method,
                                     self._source.block_dtype)
        if cfg.hist_method == "fused":
            # the fused wave-round kernel needs the resident wave grower;
            # streaming runs the sequential schedule on the staged AUTO
            # method (the documented fallback taxonomy, ops/wave_fused.py)
            method = default_hist_method("auto", self._source.block_dtype)
            log_warning("hist_method=fused: streaming training runs the "
                        "sequential schedule; using the staged "
                        f"'{method}' histogram path")
        if method == "pallas":
            log_warning("hist_method=pallas streams as per-block partial "
                        "sums: deterministic at fixed block order, but "
                        "not bit-identical to the resident kernel; use "
                        "scatter/onehot for the strict parity contract")
        if cfg.tree_growth == "leafwise":
            log_info("streaming trains the sequential best-first order "
                     "(the tree_growth=leafwise_masked / "
                     "leafwise_wave_size=1 parity schedule)")
        self._sgrow = StreamGrower(
            source=self._source,
            ledger=self._ledger,
            num_leaves=cfg.num_leaves,
            num_bins=self.num_bins,
            meta=self.meta,
            params=self.split_params,
            max_depth=cfg.max_depth,
            feature_fraction_bynode=cfg.feature_fraction_bynode,
            monotone_penalty=cfg.monotone_penalty,
            interaction_groups=parse_interaction_constraints(
                cfg.interaction_constraints, self.train_set.num_features),
            hist_method=method,
            hist_precision=cfg.hist_dtype,
            hist_pool_mb=cfg.histogram_pool_size,
            prefetch=cfg.stream_prefetch,
        )
        self._grow = None
        self._grow_binned = None
        self._step = None

    def _pred_with(self, tree, binned):
        return tree_predict_binned(tree, binned, self.meta.nan_bin,
                                   self.meta.missing_type, self._bundle,
                                   self._packed,
                                   zero_bins=self.meta.zero_bin)

    # -- streamed per-row passes ----------------------------------------
    def _stream_guard(self, grad, hess, iteration, row0):
        """_guard_grads with GLOBAL row indexing (the poison slice must
        hit the same rows regardless of block boundaries)."""
        if self._poison_iter is not None:
            n = grad.shape[0]
            rows = ((jnp.arange(n, dtype=jnp.int32) + row0) % 13) == 0
            bad = rows if grad.ndim == 1 else rows[:, None]
            firing = iteration == jnp.int32(self._poison_iter)
            poison = jnp.where(bad & firing, jnp.float32(jnp.nan),
                               jnp.float32(0.0))
            grad = grad + poison
            hess = hess + poison
        if self.config.finite_guard == "clamp":
            finite = jnp.isfinite(grad) & jnp.isfinite(hess)
            grad = jnp.where(finite, grad, 0.0)
            hess = jnp.where(finite, hess, 0.0)
        return grad, hess

    def _stream_gradients(self, score_np, iteration: int):
        """Per-block objective gradients -> host (N, K) f32 pair."""
        N, K = score_np.shape
        grad = np.empty((N, K), np.float32)
        hess = np.empty((N, K), np.float32)
        for a, b in self._source.ranges:
            s_dev = jnp.asarray(np.ascontiguousarray(score_np[a:b]))
            h = self._ledger.hold_array("grad_block", s_dev)
            s = s_dev[:, 0] if K == 1 else s_dev
            obj = self._slicer.sliced(a, b)
            g, hs = obj.get_gradients(s)
            g, hs = self._guard_jit(g, hs, jnp.asarray(iteration, jnp.int32),
                                    jnp.asarray(a, jnp.int32))
            g_np, h_np = jax.device_get((g, hs))
            grad[a:b] = np.asarray(g_np, np.float32).reshape(b - a, -1)
            hess[a:b] = np.asarray(h_np, np.float32).reshape(b - a, -1)
            self._ledger.release(h)
        return grad, hess

    def _stream_bagging_mask(self, iteration: int) -> Optional[np.ndarray]:
        """The fused step's in-jit Bernoulli draw, pulled host-side once
        per bagging period (one transient (N,) device draw — the only
        row-proportional device allocation streaming makes, 4N bytes)."""
        cfg = self.config
        use_pos_neg = (
            cfg.objective == "binary"
            and (cfg.pos_bagging_fraction < 1.0
                 or cfg.neg_bagging_fraction < 1.0))
        if cfg.bagging_freq <= 0 or (cfg.bagging_fraction >= 1.0
                                     and not use_pos_neg):
            return None
        period = iteration // max(cfg.bagging_freq, 1)
        if self._bag_cache is not None and self._bag_cache[0] == period:
            return self._bag_cache[1]
        mask = jax.jit(lambda it: self._bag_fraction_mask(None, it))(
            jnp.asarray(iteration, jnp.int32))
        h = self._ledger.hold_array("bag_mask", mask)
        mask_np = np.asarray(jax.device_get(mask), np.float32)
        self._ledger.release(h)
        self._bag_cache = (period, mask_np)
        return mask_np

    @staticmethod
    def _host_g3(grad_k, hess_k, bag):
        """_sample_g3 on host shards (f32 numpy ops are the same IEEE
        ops the fused step's jnp version emits)."""
        if bag is None:
            cnt = np.ones_like(grad_k)
        else:
            grad_k, hess_k, cnt = grad_k * bag, hess_k * bag, bag
        return np.stack([grad_k, hess_k, cnt], axis=1)

    # -- the iteration ---------------------------------------------------
    def _valid_update(self, vb, vscore, stacked_raw, rate):
        """The fused step's valid-set leg, op-for-op: shrinkage applied
        INSIDE the same jit as the walk and the one stacked add — the
        fusion context changes f32 rounding, so doing the multiply in a
        separate dispatch would break valid-score bit parity."""
        preds = []
        for k in range(self.num_class):
            tree_k = jax.tree_util.tree_map(lambda a: a[k], stacked_raw)
            shrunk = tree_k._replace(leaf_value=tree_k.leaf_value * rate)
            preds.append(self._pred_with(shrunk, vb))
        return vscore + jnp.stack(preds, axis=1)

    def _stream_plain_iter(self, shrinkage=None) -> List:
        K = self.num_class
        rate = (self.config.learning_rate if shrinkage is None
                else shrinkage)
        grad, hess = self._stream_gradients(self._train_scores.score,
                                            int(self.iter))
        bag = self._stream_bagging_mask(int(self.iter))
        raw_trees, new_trees, lids = [], [], []
        for k in range(K):
            g3 = self._host_g3(grad[:, k], hess[:, k], bag)
            key = jax.random.fold_in(self._rng_key,
                                     self.iter * K + k)
            base_mask = jnp.asarray(self._tree_feature_mask())
            tree_dev, leaf_id, _ = self._sgrow.grow(g3, base_mask, key)
            raw_trees.append(tree_dev)
            lids.append(leaf_id)
            shrunk = tree_dev._replace(
                leaf_value=tree_dev.leaf_value * rate)
            # train scores: host one-add-per-element (== the fused
            # step's leaf_lookup formulation)
            self._train_scores.add_leaf_values(shrunk.leaf_value,
                                               leaf_id, k)
            self._device_trees.append(shrunk)
            self.models.append(None)
            self._model_shrink.append(rate)
            self._model_bias.append(self._tree_bias(k))
            new_trees.append(shrunk)
        if self._valid_binned:
            stacked_raw = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *raw_trees)
            rate_dev = jnp.float32(rate)
            for vb, vs in zip(self._valid_binned, self._valid_scores):
                vs.score = self._valid_jit(vb, vs.score, stacked_raw,
                                           rate_dev)
        store = getattr(self, "_maybe_store_lids", None)
        if store is not None:
            store(np.stack(lids))
        return new_trees

    def train_one_iter(self, custom_grad=None, custom_hess=None,
                       check_stop: bool = True) -> bool:
        if custom_grad is not None:
            log_fatal("streaming training does not support custom "
                      "objectives (fobj): gradients stream per block "
                      "from the built-in objective")
        self._save_rollback_state()
        new_trees = self._stream_plain_iter()
        self.iter += 1
        if check_stop:
            stopped = all(int(t.num_leaves) <= 1 for t in new_trees)
            if stopped:
                log_warning(
                    "Stopped training because there are no more leaves "
                    "that meet the split requirements")
            return stopped
        return False

    # -- state management -------------------------------------------------
    def _save_rollback_state(self):
        # the host score array is mutated in place — the snapshot must be
        # a real copy (valid scores are immutable device arrays)
        self._prev_state = (self._train_scores.score.copy(),
                            [vs.score for vs in self._valid_scores],
                            len(self.models))

    def restore_state(self, manifest, arrays) -> None:
        super().restore_state(manifest, arrays)
        self._train_scores.score = np.asarray(arrays["train_score"],
                                              np.float32)
        self._bag_cache = None

    def check_finite_boundary(self) -> None:
        mode = self.config.finite_guard
        if mode not in ("warn", "raise"):
            return
        bad = not bool(np.isfinite(
            np.sum(self._train_scores.score, dtype=np.float64)))
        if not bad and self.objective is not None \
                and self._prev_state is not None and self.iter > 0:
            g, h = self._stream_gradients(self._prev_state[0],
                                          int(self.iter - 1))
            tot = np.sum(g, dtype=np.float64) + np.sum(h, dtype=np.float64)
            bad = not bool(np.isfinite(tot))
        if not bad:
            return
        from .gbdt import FiniteGuardError

        msg = (f"non-finite gradient/score state at iteration {self.iter} "
               f"boundary (finite_guard={mode}): the last iteration's "
               "trees are suspect — roll back or resume from the "
               "previous checkpoint")
        if mode == "raise":
            raise FiniteGuardError(msg)
        if not self._finite_warned:
            self._finite_warned = True
            log_warning(msg)


class StreamingDART(StreamingGBDT, DART):
    """Out-of-core DART: drop removal / restore stream per block through
    the recorded leaf-assignment tables (or per-block tree walks when no
    assignments were recorded), with the resident fused step's padded
    (P, K) drop matmul shape kept so the f32 reduction matches."""

    def train_one_iter(self, custom_grad=None, custom_hess=None,
                       check_stop: bool = True) -> bool:
        cfg = self.config
        if custom_grad is not None:
            log_fatal("streaming DART does not support custom objectives")
        self._save_rollback_state()
        self._prev_weights = (list(self._tree_weight), self._sum_weight)
        drop_iters = self._select_drops()
        if not drop_iters:
            new_trees = self._stream_plain_iter()
            if not cfg.uniform_drop:
                lr = cfg.learning_rate
                self._tree_weight.append(lr)
                self._sum_weight += lr
        else:
            new_trees = self._stream_dart_iter(drop_iters)
        self.iter += 1
        if check_stop:
            return all(int(t.num_leaves) <= 1 for t in new_trees)
        return False

    def _dart_valid_update(self, vb, vscore, drop_stack, w, old_factor,
                           stacked_raw, shrink_new):
        """The fused DART step's valid-set leg (models/gbdt.py full()):
        removal via the drop stack, restore at old_factor, then the new
        trees' predictions — identical op order, with the new trees'
        shrinkage applied INSIDE the jit exactly like step() does."""
        vp = jax.vmap(lambda t: self._pred_with(t, vb))(drop_stack)
        vd = vp.T @ w
        nv = (vscore - vd) + old_factor * vd
        for k in range(self.num_class):
            tree_k = jax.tree_util.tree_map(lambda a: a[k], stacked_raw)
            shrunk = tree_k._replace(
                leaf_value=tree_k.leaf_value * shrink_new)
            nv = nv.at[:, k].add(self._pred_with(shrunk, vb))
        return nv

    def _stream_dart_iter(self, drop_iters: List[int]) -> List:
        cfg = self.config
        K = self.num_class
        k_drop = len(drop_iters)
        shrink_new, old_factor, w_dec = self._normalization(k_drop)
        self._snapshot_dropped(drop_iters)

        n_real = k_drop * K
        P = next(b for b in (4, 16, 64, 256, 1024) if b >= n_real) \
            if n_real <= 1024 else n_real
        use_lids = self._drop_lids_usable()
        need_stack = (not use_lids) or bool(self._valid_binned)
        entries, weights = [], np.zeros((P, K), np.float32)
        lv_tables, lid_rows = [], []
        for j, it in enumerate(drop_iters):
            for k in range(K):
                idx = it * K + k
                t = self._device_trees[idx]
                b = self._model_bias[idx]
                if b:
                    t = t._replace(leaf_value=t.leaf_value + b)
                if need_stack:
                    entries.append(t)
                if use_lids:
                    lv_tables.append(t.leaf_value)
                    lid_rows.append(np.asarray(self._train_leaf_ids[it][k]))
                weights[j * K + k, k] = 1.0
        drop_stack = drop_lv = lid_rows_np = None
        if need_stack:
            while len(entries) < P:
                entries.append(entries[0])    # padding; weight row is 0
            drop_stack = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                                *entries)
        if use_lids:
            while len(lv_tables) < P:
                lv_tables.append(lv_tables[0])
                lid_rows.append(lid_rows[0])
            drop_lv = jnp.stack(lv_tables)
            lid_rows_np = np.stack(lid_rows)          # (P, N) host
        w_dev = jnp.asarray(weights)

        # drop removal, block by block -> host s_drop / delta shards
        score = self._train_scores.score
        s_drop = np.empty_like(score)
        delta = np.empty_like(score)
        for i, (a, b2) in enumerate(self._source.ranges):
            handles = []
            if use_lids:
                lid_blk = jnp.asarray(
                    np.ascontiguousarray(lid_rows_np[:, a:b2]))
                handles.append(self._ledger.hold_array("drop_lids",
                                                       lid_blk))
                preds = jax.vmap(leaf_lookup)(drop_lv, lid_blk)
            else:
                bins = jax.device_put(self._source.load_block(i))
                handles.append(self._ledger.hold_array("block_bins", bins))
                preds = jax.vmap(lambda t: self._pred_with(t, bins))(
                    drop_stack)
            sc_blk = jnp.asarray(np.ascontiguousarray(score[a:b2]))
            handles.append(self._ledger.hold_array("grad_block", sc_blk))
            d_blk, s_blk = self._drop_jit(preds, w_dev, sc_blk)
            d_np, s_np = jax.device_get((d_blk, s_blk))
            delta[a:b2] = np.asarray(d_np)
            s_drop[a:b2] = np.asarray(s_np)
            for h in handles:
                self._ledger.release(h)

        grad, hess = self._stream_gradients(s_drop, int(self.iter))
        bag = self._stream_bagging_mask(int(self.iter))
        shrink_dev = jnp.float32(shrink_new)
        raw_trees, trees, lids = [], [], []
        for k in range(K):
            g3 = self._host_g3(grad[:, k], hess[:, k], bag)
            key = jax.random.fold_in(self._rng_key, self.iter * K + k)
            base_mask = jnp.asarray(self._tree_feature_mask())
            tree_dev, leaf_id, _ = self._sgrow.grow(g3, base_mask, key)
            raw_trees.append(tree_dev)
            trees.append(tree_dev._replace(
                leaf_value=tree_dev.leaf_value * shrink_dev))
            lids.append(leaf_id)

        # train scores: restore at old_factor + the new trees' outputs
        # (the fused step's op order: one restore add, then one add per
        # class column)
        new_score = s_drop + np.float32(old_factor) * delta
        for k in range(K):
            lv = np.asarray(trees[k].leaf_value)
            new_score[:, k] += lv[lids[k]]
        self._train_scores.score = new_score

        if self._valid_binned:
            stacked_raw = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *raw_trees)
            of_dev = jnp.float32(old_factor)
            if not hasattr(self, "_dart_valid_jit"):
                self._dart_valid_jit = jax.jit(self._dart_valid_update)
            for vb, vs in zip(self._valid_binned, self._valid_scores):
                vs.score = self._dart_valid_jit(vb, vs.score, drop_stack,
                                                w_dev, of_dev,
                                                stacked_raw, shrink_dev)

        self._maybe_store_lids(np.stack(lids))
        for k in range(K):
            self._device_trees.append(trees[k])
            self.models.append(None)
            self._model_shrink.append(shrink_new)
            self._model_bias.append(self._tree_bias(k))

        self._rescale_dropped(drop_iters, old_factor, w_dec)
        if not cfg.uniform_drop:
            self._tree_weight.append(shrink_new)
            self._sum_weight += shrink_new
        return trees

    def _restore_extra(self, manifest, arrays) -> None:
        from ..io.checkpoint import decode_rng_state

        d = manifest["dart"]
        self._drop_rng.set_state(decode_rng_state(d["drop_rng"]))
        self._tree_weight = [float(v) for v in d["tree_weight"]]
        self._sum_weight = float(d["sum_weight"])
        self._train_leaf_ids.clear()
        if d.get("lids_kept") and "dart_lids" in arrays:
            lids = arrays["dart_lids"]
            # host shards (NOT device arrays): the drop gather slices them
            # per block
            self._train_leaf_ids.extend(
                np.asarray(lids[i]).astype(self._lid_dtype)
                for i in range(lids.shape[0]))
            self._keep_lids = True
            self._lids_aligned = True
        else:
            self._keep_lids = False
            self._lids_aligned = False
        self._prev_weights = None


def create_streaming_boosting(config: Config, train_set: BinnedDataset,
                              **kw) -> GBDT:
    """Streaming analog of create_boosting (gbdt.py dispatches here when
    the dataset is a block cache or stream_enable is set)."""
    kind = config.boosting
    if kind in ("gbdt", "gbrt"):
        return StreamingGBDT(config, train_set, **kw)
    if kind == "dart":
        return StreamingDART(config, train_set, **kw)
    log_fatal(f"boosting={kind} is not supported by the streaming "
              "trainer (supported: gbdt, dart)")
