"""TPU-native batched inference engine.

The reference serves bulk prediction with an OMP row-parallel per-row
walker (``src/application/predictor.hpp:29-160``).  The first device port
(`models/tree.ensemble_predict_raw`) kept the reference's *tree*-sequential
structure — a ``lax.scan`` whose body is a data-dependent while-loop walk,
i.e. O(T) serialized dispatches of unvectorizable gathers.  This module
rebuilds inference the same way training was made TPU-native: the
sequential branchy loop becomes fixed-trip-count dense array ops.

Three layers:

* **Depth-stepped all-trees walk** — an ``(N, T)`` int32 node-pointer
  array advanced ``max_depth`` times (computed host-side from the actual
  ensemble) with batched gathers over the stacked SoA node tables; leaves
  self-loop so the trip count is static.  ~``max_depth`` fused steps
  replace T sequential tree walks (`serving_leaf_raw` on raw features,
  `serving_leaf_binned` on prebinned codes; both carry raw-space
  categorical bitsets).

* **Prebinned serving codes** — the serving analog of the training
  ``BinMapper``: every threshold the ensemble actually splits on becomes a
  per-feature sorted boundary list, rows are binned ONCE on the host (in
  float64, so decisions are bit-exact against the reference's double
  compares — the raw device walk compares f32), and the walk compares
  uint8/uint16 codes against per-node bin indices.  The feature matrix
  shrinks 4x (8x vs f64) in HBM, NaN/missing-type routing is carried by
  two reserved codes, and categorical splits use raw-value bitsets.

* **Compile-amortizing predictor cache** — ``BatchPredictor`` pads batches
  to power-of-two row buckets and caches the jitted walk per (bucket,
  output kind); repeated serving calls never retrace (`Booster.predict`
  keys the predictor itself on (slice, tree count, model version), so a
  refit/update invalidates it).  Large batches stream through fixed-size
  chunks with the next chunk's H2D issued before the current chunk's walk
  is consumed (double buffering via JAX's async dispatch).

* **Serving megakernel** (``predict_method=fused``,
  ops/predict_pallas.serving_fused_pallas) — one Pallas launch per row
  tile walks every tree AND accumulates the per-class scores in VMEM;
  ``plan_predict_tiles`` tiles oversized ensembles into VMEM-sized tree
  groups, and with <= 15 serving codes per feature the codes ship 4-bit
  PACKED (two per byte), halving the H2D stream.  Node-exactness is
  pinned against the staged walk on the CPU interpret lane; a Mosaic
  lowering failure falls back to the staged walk, warned ONCE.

Row-sharded multi-chip serving reuses the training mesh helpers
(`parallel/cluster.make_mesh` + `parallel/trainer.shard_rows`): rows are
split over the mesh, the model is replicated, and no collective runs at
all — `tools` dryrun_multichip asserts node-exact parity vs single-device.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..io.binning import K_ZERO_THRESHOLD, MISSING_NAN, MISSING_ZERO
from ..obs import xla as obs_xla
from ..utils import faults
from ..utils.log import log_info, log_warning
from .tree import HostTree, host_tree_depth, validate_host_tree

# widest raw category representable as a serving bitset (same bar as the
# native predictor pack, native/__init__.py build_ensemble_pack)
_MAX_CAT_BITSET = 1 << 22

# process-wide log-once keys (the select_bin_layout engage/refuse idiom):
# a chunked streaming predict hits the same fallback on every chunk and
# a server rebuilds predictors per publish — the reason is logged once
_logged_once: set = set()


def _log_once(key: str, msg: str, warn: bool = False) -> None:
    if key in _logged_once:
        return
    _logged_once.add(key)
    (log_warning if warn else log_info)(msg)


def pack_serving_codes(codes: np.ndarray) -> np.ndarray:
    """(N, F) serving codes <= 15 -> (N, ceil(F/2)) packed bytes, two
    features per byte in the ops/hist_pallas.pack4bit nibble layout (lo
    nibble = even feature 2p, hi = 2p+1) — halves the serving H2D
    payload and the kernel's per-tile code footprint."""
    codes = np.asarray(codes, np.uint8)
    n, f = codes.shape
    if f % 2:
        codes = np.concatenate([codes, np.zeros((n, 1), np.uint8)], axis=1)
    return (codes[:, 0::2] | (codes[:, 1::2] << 4)).astype(np.uint8)


def unpack_serving_codes(packed, num_features: int):
    """``pack_serving_codes``'s inverse, numpy or jnp — the staged
    fallback unpacks ON DEVICE so packed H2D transport still pays off
    when the fused kernel refuses or fails to lower."""
    import jax
    import jax.numpy as jnp

    xp = jnp if isinstance(packed, jax.Array) else np
    lo = packed & 15
    hi = packed >> 4
    un = xp.stack([lo, hi], axis=2).reshape(packed.shape[0], -1)
    return un[:, :num_features].astype(xp.uint8)


def _transform_scores(s, transform):
    """The objective epilogue (None | 'sigmoid' | 'softmax') applied
    OUTSIDE the megakernel — the staged path's equivalent of the fused
    kernel's in-launch epilogue (same f32 math)."""
    if transform is None:
        return s
    import jax.numpy as jnp

    if transform == "sigmoid":
        return 1.0 / (1.0 + jnp.exp(-s))
    m = jnp.max(s, axis=1, keepdims=True)
    e = jnp.exp(s - m)
    return e / jnp.sum(e, axis=1, keepdims=True)


class ServingArrays(NamedTuple):
    """Stacked (T, ...) SoA node tables of the whole ensemble, device side.

    ``threshold`` carries the REAL split values (raw-feature walk);
    ``threshold_bin`` the serving-bin index of the same split (prebinned
    walk); ``cat_bitset`` is in RAW category space (unlike training-time
    ``TreeArrays`` whose bitsets live in training-bin space), so serving
    needs no host-side category dictionary."""

    num_leaves: Any      # (T,) int32
    split_feature: Any   # (T, L1) int32
    threshold: Any       # (T, L1) f32
    threshold_bin: Any   # (T, L1) int32 — serving-bin index
    zero_bin: Any        # (T, L1) int32 — serving bin of 0.0 for the
                         #   node's feature (NaN-as-zero / zero-missing)
    default_left: Any    # (T, L1) bool
    missing_type: Any    # (T, L1) int32
    left_child: Any      # (T, L1) int32
    right_child: Any     # (T, L1) int32
    leaf_value: Any      # (T, L) f32
    is_cat: Any          # (T, L1) bool
    cat_bitset: Any      # (T, L1, W) uint32 — RAW-value membership


@dataclass
class ServingBinner:
    """Per-feature serving-bin boundaries derived from the ensemble's own
    thresholds (the model IS the bin mapper at serving time: two raw
    values that no tree distinguishes need no distinct codes).

    Codes per feature f:
      numeric   — ``searchsorted(thresholds[f], v, side='left')`` (count
                  of thresholds < v), so ``code(v) <= bin(t_j) == j`` iff
                  ``v <= t_j`` — the float64 compare happens ONCE here
                  instead of at every node;
      reserved  — ``zero_code`` for |v| <= kZeroThreshold (missing-type
                  Zero routing), ``nan_code`` for NaN;
      categorical — ``trunc(v)`` clipped to the feature's bitset range
                  (negatives/NaN/overflow map to a code outside every
                  left set, reference CategoricalDecision semantics).
    """

    thresholds: List[np.ndarray]      # per feature, sorted float64
    zero_bin: np.ndarray              # (F,) int32 — code of 0.0
    cat_feat: np.ndarray              # (F,) bool
    cat_limit: np.ndarray             # (F,) int64 — clip target (not in
                                      #   any left set)
    zero_code: int
    nan_code: int
    dtype: Any                        # np.uint8 | np.uint16 | np.int32
    ok: bool = True
    why_not: str = ""

    @property
    def packed_ok(self) -> bool:
        """4-bit packed serving codes are exact when every code —
        including the two reserved NaN/zero codes — fits a nibble."""
        return bool(self.ok and self.nan_code <= 15)

    def prebin(self, X: np.ndarray) -> np.ndarray:
        """(N, F) float -> (N, F) serving codes.  Float64 exact."""
        X = np.asarray(X, np.float64)
        N, F = X.shape
        codes = np.zeros((N, F), self.dtype)
        for f in range(min(F, len(self.thresholds))):
            col = X[:, f]
            isnan = np.isnan(col)
            if self.cat_feat[f]:
                lim = int(self.cat_limit[f])
                vi = np.where(isnan, -1.0, np.trunc(np.where(isnan, 0.0,
                                                             col)))
                code = np.where((vi < 0) | (vi > lim), lim, vi)
                codes[:, f] = code.astype(self.dtype)
            else:
                b = np.searchsorted(self.thresholds[f], col, side="left")
                b = b.astype(np.int64)
                b[np.abs(col) <= K_ZERO_THRESHOLD] = self.zero_code
                b[isnan] = self.nan_code
                codes[:, f] = b.astype(self.dtype)
        return codes


def build_serving_binner(trees: List[HostTree],
                         num_features: int) -> ServingBinner:
    """Collect every split threshold / category set in the ensemble into
    per-feature serving bins.  ``ok=False`` (with a reason) when the
    prebinned path cannot be EXACT — callers fall back to the raw walk."""
    th: List[set] = [set() for _ in range(num_features)]
    cat_feat = np.zeros(num_features, bool)
    num_feat = np.zeros(num_features, bool)
    cat_max = np.zeros(num_features, np.int64)
    ok, why = True, ""
    for t in trees:
        for i in range(t.num_leaves - 1):
            f = int(t.split_feature[i])
            if f >= num_features:
                ok, why = False, f"split feature {f} out of range"
                continue
            if bool(t.is_cat[i]):
                cat_feat[f] = True
                s = t.cat_sets[i]
                if s is None:
                    ok, why = False, "raw categorical sets unavailable"
                    continue
                if len(s):
                    cat_max[f] = max(cat_max[f], int(np.max(s)))
            else:
                num_feat[f] = True
                th[f].add(float(t.threshold[i]))
    if (cat_feat & num_feat).any():
        ok, why = False, "feature used both numeric and categorical"
    if (cat_max >= _MAX_CAT_BITSET).any():
        ok, why = False, "category value too large for a serving bitset"
    thresholds = [np.array(sorted(s), np.float64) for s in th]
    # exactness guard: a threshold STRICTLY inside the +-kZeroThreshold
    # band would make the zero-code collapse lossy (|v|<=kzero rows all
    # take the bin of 0.0).  Thresholds at EXACTLY +-kzero are routine —
    # the training binner bounds the zero bin there (io/binning.py) — and
    # stay exact for every input except a raw value of exactly
    # -kZeroThreshold on such a feature (the same collapse the training
    # bin space itself makes); real models never split strictly inside.
    for f, a in enumerate(thresholds):
        if len(a) and (np.abs(a) < K_ZERO_THRESHOLD).any():
            ok, why = False, "threshold within the zero-missing band"
    cat_limit = cat_max + 1
    n_codes = max([len(a) + 1 for a in thresholds] or [1])
    if cat_feat.any():
        n_codes = max(n_codes, int(cat_limit[cat_feat].max()) + 1)
    zero_code, nan_code = n_codes, n_codes + 1
    if nan_code < 256:
        dtype: Any = np.uint8
    elif nan_code < 65536:
        dtype = np.uint16
    else:
        dtype = np.int32
    zero_bin = np.array(
        [np.searchsorted(a, 0.0, side="left") for a in thresholds]
        + [0] * (num_features - len(thresholds)), np.int32)
    return ServingBinner(thresholds=thresholds, zero_bin=zero_bin,
                         cat_feat=cat_feat, cat_limit=cat_limit,
                         zero_code=zero_code, nan_code=nan_code,
                         dtype=dtype, ok=ok, why_not=why)


def build_serving_arrays(trees: List[HostTree], binner: ServingBinner,
                         num_features: int) -> Tuple[ServingArrays, int]:
    """HostTrees (real thresholds filled) -> stacked device tables +
    the ensemble's max depth (the static walk trip count)."""
    import jax.numpy as jnp

    for i, t in enumerate(trees):
        validate_host_tree(t, i)
    depth = max([host_tree_depth(t) for t in trees] or [0])
    L = max([max(t.num_leaves, 1) for t in trees] or [1])
    L1 = max(L - 1, 1)
    W = 1
    if binner.ok and binner.cat_feat.any():
        W = int(binner.cat_limit[binner.cat_feat].max()) // 32 + 1
    T = len(trees)

    def zeros(shape, dt):
        return np.zeros(shape, dt)

    num_leaves = zeros(T, np.int32)
    feat = zeros((T, L1), np.int32)
    thr = zeros((T, L1), np.float32)
    tbin = zeros((T, L1), np.int32)
    zbin = zeros((T, L1), np.int32)
    dl = zeros((T, L1), bool)
    mt = zeros((T, L1), np.int32)
    lc = np.full((T, L1), -1, np.int32)
    rc = np.full((T, L1), -2, np.int32)
    lv = zeros((T, L), np.float32)
    is_cat = zeros((T, L1), bool)
    bitset = zeros((T, L1, W), np.uint32)
    for ti, t in enumerate(trees):
        n = t.num_leaves
        nn = max(n - 1, 0)
        num_leaves[ti] = n
        if nn:
            feat[ti, :nn] = t.split_feature
            thr[ti, :nn] = t.threshold
            dl[ti, :nn] = t.default_left
            mt[ti, :nn] = t.missing_type
            lc[ti, :nn] = t.left_child
            rc[ti, :nn] = t.right_child
            is_cat[ti, :nn] = t.is_cat
            for i in range(nn):
                f = int(t.split_feature[i])
                if binner.ok and f < num_features:
                    zbin[ti, i] = binner.zero_bin[f]
                    if bool(t.is_cat[i]):
                        s = t.cat_sets[i]
                        if s is not None and len(s):
                            s = np.asarray(s, np.int64)
                            np.bitwise_or.at(
                                bitset[ti, i], s // 32,
                                np.uint32(1) << (s % 32).astype(np.uint32))
                    else:
                        j = int(np.searchsorted(binner.thresholds[f],
                                                float(t.threshold[i]),
                                                side="left"))
                        tbin[ti, i] = j
        lv[ti, :n] = t.leaf_value[:n]
    arrays = ServingArrays(
        num_leaves=jnp.asarray(num_leaves),
        split_feature=jnp.asarray(feat),
        threshold=jnp.asarray(thr),
        threshold_bin=jnp.asarray(tbin),
        zero_bin=jnp.asarray(zbin),
        default_left=jnp.asarray(dl),
        missing_type=jnp.asarray(mt),
        left_child=jnp.asarray(lc),
        right_child=jnp.asarray(rc),
        leaf_value=jnp.asarray(lv),
        is_cat=jnp.asarray(is_cat),
        cat_bitset=jnp.asarray(bitset),
    )
    return arrays, depth


# ---------------------------------------------------------------------------
# Depth-stepped serving walks (pure XLA; ops/predict_pallas.py is the
# VMEM-pinned variant, this is the bit-parity pin for it)
# ---------------------------------------------------------------------------


def _cat_go_left(sm: ServingArrays, ti, nd, code, go_left, has_cat: bool):
    import jax.numpy as jnp

    if not has_cat:
        return go_left
    W = sm.cat_bitset.shape[-1]
    bi = jnp.clip(code, 0, W * 32 - 1)
    word = sm.cat_bitset[ti, nd, bi >> 5]
    in_set = ((word >> (bi.astype(jnp.uint32) & 31)) & 1) == 1
    in_set = in_set & (code >= 0) & (code < W * 32)
    return jnp.where(sm.is_cat[ti, nd], in_set, go_left)


def serving_leaf_raw(sm: ServingArrays, X, n_steps: int,
                     has_cat: bool = False):
    """Depth-stepped walk on RAW float features (f32 compares).  With
    ``has_cat`` the categorical decision is ``trunc(v)`` membership in the
    node's raw bitset (reference CategoricalDecision, tree.h:302-320)."""
    import jax.numpy as jnp
    from jax import lax

    N = X.shape[0]
    T = sm.left_child.shape[0]
    ti = jnp.arange(T, dtype=jnp.int32)[None, :]

    def body(_, node):
        nd = jnp.maximum(node, 0)
        f = sm.split_feature[ti, nd]
        v = jnp.take_along_axis(X, f, axis=1)
        t = sm.threshold[ti, nd]
        dl = sm.default_left[ti, nd]
        mtype = sm.missing_type[ti, nd]
        is_nan = jnp.isnan(v)
        v0 = jnp.where(is_nan, 0.0, v)
        is_missing = jnp.where(
            mtype == MISSING_NAN, is_nan,
            jnp.where(mtype == MISSING_ZERO,
                      is_nan | (jnp.abs(v0) <= K_ZERO_THRESHOLD), False))
        go_left = jnp.where(is_missing, dl, v0 <= t)
        if has_cat:
            W = sm.cat_bitset.shape[-1]
            vc = jnp.clip(v0, -1.0, float(W * 32))
            vi = jnp.where(is_nan, -1, vc.astype(jnp.int32))  # C trunc
            go_left = _cat_go_left(sm, ti, nd, vi, go_left, True)
        nxt = jnp.where(go_left, sm.left_child[ti, nd],
                        sm.right_child[ti, nd])
        return jnp.where(node >= 0, nxt, node)

    node0 = jnp.where(sm.num_leaves[None, :] > 1,
                      jnp.zeros((N, T), jnp.int32),
                      jnp.full((N, T), -1, jnp.int32))
    node = lax.fori_loop(0, max(int(n_steps), 1), body, node0)
    return -node - 1


def serving_leaf_binned(sm: ServingArrays, codes, n_steps: int,
                        zero_code: int, nan_code: int,
                        has_cat: bool = False):
    """Depth-stepped walk on prebinned serving codes: every decision is an
    integer compare against the node's serving-bin threshold; NaN /
    zero-missing routing rides the two reserved codes (``b0`` restores the
    reference's NaN-as-0.0 compare via the precomputed zero bin)."""
    import jax.numpy as jnp
    from jax import lax

    N = codes.shape[0]
    T = sm.left_child.shape[0]
    ti = jnp.arange(T, dtype=jnp.int32)[None, :]

    def body(_, node):
        nd = jnp.maximum(node, 0)
        f = sm.split_feature[ti, nd]
        b = jnp.take_along_axis(codes, f, axis=1).astype(jnp.int32)
        is_nan = b == nan_code
        is_zero = b == zero_code
        b0 = jnp.where(is_nan | is_zero, sm.zero_bin[ti, nd], b)
        dl = sm.default_left[ti, nd]
        mtype = sm.missing_type[ti, nd]
        is_missing = jnp.where(
            mtype == MISSING_NAN, is_nan,
            jnp.where(mtype == MISSING_ZERO, is_nan | is_zero, False))
        go_left = jnp.where(is_missing, dl, b0 <= sm.threshold_bin[ti, nd])
        go_left = _cat_go_left(sm, ti, nd, b, go_left, has_cat)
        nxt = jnp.where(go_left, sm.left_child[ti, nd],
                        sm.right_child[ti, nd])
        return jnp.where(node >= 0, nxt, node)

    node0 = jnp.where(sm.num_leaves[None, :] > 1,
                      jnp.zeros((N, T), jnp.int32),
                      jnp.full((N, T), -1, jnp.int32))
    node = lax.fori_loop(0, max(int(n_steps), 1), body, node0)
    return -node - 1


# ---------------------------------------------------------------------------
# The predictor object: compile cache, buckets, chunk streaming, sharding
# ---------------------------------------------------------------------------


_obs_cache = {}


def _obs_cache_counter(event: str,
                       metric_name: str = "predict_cache_events_total"):
    """Process-wide predictor-cache counters in the unified registry
    (``predict_cache_events_total{event=hits|misses|evictions}``, and
    ``predict_shared_cache_events_total`` for the cross-instance
    executable cache) — the per-instance ``cache_info()`` integers stay
    the test surface; these aggregate across predictors for scraping."""
    c = _obs_cache.get((metric_name, event))
    if c is None:
        from ..obs.metrics import default_registry

        metric = default_registry().counter(
            metric_name,
            "Compiled-walk cache hits/misses/evictions",
            label_names=("event",))
        c = _obs_cache[(metric_name, event)] = metric.labels(event=event)
    return c


# ---------------------------------------------------------------------------
# Cross-instance shared executable cache (multi-tenant serving, ISSUE 20)
# ---------------------------------------------------------------------------
# The walk closures are pure in everything per-model — node tables and
# encoded rows arrive as ARGUMENTS — so two predictors whose traced
# program is byte-identical (same tree-shape signature: stacked table
# geometry, binner code geometry, walk statics) can share ONE
# InstrumentedJit and therefore ONE compiled executable per bucket.
# That is the multi-tenant compile-bucket sharing contract: the cache
# key is ``(shape_signature, bucket, kind)`` — TENANT IDENTITY IS NOT
# IN THE KEY.  Opt-in per predictor (``shared_cache=True``; the tenant
# platform enables it) so single-model deployments keep today's
# per-instance behavior bit-identically.  Entries hold only the jitted
# closure + small statics (never the model arrays), LRU-bounded.

_SHARED_CACHE_CAPACITY = 256
_shared_lock = threading.RLock()
_shared_cache: "OrderedDict[tuple, Any]" = OrderedDict()
_shared_stats = {"hits": 0, "misses": 0, "evictions": 0}


def shared_cache_stats() -> Dict[str, int]:
    """Point read of the cross-instance executable cache — bench.py's
    ``tenant_compile_share_frac`` is ``hits / (hits + misses)``."""
    with _shared_lock:
        out = dict(_shared_stats)
        out["entries"] = len(_shared_cache)
        out["capacity"] = _SHARED_CACHE_CAPACITY
    return out


def reset_shared_cache() -> None:
    """Drop every shared executable and zero the counters (tests and
    bench probes only — live predictors keep their adopted entries)."""
    with _shared_lock:
        _shared_cache.clear()
        for k in _shared_stats:
            _shared_stats[k] = 0


class _TraceCell:
    """Trace-time counter the walk closures bump instead of closing over
    the predictor — a shared executable must never keep its builder's
    model arrays alive through the cache."""

    __slots__ = ("n",)

    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1


def _next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


class BatchPredictor:
    """Device serving engine for one frozen ensemble slice.

    Owns the stacked node tables, the serving binner, and a jit cache
    keyed on (row bucket, output kind) so repeated `predict` calls at any
    batch size inside a bucket reuse one compiled executable —
    ``trace_count`` counts actual retraces and is asserted zero-growth by
    the cache tests.  The cache is LRU-bounded at ``cache_entries``
    executables (``cache_info()`` exposes hits/misses/evictions) so a
    long-running server seeing many batch shapes cannot accumulate
    compiled programs without limit.  `Booster.predict` holds one
    BatchPredictor per (start_iteration, tree count, model_version) — any
    ensemble mutation bumps ``model_version`` and drops the predictor
    wholesale."""

    def __init__(self, trees: List[HostTree], K: int, num_features: int, *,
                 method: str = "depthwise", prebin: str = "auto",
                 code_layout: str = "auto", num_shards: int = 0,
                 bucket_min: int = 256, chunk_rows: int = 1 << 17,
                 interpret: Optional[bool] = None, cache_entries: int = 64,
                 shared_cache: bool = False):
        import jax

        if not trees:
            raise ValueError("BatchPredictor needs at least one tree")
        if method not in ("depthwise", "pallas", "scan", "fused"):
            raise ValueError(f"predict_method={method!r}: expected "
                             "depthwise | pallas | scan | fused")
        if code_layout not in ("auto", "u8", "packed4"):
            raise ValueError(f"predict_code_layout={code_layout!r}: "
                             "expected auto | u8 | packed4")
        self.K = max(int(K), 1)
        self.T = len(trees)
        self.F = int(num_features)
        self.method = method
        self.num_shards = int(num_shards)
        self.bucket_min = max(int(bucket_min), 8)
        self.chunk_rows = max(int(chunk_rows), self.bucket_min)
        self.binner = build_serving_binner(trees, num_features)
        self.arrays, self.depth = build_serving_arrays(
            trees, self.binner, num_features)
        self.has_cat = bool(np.asarray(self.arrays.is_cat).any())
        if self.has_cat and not self.binner.ok:
            raise ValueError(
                "device serving of this categorical model is not possible: "
                + self.binner.why_not)
        if method == "scan" and self.has_cat:
            raise ValueError("predict_method=scan does not support "
                             "categorical splits")
        if method == "scan" and self.K != 1:
            raise ValueError("predict_method=scan supports K=1 ensembles")
        if prebin not in ("auto", "on", "off"):
            raise ValueError(f"predict_prebin={prebin!r}")
        self.prebin = (self.binner.ok and method != "scan") \
            if prebin == "auto" else (prebin == "on")
        if self.prebin and not self.binner.ok:
            log_warning("predict_prebin=on but the prebinned path cannot "
                        f"be exact ({self.binner.why_not}); using the raw "
                        "walk")
            self.prebin = False
        # -- 4-bit packed serving codes (the select_bin_layout engage/
        # refuse contract): "auto" engages exactly when eligible AND the
        # fused kernel consumes nibbles directly; an explicit "packed4"
        # engages on any prebinned walk (the staged path unpacks ON
        # DEVICE, keeping the halved H2D) or refuses with one reason
        self.code_layout = code_layout
        packed_able = bool(self.prebin and self.binner.packed_ok
                           and method != "scan")
        if code_layout == "packed4":
            if packed_able:
                self.packed = True
                _log_once("packed4:on",
                          "predict_code_layout=packed4: serving codes "
                          "packed two per byte")
            else:
                reason = (f"{self.binner.nan_code + 1} serving codes "
                          "exceed the 16 nibble values"
                          if self.prebin and self.binner.ok
                          else "prebinned serving codes not in play")
                _log_once(f"packed4:refuse:{reason}",
                          f"predict_code_layout=packed4: {reason}; "
                          "storing unpacked codes", warn=True)
                self.packed = False
        else:
            self.packed = bool(code_layout == "auto" and method == "fused"
                               and packed_able)
        # float64 leaf table for exact score reconstruction (the native
        # predictor / HostTree accumulate f64 in tree order)
        self._leaf_value64 = np.zeros((self.T, self.arrays.leaf_value.shape[1]),
                                      np.float64)
        for i, t in enumerate(trees):
            self._leaf_value64[i, : t.num_leaves] = t.leaf_value[: t.num_leaves]
        if interpret is None:
            interpret = jax.default_backend() == "cpu"
        self.interpret = bool(interpret)
        self._mesh = None
        if self.num_shards > 1:
            from ..parallel.cluster import make_mesh

            self._mesh = make_mesh(self.num_shards, "rows")
        # LRU-bounded jit cache over (bucket, kind) keys: a long-running
        # server seeing many batch shapes would otherwise accumulate
        # compiled executables without limit (each bucket x output kind is
        # its own XLA program).  Eviction drops the least-recently-used
        # executable; re-touching that bucket retraces (counted).
        self._cache: "OrderedDict[Tuple[int, str], Any]" = OrderedDict()
        self.cache_capacity = max(int(cache_entries), 2)
        # cross-instance executable sharing (multi-tenant serving): the
        # per-instance LRU stays the front line; on a miss the shared
        # cache is consulted under (shape signature, bucket, kind).
        # Row-sharded predictors are excluded (their walks close over a
        # per-instance mesh binding).
        self.shared_cache = bool(shared_cache) and self.num_shards <= 1
        self._shape_sig: Optional[tuple] = None
        self._tc = _TraceCell()
        self.call_count = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0
        self._scan_stacked = None
        self._pallas_broken = False
        # -- serving-megakernel plan (static, recorded in BENCH): tiles
        # trees into VMEM-sized groups; refusal = staged walk + one
        # honest reason line
        self.fused_plan = None
        self._fused_tables = None
        self._fused_broken = False
        if method == "fused":
            from ..ops.predict_pallas import plan_predict_tiles

            self.fused_plan = plan_predict_tiles(
                T=self.T, L1=self.arrays.split_feature.shape[1],
                L=self.arrays.leaf_value.shape[1], F=self.F, K=self.K,
                depth=self.depth, has_cat=self.has_cat,
                prebin=self.prebin, packed=self.packed)
            if self.fused_plan["eligible"]:
                from .tree import pad_tree_axis

                self._fused_tables = pad_tree_axis(
                    self.arrays, self.fused_plan["t_pad"])
            else:
                _log_once("fused:refuse:" + self.fused_plan["reason"],
                          f"predict_method=fused: "
                          f"{self.fused_plan['reason']}; serving the "
                          "staged depth-stepped walk", warn=True)

    # -- cache ----------------------------------------------------------
    @property
    def trace_count(self) -> int:
        """Traces this instance's walk builds triggered (the zero-retrace
        contract's per-instance surface).  A predictor that ADOPTS a
        shared executable never traces — its count stays 0, which is
        exactly the multi-tenant compile-sharing assertion."""
        return self._tc.n

    def bucket_for(self, n: int) -> int:
        b = _next_pow2(max(n, self.bucket_min))
        b = min(b, _next_pow2(self.chunk_rows))
        if self.num_shards > 1 and b % self.num_shards:
            b = self.num_shards * (-(-b // self.num_shards))
        return b

    def shape_signature(self) -> tuple:
        """Every static the traced walk program depends on — two
        predictors with equal signatures lower to byte-identical XLA
        programs per (bucket, kind), which is what makes the shared
        executable cache sound.  Covers the walk statics (method /
        prebin / packed / depth / categorical handling), the binner code
        geometry (zero/nan codes are baked into the trace as constants),
        the stacked table geometry (shape + dtype of every SoA field —
        they are jit ARGUMENTS, so shape/dtype is what the trace keys
        on), and the megakernel tiling plan."""
        if self._shape_sig is None:
            geom = tuple((tuple(v.shape), str(v.dtype))
                         for v in self.arrays)
            fused = None
            if self.fused_plan is not None and self.fused_plan["eligible"]:
                fused = (int(self.fused_plan["tree_tile"]),
                         int(self.fused_plan["t_pad"]))
            self._shape_sig = (
                self.method, self.prebin, self.packed, self.interpret,
                self.depth, self.has_cat, self.K, self.T, self.F,
                self.binner.zero_code, self.binner.nan_code,
                str(np.dtype(self.binner.dtype)), geom, fused)
        return self._shape_sig

    def _shared_jit(self, bucket: int, kind: str, build):
        """Fetch-or-build one instrumented jitted walk through the
        cross-instance shared cache (``shared_cache=True`` only) —
        keyed ``(shape_signature, bucket, kind)``, never on model or
        tenant identity.  ``build()`` must return a closure that is
        pure in everything per-model (tables arrive as arguments)."""
        if not self.shared_cache:
            return build()
        skey = (self.shape_signature(), bucket, kind)
        with _shared_lock:
            ent = _shared_cache.get(skey)
            if ent is not None:
                _shared_cache.move_to_end(skey)
                _shared_stats["hits"] += 1
        if ent is not None:
            _obs_cache_counter(
                "hits", "predict_shared_cache_events_total").inc()
            return ent
        _obs_cache_counter(
            "misses", "predict_shared_cache_events_total").inc()
        jfn = build()
        with _shared_lock:
            _shared_stats["misses"] += 1
            _shared_cache[skey] = jfn
            _shared_cache.move_to_end(skey)
            while len(_shared_cache) > _SHARED_CACHE_CAPACITY:
                _shared_cache.popitem(last=False)
                _shared_stats["evictions"] += 1
                _obs_cache_counter(
                    "evictions",
                    "predict_shared_cache_events_total").inc()
        return jfn

    def _cache_get(self, key):
        fn = self._cache.get(key)
        if fn is not None:
            self._cache.move_to_end(key)
            self.cache_hits += 1
            _obs_cache_counter("hits").inc()
        else:
            self.cache_misses += 1
            _obs_cache_counter("misses").inc()
        return fn

    def _cache_put(self, key, fn):
        self._cache[key] = fn
        self._cache.move_to_end(key)
        while len(self._cache) > self.cache_capacity:
            self._cache.popitem(last=False)
            self.cache_evictions += 1
            _obs_cache_counter("evictions").inc()
        return fn

    def cache_stats(self) -> Dict[str, int]:
        return {"traces": self.trace_count, "calls": self.call_count,
                "entries": len(self._cache)}

    def cache_info(self) -> Dict[str, int]:
        """functools.lru_cache-style accessor for the compiled-walk cache
        (serve metrics and the cache tests read this)."""
        return {"entries": len(self._cache),
                "capacity": self.cache_capacity,
                "hits": self.cache_hits, "misses": self.cache_misses,
                "evictions": self.cache_evictions,
                "traces": self.trace_count, "calls": self.call_count}

    def _leaf_fn(self, bucket: int):
        """Compiled (bucket, F) -> (bucket, T) leaf-index walk."""
        key = (bucket, "leaf")
        fn = self._cache_get(key)
        if fn is not None:
            return fn
        import jax

        method, prebin = self.method, self.prebin
        depth, has_cat = self.depth, self.has_cat
        zc, nc = self.binner.zero_code, self.binner.nan_code
        packed, F = self.packed, self.F
        interpret, tc = self.interpret, self._tc

        def build():
            def walk(arrays, xb):
                tc.bump()                # trace-time side effect only
                if packed:
                    xb = unpack_serving_codes(xb, F)
                if method == "pallas" and prebin and not has_cat:
                    from ..ops.predict_pallas import serving_leaf_pallas

                    return serving_leaf_pallas(
                        arrays, xb, n_steps=depth, zero_code=zc,
                        nan_code=nc, interpret=interpret)
                if prebin:
                    return serving_leaf_binned(arrays, xb, depth, zc, nc,
                                               has_cat)
                return serving_leaf_raw(arrays, xb, depth, has_cat)

            fn = walk
            if self._mesh is not None:
                from ..parallel.trainer import shard_rows

                fn = shard_rows(walk, self._mesh, "rows", n_replicated=1)
            # labeled compile telemetry (obs/xla.py): every (bucket,
            # kind) compile is an observed event, and the per-label
            # retrace counters are the serving zero-retrace contract's
            # instrument
            return obs_xla.instrument_jit(fn, "predict.leaf")

        jfn = self._shared_jit(bucket, "leaf", build)
        if self.method == "pallas":
            # the lowering-failure guard is PER INSTANCE (it reads this
            # predictor's broken flag and fallback tables); only the
            # inner jitted walk is shared
            jfn = self._pallas_guard(jfn, bucket)
        return self._cache_put(key, jfn)

    def _pallas_guard(self, jfn, bucket):
        """First-call fallback: if the Pallas kernel fails to lower on
        this backend, swap in the pure-XLA walk (the bit-parity pin) for
        every subsequent call.  The warning is deduplicated process-wide
        (``_log_once``): a chunked streaming predict previously re-logged
        it per chunk."""

        def guarded(arrays, xb):
            if self._pallas_broken:
                return self._xla_fallback(bucket)(arrays, xb)
            try:
                return jfn(arrays, xb)
            except Exception as e:  # noqa: BLE001 — Mosaic lowering gap
                _log_once(f"pallas:lower:{type(e).__name__}",
                          f"predict_method=pallas failed to lower "
                          f"({type(e).__name__}); falling back to the "
                          "XLA depth-stepped walk", warn=True)
                self._pallas_broken = True
                return self._xla_fallback(bucket)(arrays, xb)

        return guarded

    def _xla_fallback(self, bucket):
        key = (bucket, "leaf_xla")
        cached = self._cache_get(key)
        if cached is not None:
            return cached
        import jax

        depth, has_cat = self.depth, self.has_cat
        zc, nc = self.binner.zero_code, self.binner.nan_code
        prebin, packed, F = self.prebin, self.packed, self.F
        tc = self._tc

        def build():
            def walk(arrays, xb):
                tc.bump()
                if packed:
                    xb = unpack_serving_codes(xb, F)
                if prebin:
                    return serving_leaf_binned(arrays, xb, depth, zc, nc,
                                               has_cat)
                return serving_leaf_raw(arrays, xb, depth, has_cat)

            fn = walk
            if self._mesh is not None:
                from ..parallel.trainer import shard_rows

                fn = shard_rows(walk, self._mesh, "rows", n_replicated=1)
            return obs_xla.instrument_jit(fn, "predict.leaf")

        return self._cache_put(key, self._shared_jit(
            bucket, "leaf_xla", build))

    # -- serving megakernel (predict_method=fused) -----------------------
    def _fused_engaged(self) -> bool:
        return bool(self.method == "fused" and self.fused_plan is not None
                    and self.fused_plan["eligible"]
                    and not self._fused_broken)

    def _fused_walk(self, mode: str = "scores", transform=None):
        """The raw (unjitted) megakernel call for one bucket — exposed
        separately so bench.py can ``jax.jit(...).lower()`` it for the
        single-read ``cost_analysis`` contract."""
        from ..ops.predict_pallas import serving_fused_pallas

        depth, K, T = self.depth, self.K, self.T
        zc, nc = self.binner.zero_code, self.binner.nan_code
        packed, interpret = self.packed, self.interpret
        tree_tile = self.fused_plan["tree_tile"]
        tc = self._tc

        def walk(tables, xb):
            tc.bump()
            out = serving_fused_pallas(
                tables, xb, n_steps=depth, zero_code=zc, nan_code=nc,
                K=K, tree_tile=tree_tile, mode=mode, packed=packed,
                transform=transform, interpret=interpret)
            if mode == "leaf":
                out = out[:, :T]      # slice the tree-tile pad away
            return out

        return walk

    def _fused_fn(self, bucket: int, mode: str = "scores", transform=None):
        """Compiled megakernel per (bucket, output kind): leaves for the
        node-exact / f64 lane, (N, K) scores — optionally with the
        in-launch sigmoid/softmax epilogue — for the fast lane."""
        kind = ("fused_leaf" if mode == "leaf"
                else f"fused:{transform or 'raw'}")
        key = (bucket, kind)
        cached = self._cache_get(key)
        if cached is not None:
            return cached

        def build():
            fn = self._fused_walk(mode=mode, transform=transform)
            if self._mesh is not None:
                from ..parallel.trainer import shard_rows

                fn = shard_rows(fn, self._mesh, "rows", n_replicated=1)
            return obs_xla.instrument_jit(fn, "predict.fused")

        jfn = self._shared_jit(bucket, kind, build)
        return self._cache_put(
            key, self._fused_guard(jfn, bucket, mode, transform))

    def _fused_guard(self, jfn, bucket, mode, transform):
        """Mosaic probe for the megakernel: a lowering failure swaps in
        the staged walk (+ the out-of-kernel epilogue) for every
        subsequent call, warned ONCE process-wide — the chunked stream
        must not re-log per chunk."""

        def staged(xb):
            leaf = self._xla_fallback(bucket)(self.arrays, xb)
            if mode == "leaf":
                return leaf
            s = self._scores_fn(bucket)(self.arrays.leaf_value, leaf)
            return _transform_scores(s, transform)

        def guarded(tables, xb):
            if self._fused_broken:
                return staged(xb)
            try:
                return jfn(tables, xb)
            except Exception as e:  # noqa: BLE001 — Mosaic lowering gap
                _log_once(f"fused:lower:{type(e).__name__}",
                          f"predict_method=fused failed to lower "
                          f"({type(e).__name__}); falling back to the "
                          "staged depth-stepped walk", warn=True)
                self._fused_broken = True
                return staged(xb)

        return guarded

    def _scan_fn(self, bucket: int):
        """The parity-pin scan walk (models/tree.ensemble_predict_raw) as
        a predict_method — per-tree while-loop walks, summed f32."""
        key = (bucket, "scan")
        cached = self._cache_get(key)
        if cached is not None:
            return cached
        import jax

        from .tree import ensemble_predict_raw

        tc = self._tc

        def build():
            def fwd(stacked, xb):
                tc.bump()
                return ensemble_predict_raw(stacked, xb)

            fn = fwd
            if self._mesh is not None:
                from ..parallel.trainer import shard_rows

                fn = shard_rows(fwd, self._mesh, "rows", n_replicated=1)
            return obs_xla.instrument_jit(fn, "predict.scan")

        return self._cache_put(key, self._shared_jit(
            bucket, "scan", build))

    # -- host <-> device ------------------------------------------------
    def encode(self, X: np.ndarray) -> np.ndarray:
        """Host-side input encoding for the device walk: prebinned codes
        (uint8/uint16, or 4-bit packed bytes when the nibble layout is
        engaged) or f32 raw features."""
        if self.prebin:
            codes = self.binner.prebin(X)
            if self.packed:
                return pack_serving_codes(codes)
            return codes
        return np.asarray(X, np.float32)

    def _pad(self, enc: np.ndarray, bucket: int) -> np.ndarray:
        n = enc.shape[0]
        if n == bucket:
            return enc
        pad = np.zeros((bucket - n, enc.shape[1]), enc.dtype)
        return np.concatenate([enc, pad], axis=0)

    # -- public API ------------------------------------------------------
    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """(N, T) int32 leaf index per (row, tree) — node-exact vs the
        host walks (prebinned path; the raw walk compares f32)."""
        import jax

        X = np.asarray(X)
        n = X.shape[0]
        outs = []
        for lo in range(0, n, self.chunk_rows):
            chunk = X[lo: lo + self.chunk_rows]
            bucket = self.bucket_for(chunk.shape[0])
            enc = self._pad(self.encode(chunk), bucket)
            # chaos seam: a transient host->device transfer failure lands
            # here, before the walk dispatch (utils/faults.py) — the
            # serving retry loop must absorb it
            faults.fire("h2d", site="predict_leaf")
            self.call_count += 1
            if self._fused_engaged():
                leaf = self._fused_fn(bucket, mode="leaf")(
                    self._fused_tables, jax.numpy.asarray(enc))
            else:
                leaf = self._leaf_fn(bucket)(self.arrays,
                                             jax.numpy.asarray(enc))
            outs.append(jax.device_get(leaf)[: chunk.shape[0]])
        return np.concatenate(outs, axis=0)

    def predict_raw(self, X: np.ndarray, f64_exact: bool = False,
                    chunk_rows: Optional[int] = None) -> np.ndarray:
        """(N, K) raw scores.

        Default: leaf values summed on-device in f32 (fast serving path).
        ``f64_exact``: the device walk produces leaf indices and the
        scores are reconstructed host-side in float64 IN TREE ORDER —
        bit-identical to the native C++ predictor / HostTree path.
        Chunks stream with the next chunk's H2D enqueued before the
        current chunk's result is consumed (double-buffered via JAX async
        dispatch)."""
        import jax
        import jax.numpy as jnp

        X = np.asarray(X)
        n = X.shape[0]
        chunk_rows = chunk_rows or self.chunk_rows
        if f64_exact:
            leaf = self.predict_leaf(X)
            out = np.zeros((n, self.K), np.float64)
            for t in range(self.T):   # tree order = the reference's f64
                out[:, t % self.K] += self._leaf_value64[t][leaf[:, t]]
            return out

        if self.method == "scan":
            return self._predict_raw_scan(X, chunk_rows)

        chunks = [X[lo: lo + chunk_rows] for lo in range(0, n, chunk_rows)]
        pending = []
        nxt_dev = None
        for i, chunk in enumerate(chunks):
            faults.fire("h2d", site="predict_raw")
            bucket = self.bucket_for(chunk.shape[0])
            if nxt_dev is not None and nxt_dev[1] == bucket:
                enc_dev = nxt_dev[0]
            else:
                enc_dev = jnp.asarray(self._pad(self.encode(chunk), bucket))
            # enqueue the NEXT chunk's H2D before consuming this walk
            if i + 1 < len(chunks):
                nb = self.bucket_for(chunks[i + 1].shape[0])
                nxt_dev = (jax.device_put(
                    self._pad(self.encode(chunks[i + 1]), nb)), nb)
            self.call_count += 1
            if self._fused_engaged():
                # one launch: walk + accumulate, no (N, T) intermediate
                scores = self._fused_fn(bucket)(self._fused_tables,
                                                enc_dev)
            else:
                leaf = self._leaf_fn(bucket)(self.arrays, enc_dev)
                scores = self._scores_fn(bucket)(self.arrays.leaf_value,
                                                 leaf)
            pending.append((scores, chunk.shape[0]))
        return np.concatenate(
            [np.asarray(jax.device_get(s))[:m] for s, m in pending], axis=0)

    def predict_scores(self, X: np.ndarray, transform=None,
                       chunk_rows: Optional[int] = None) -> np.ndarray:
        """(N, K) scores with the optional objective epilogue
        (``transform``: None | 'sigmoid' | 'softmax').  When the
        megakernel is engaged the transform runs IN-KERNEL on the VMEM
        accumulator — the whole request is one launch; otherwise it is
        applied after the staged walk's score sum (same f32 math, one
        extra elementwise pass)."""
        import jax
        import jax.numpy as jnp

        if transform not in (None, "sigmoid", "softmax"):
            raise ValueError(f"transform={transform!r}: expected None | "
                             "sigmoid | softmax")
        X = np.asarray(X)
        if not self._fused_engaged():
            raw = jnp.asarray(self.predict_raw(X, chunk_rows=chunk_rows))
            return np.asarray(jax.device_get(
                _transform_scores(raw, transform)))
        n = X.shape[0]
        chunk_rows = chunk_rows or self.chunk_rows
        pending = []
        for lo in range(0, n, chunk_rows):
            chunk = X[lo: lo + chunk_rows]
            bucket = self.bucket_for(chunk.shape[0])
            enc_dev = jnp.asarray(self._pad(self.encode(chunk), bucket))
            self.call_count += 1
            s = self._fused_fn(bucket, transform=transform)(
                self._fused_tables, enc_dev)
            pending.append((s, chunk.shape[0]))
        return np.concatenate(
            [np.asarray(jax.device_get(s))[:m] for s, m in pending], axis=0)

    def _scores_fn(self, bucket: int):
        key = (bucket, "scores")
        cached = self._cache_get(key)
        if cached is not None:
            return cached
        import jax

        from .tree import leaves_to_scores

        K, tc = self.K, self._tc

        def build():
            def fn(leaf_value, leaf):
                tc.bump()
                return leaves_to_scores(leaf_value, leaf, K)

            return obs_xla.instrument_jit(fn, "predict.scores")

        return self._cache_put(key, self._shared_jit(
            bucket, "scores", build))

    def _predict_raw_scan(self, X, chunk_rows):
        import jax
        import jax.numpy as jnp

        if self.K != 1:
            raise ValueError("predict_method=scan supports K=1 ensembles")
        if self._scan_stacked is None:
            # a training-style stacked TreeArrays view over the serving
            # tables (the scan walk reads the same SoA fields)
            self._scan_stacked = self._as_tree_arrays()
        n = X.shape[0]
        outs = []
        for lo in range(0, n, chunk_rows):
            chunk = np.asarray(X[lo: lo + chunk_rows], np.float32)
            bucket = self.bucket_for(chunk.shape[0])
            xb = jnp.asarray(self._pad(chunk, bucket))
            self.call_count += 1
            out = self._scan_fn(bucket)(self._scan_stacked, xb)
            outs.append(np.asarray(jax.device_get(out))[: chunk.shape[0]])
        return np.concatenate(outs, axis=0)[:, None]

    def _as_tree_arrays(self):
        """Serving tables -> the TreeArrays layout the scan pin expects."""
        import jax.numpy as jnp

        from .tree import TreeArrays

        a = self.arrays
        T, L1 = a.split_feature.shape
        L = a.leaf_value.shape[1]
        zf = jnp.zeros((T, L1), jnp.float32)
        zl = jnp.zeros((T, L), jnp.float32)
        return TreeArrays(
            num_leaves=a.num_leaves, split_feature=a.split_feature,
            threshold_bin=a.threshold_bin, threshold=a.threshold,
            default_left=a.default_left, missing_type=a.missing_type,
            left_child=a.left_child, right_child=a.right_child,
            split_gain=zf, internal_value=zf, internal_weight=zf,
            internal_count=zf, leaf_value=a.leaf_value, leaf_weight=zl,
            leaf_count=zl,
            leaf_parent=jnp.full((T, L), -1, jnp.int32),
            is_cat=a.is_cat, cat_bitset=a.cat_bitset,
        )

    def h2d_bytes(self, n_rows: int) -> int:
        """Host->device payload of one batch (the prebinned path's 4-8x
        shrink is the point; packed nibble codes halve it again —
        recorded by bench.py / dryrun_multichip)."""
        if self.prebin and self.packed:
            return int(n_rows) * (-(-self.F // 2))
        itemsize = (np.dtype(self.binner.dtype).itemsize if self.prebin
                    else 4)
        return int(n_rows) * self.F * itemsize
